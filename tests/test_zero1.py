"""ZeRO-1 sharded weight update (parallel/collectives.py, arXiv
2004.13336): bit-level parity with the replicated update on the 8-CPU
mesh, ~num_workers x less optimizer-state memory per device (asserted
from addressable shards), and checkpoint/resume of the scattered state
through both backends — including the Supervisor's bit-for-bit resume
harness from the resilience subsystem.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel import collectives as cl
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.resilience import FaultPlan, Supervisor
from jax.sharding import NamedSharding, PartitionSpec as P


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)

# "Within float tolerance <= 1e-6 where reduction order legitimately
# differs" (the collective's accumulation order vs the fused
# all-reduce); rtol guards the well-scaled elements on top.
TOL = dict(rtol=2e-5, atol=1e-6)


def tokens(rng, n=64, s=16):
    return rng.integers(0, 64, (n, s + 1)).astype(np.int32)


def tree_close(a, b, **kw):
    kw = kw or TOL
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


# ------------------------------------------------------------- layout


def test_layout_pack_unpack_roundtrip(rng):
    tree = {"w": jnp.asarray(rng.normal(size=(33, 7)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(5,)), jnp.float32),
            "h": jnp.asarray(rng.normal(size=(64, 16)), jnp.bfloat16),
            "s": jnp.asarray(rng.normal(size=()), jnp.float32)}
    lay = cl.Zero1Layout.for_tree(tree, 8, bucket_mb=0.001)
    buckets = lay.pack(tree)
    # Buckets are dtype-homogeneous and row-count n.
    assert all(b.shape[0] == 8 for b in buckets)
    assert {b.dtype for b in buckets} == {np.dtype(jnp.float32),
                                          np.dtype(jnp.bfloat16)}
    # Every padded leaf is a multiple of n by construction.
    for s in lay.slots:
        assert (s.cols * 8) % 8 == 0 and s.cols * 8 >= s.size
    out = lay.unpack(buckets)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(tree[k]))
    # shard_views/unview roundtrip too (the EMA-shadow read path).
    views = lay.shard_views(tree)
    for k in tree:
        assert views[k].shape[0] == 8
    back = lay.unview(views)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(tree[k]))


def test_layout_bucket_budget_splits(rng):
    tree = [jnp.ones((1024,), jnp.float32) for _ in range(4)]
    # 1 KB budget = 256 f32 elements: every 1024-element leaf gets its
    # own bucket; a huge budget fuses all four.
    small = cl.Zero1Layout.for_tree(tree, 8, bucket_mb=1 / 1024)
    assert len(small.bucket_cols) == 4
    big = cl.Zero1Layout.for_tree(tree, 8, bucket_mb=64.0)
    assert len(big.bucket_cols) == 1
    assert big.bucket_cols[0] == 4 * 128  # four leaves x (1024/8) cols


def test_views_from_buckets_are_column_slices(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    tree = {"a": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(24,)), jnp.float32)}
    lay = cl.Zero1Layout.for_tree(tree, 8)
    buckets = [jax.device_put(b, NamedSharding(mesh, P("data", None)))
               for b in lay.pack(tree)]
    views = lay.views_from_buckets(buckets)
    # Slicing a scattered bucket along columns keeps the row sharding:
    # no resharding between the reduce-scatter and the update.
    for v in jax.tree.leaves(views):
        assert v.sharding.spec == P("data", None)
        assert v.addressable_shards[0].data.shape[0] == 1


# --------------------------------------------------------- primitives


def test_reduce_scatter_primitive(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    out = cl.reduce_scatter(xs, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-6)
    assert out.sharding.spec == P("data")
    assert out.addressable_shards[0].data.size == 2  # 16 / 8
    # Contract: [n, C] with C divisible by n, clearly rejected otherwise.
    with pytest.raises(ValueError, match="divisible"):
        cl.reduce_scatter(jnp.ones((8, 10)), mesh)
    with pytest.raises(ValueError, match="axis"):
        cl.reduce_scatter(jnp.ones((4, 16)), mesh)


def test_all_gather_primitive(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    out = cl.all_gather(xs, mesh)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    # Replicated: every device holds the full value.
    assert out.addressable_shards[0].data.shape == (8, 16)


def test_zero1_optimizer_matches_plain(devices, rng):
    """The wrapper is math-identical to the wrapped transform — chained
    global-norm clip included (its norm becomes a scalar psum over the
    shard views)."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    tree = {"w": jnp.asarray(rng.normal(size=(33, 7)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(5,)), jnp.float32)}
    grads = jax.tree.map(lambda v: v * 0.1, tree)
    inner = optax.chain(optax.clip_by_global_norm(0.1), optax.adamw(1e-2))
    z = cl.zero1_optimizer(inner, mesh, bucket_mb=0.001)

    u0, s0 = jax.jit(inner.update)(grads, inner.init(tree), tree)
    u1, s1 = jax.jit(z.update)(grads, z.init(tree), tree)
    tree_close(u1, u0, rtol=1e-6, atol=1e-7)
    # Moments live as [n, cols] shard views.
    mu = s1[1][0].mu
    assert all(v.shape[0] == 8 for v in jax.tree.leaves(mu))


# ----------------------------------------------------------- trainers


def _adag(zero1, blobs, **kw):
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    from helpers import make_mlp

    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", learning_rate=0.05,
                batch_size=8, num_epoch=2, communication_window=4,
                zero1=zero1, **kw)
    state = t._fit(ds)
    return t, state


def test_adag_zero1_matches_replicated(devices, blobs):
    base, s0 = _adag(False, blobs)
    z, s1 = _adag(True, blobs)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(s1.tv, s0.tv)


def test_adag_zero1_shards_opt_memory(devices, blobs):
    """Acceptance: per-device optimizer-state bytes drop ~num_workers x,
    asserted from the sharded state's addressable shards."""
    base, s0 = _adag(False, blobs)
    z, s1 = _adag(True, blobs)

    def per_device(state):
        return sum(l.addressable_shards[0].data.nbytes
                   for l in jax.tree.leaves(state.opt_state)
                   if hasattr(l, "addressable_shards"))

    rep_bytes, z_bytes = per_device(s0), per_device(s1)
    # Padding to multiples of 8 costs a little; the ratio must still
    # land near num_workers (=8).
    assert rep_bytes / z_bytes > 6.0, (rep_bytes, z_bytes)
    for l in jax.tree.leaves(s1.opt_state):
        if hasattr(l, "addressable_shards") and l.ndim == 2:
            assert l.sharding.spec == P("data", None)
            assert l.addressable_shards[0].data.size == l.size // 8


def _lm(zero1, mesh, rng, **kw):
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=2,
                     mesh=mesh, zero1=zero1, **kw)
    params = t.train(tokens(rng))
    return t, params


def test_lm_zero1_matches_dp(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base, p0 = _lm(False, mesh, np.random.default_rng(0))
    z, p1 = _lm(True, mesh, np.random.default_rng(0))
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)
    assert z.step_timer.phase_s("step") > 0  # phases observable


def test_lm_zero1_shards_opt_memory(devices):
    """The LM flagship's moments scatter 8x: built exactly the way
    train() builds them (eval_shape -> jit init under the zero1
    sharding rule)."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, mesh=mesh,
                     zero1=True)
    params = t.init_params()
    opt_shapes = jax.eval_shape(t.optimizer.init, params)
    psh, osh = t._state_shardings(params, opt_shapes)
    opt_state = jax.jit(t.optimizer.init, out_shardings=osh)(params)

    n_param_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
    per_dev = sum(l.addressable_shards[0].data.nbytes
                  for l in jax.tree.leaves(opt_state)
                  if hasattr(l, "addressable_shards"))
    # adamw: mu + nu ~= 2x params replicated; sharded it must be ~2x/8.
    assert per_dev < 2 * n_param_bytes / 6.0, (per_dev, n_param_bytes)


def test_lm_zero1_clip_ema_matches_dp(devices):
    """clip_by_global_norm + the EMA shadow both ride the shard views;
    ema_params comes back in parameter layout."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    kw = dict(grad_clip_norm=1.0, ema_decay=0.9)
    base, p0 = _lm(False, mesh, np.random.default_rng(0), **kw)
    z, p1 = _lm(True, mesh, np.random.default_rng(0), **kw)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)
    tree_close(z.ema_params, base.ema_params)
    for a, b in zip(jax.tree.leaves(base.ema_params),
                    jax.tree.leaves(z.ema_params)):
        assert a.shape == b.shape


def test_lm_zero1_grad_accum_matches_dp(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base, p0 = _lm(False, mesh, np.random.default_rng(0), grad_accum=2)
    z, p1 = _lm(True, mesh, np.random.default_rng(0), grad_accum=2)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)


# --------------------------------------------------------- checkpoints


@pytest.mark.parametrize("backend", ["pickle", "orbax"])
def test_lm_zero1_checkpoint_resume(devices, tmp_path, backend):
    """Scattered optimizer state round-trips: gather-on-save for the
    pickle backend, shard-native for orbax; the resumed run continues
    the uninterrupted run's loss trajectory."""
    if backend == "orbax":
        pytest.importorskip("orbax.checkpoint")
    d = str(tmp_path / "ck")
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    data = tokens(np.random.default_rng(0))
    kw = dict(learning_rate=1e-2, batch_size=16, mesh=mesh, zero1=True,
              checkpoint_backend=backend)
    full = dk.LMTrainer(CFG, num_epoch=2, **{k: v for k, v in kw.items()
                                             if k != "checkpoint_backend"})
    full.train(data)

    first = dk.LMTrainer(CFG, num_epoch=1, checkpoint_dir=d,
                         checkpoint_every=1, **kw)
    first.train(data)
    resumed = dk.LMTrainer(CFG, num_epoch=2, checkpoint_dir=d,
                           checkpoint_every=1, resume=True, **kw)
    p2 = resumed.train(data)
    np.testing.assert_allclose(
        resumed.history, full.history[len(first.history):], rtol=1e-5)
    jax.block_until_ready(jax.tree.leaves(p2)[0])


@pytest.mark.chaos
def test_adag_zero1_supervisor_bit_for_bit(devices, tmp_path, blobs):
    """PR-1's resilience acceptance harness over the ZeRO-1 path: an
    injected kill mid-run + Supervisor auto-resume reproduces the
    uninterrupted run's loss trajectory bit-for-bit — the scattered
    optimizer state restores exactly."""
    from helpers import make_mlp

    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    kw = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="adam", learning_rate=0.05,
              batch_size=8, num_epoch=2, communication_window=4,
              zero1=True)

    straight = dk.ADAG(make_mlp(), **kw)
    ref = straight.train(ds)

    t = dk.ADAG(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                checkpoint_every=1, checkpoint_backend="pickle", **kw)
    sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    with FaultPlan().fail("train.round", at=3):
        out = sup.run(ds)

    assert t.history == straight.history[2:]  # bit-for-bit
    for wr, wo in zip(ref.get_weights(), out.get_weights()):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    assert [a.outcome for a in sup.attempts] == ["fault", "ok"]


# ------------------------------------------------------------ guards


def test_zero1_rejections(devices, blobs):
    from helpers import make_mlp

    with pytest.raises(ValueError, match="only one of"):
        dk.ADAG(make_mlp(), zero1=True, fsdp=True)
    with pytest.raises(ValueError, match="only one of"):
        dk.ADAG(make_mlp(), zero1=True, plan=dk.dp_plan())
    with pytest.raises(ValueError, match="zero1"):
        dk.AEASGD(make_mlp(), zero1=True)
    with pytest.raises(ValueError, match="zero1"):
        dk.AEASGD(make_mlp(), plan=dk.zero1_plan())
    with pytest.raises(ValueError, match="exclusive"):
        dk.LMTrainer(CFG, fsdp=True, zero1=True)
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    with pytest.raises(ValueError, match="data axis only"):
        dk.LMTrainer(CFG, mesh=mesh, zero1=True)
    with pytest.raises(ValueError, match="zero1"):
        dk.LoRATrainer(CFG, base_params=tfm.init_params(
            jax.random.key(0), CFG), zero1=True)
    with pytest.raises(ValueError, match="zero1_bucket_mb"):
        dk.ADAG(make_mlp(), zero1_bucket_mb=8.0)
    with pytest.raises(ValueError, match="zero1_bucket_mb"):
        dk.LMTrainer(CFG, zero1_bucket_mb=8.0)


def test_zero1_plan_spelling_matches_flag(devices, blobs):
    """plan=zero1_plan() is the explicit spelling of zero1=True — the
    optimizer gets wrapped either way."""
    base, s0 = _adag(False, blobs)
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    from helpers import make_mlp

    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", learning_rate=0.05,
                batch_size=8, num_epoch=2, communication_window=4,
                plan=dk.zero1_plan())
    assert t.zero1
    state = t._fit(ds)
    np.testing.assert_allclose(t.history, base.history, **TOL)
    tree_close(state.tv, s0.tv)


def test_custom_transform_warns(blobs):
    """A prebuilt transform the inspector cannot attribute warns; a
    bare optax.adam is now RECOGNIZED elementwise by closure
    inspection (ops/optimizers.zero1_compatible) and constructs
    silently — the round-12 construction-time check upgrade."""
    import warnings

    from helpers import make_mlp

    opaque = optax.GradientTransformation(
        lambda p: (), lambda g, s, p=None: (g, s))
    with pytest.warns(UserWarning, match="elementwise"):
        dk.ADAG(make_mlp(), worker_optimizer=opaque, zero1=True)
    with pytest.warns(UserWarning, match="elementwise"):
        dk.LMTrainer(CFG, optimizer=opaque, zero1=True)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dk.ADAG(make_mlp(), worker_optimizer=optax.adam(1e-3),
                zero1=True)
        assert not [x for x in w if "elementwise" in str(x.message)]


def test_exports():
    assert dk.zero1_plan is not None
    assert dk.zero1_optimizer is cl.zero1_optimizer
    assert dk.collectives is cl
    from distkeras_tpu.ops.optimizers import (ZERO1_ELEMENTWISE,
                                              zero1_compatible)

    assert zero1_compatible("adamw") is True
    # Round 12: a bare prebuilt adam is recognized elementwise by
    # closure inspection; an unattributable transform stays None.
    assert zero1_compatible(optax.adam(1e-3)) is True
    assert zero1_compatible(optax.GradientTransformation(
        lambda p: (), lambda g, s, p=None: (g, s))) is None
    assert "sgd" in ZERO1_ELEMENTWISE
