"""Mid-training evaluation: metrics=('accuracy',) + eval_every produce
an eval_history of (round, {"loss", "accuracy"}) across the trainer
family — observability the reference does not have (its only signal is
the worker loss history, reference: distkeras/workers.py)."""

import numpy as np
import pytest

import distkeras_tpu as dk
from helpers import make_blobs, make_mlp


def _sets(blobs):
    feats, labels = blobs
    train = dk.Dataset({"features": feats[:384], "label": labels[:384]})
    evals = dk.Dataset({"features": feats[384:], "label": labels[384:]})
    return train, evals


def test_single_trainer_eval_history(blobs):
    train, evals = _sets(blobs)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         worker_optimizer="adam", learning_rate=1e-2,
                         batch_size=32, num_epoch=4,
                         metrics=("accuracy",), eval_every=6)
    t.train(train, eval_dataset=evals)
    rounds = [r for r, _ in t.eval_history]
    assert rounds[0] == 6 and rounds[-1] == -1  # periodic + final
    first, last = t.eval_history[0][1], t.eval_history[-1][1]
    assert set(first) == {"loss", "accuracy"}
    assert last["accuracy"] > 0.9 and last["accuracy"] > first["accuracy"] - 0.05
    assert last["loss"] < first["loss"]


def test_adag_eval_history(devices, blobs):
    train, evals = _sets(blobs)
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", learning_rate=1e-2,
                batch_size=8, num_epoch=4, communication_window=2,
                metrics=("accuracy",), eval_every=2)
    t.train(train, eval_dataset=evals)
    assert len(t.eval_history) >= 2
    assert t.eval_history[-1][1]["accuracy"] > 0.9


def test_downpour_evaluates_center(devices, blobs):
    train, evals = _sets(blobs)
    t = dk.DOWNPOUR(make_mlp(), loss="sparse_categorical_crossentropy",
                    worker_optimizer="adam", learning_rate=1e-2,
                    batch_size=8, num_epoch=6, communication_window=2,
                    metrics=("accuracy",), eval_every=1)
    t.train(train, eval_dataset=evals)
    accs = [m["accuracy"] for _, m in t.eval_history]
    assert accs[-1] > 0.85


def test_eval_without_dataset_and_unknown_metric(blobs):
    train, evals = _sets(blobs)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         worker_optimizer="adam", eval_every=2)
    with pytest.raises(ValueError, match="eval_dataset"):
        t.train(train)
    # Unknown metrics fail at construction, before any training runs.
    with pytest.raises(ValueError, match="unknown metric"):
        dk.SingleTrainer(make_mlp(), worker_optimizer="adam",
                         metrics=("f1",))


def test_one_hot_labels_accuracy(blobs):
    feats, labels = blobs
    onehot = np.eye(4, dtype=np.float32)[labels]
    train = dk.Dataset({"features": feats[:384], "label": onehot[:384]})
    evals = dk.Dataset({"features": feats[384:], "label": onehot[384:]})
    t = dk.SingleTrainer(make_mlp(), loss="categorical_crossentropy",
                         worker_optimizer="adam", learning_rate=1e-2,
                         batch_size=32, num_epoch=4, metrics=("accuracy",))
    t.train(train, eval_dataset=evals)
    assert t.eval_history[-1][1]["accuracy"] > 0.9


def test_eval_batches_not_monolithic(blobs):
    """The hook feeds the eval set in training-batch-size chunks (a
    large eval split must never run as one monolithic forward)."""
    feats, labels = blobs
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         worker_optimizer="adam", batch_size=32,
                         metrics=("accuracy",))
    seen = []
    state = t.adapter.init_state()
    t._eval_batch = (feats[:100], labels[:100])  # 3 full chunks + 4 rows
    t._eval_fn = (lambda tv, ntv, x, y:
                  (seen.append(len(x)) or
                   {"loss": np.float32(0.0), "accuracy": np.float32(1.0)}))
    t._eval_hook(state, rnd=None, final=True)
    assert seen == [32, 32, 32, 4]
    assert t.eval_history[-1][1]["accuracy"] == 1.0


def test_final_eval_without_eval_every(blobs):
    train, evals = _sets(blobs)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         worker_optimizer="adam", learning_rate=1e-2,
                         batch_size=32, num_epoch=2, metrics=("accuracy",))
    t.train(train, eval_dataset=evals)
    assert len(t.eval_history) == 1 and t.eval_history[0][0] == -1


def test_ensemble_rejects_eval(blobs):
    train, evals = _sets(blobs)
    with pytest.raises(ValueError, match="[Ee]nsemble"):
        dk.EnsembleTrainer(make_mlp(), num_models=2, eval_every=2)
    t = dk.EnsembleTrainer(make_mlp(), num_models=2,
                           loss="sparse_categorical_crossentropy",
                           worker_optimizer="sgd", batch_size=8)
    with pytest.raises(ValueError, match="[Ee]nsemble"):
        t.train(train, eval_dataset=evals)


def test_binary_accuracy(blobs):
    feats, labels = blobs
    import keras

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.Input((16,)),
                              keras.layers.Dense(16, activation="relu"),
                              keras.layers.Dense(1)])
    binary = (labels % 2).astype(np.float32)
    train = dk.Dataset({"features": feats[:384], "label": binary[:384]})
    evals = dk.Dataset({"features": feats[384:], "label": binary[384:]})
    t = dk.SingleTrainer(model, loss="binary_crossentropy",
                         worker_optimizer="adam", learning_rate=1e-2,
                         batch_size=32, num_epoch=2, metrics=("accuracy",))
    t.train(train, eval_dataset=evals)
    assert 0.0 <= t.eval_history[-1][1]["accuracy"] <= 1.0


def test_perplexity_evaluator_matches_trainer_eval(rng):
    """Standalone PerplexityEvaluator == the eval_every machinery's
    final number (same chunks, same NLL)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=1, d_ff=64, max_len=17)
    tokens = np.repeat(rng.integers(0, 64, (64, 1)), 17,
                       axis=1).astype(np.int32)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1,
                      eval_every=2)
    params = tr.train(tokens, eval_tokens=tokens[:32])
    ev = dk.PerplexityEvaluator(params, cfg, batch_size=16)
    ppl = ev.evaluate(tokens[:32])
    np.testing.assert_allclose(
        ppl, tr.eval_history[-1][1]["perplexity"], rtol=1e-6)
    # Dataset-column form.
    ppl2 = ev.evaluate(dk.Dataset({"tokens": tokens[:32]}))
    np.testing.assert_allclose(ppl2, ppl, rtol=1e-12)


def test_perplexity_evaluator_validation(rng):
    import pytest

    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm

    import jax

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=17)
    params = tfm.init_params(jax.random.key(0), cfg)
    ev = dk.PerplexityEvaluator(params, cfg, batch_size=16)
    with pytest.raises(ValueError, match="one batch needs"):
        ev.evaluate(np.zeros((4, 17), np.int32))
    with pytest.raises(ValueError, match="seq"):
        ev.evaluate(np.zeros((32,), np.int32))
    with pytest.raises(ValueError, match="batch_size"):
        dk.PerplexityEvaluator(params, cfg, batch_size=0)
