"""LoRA fine-tuning: merge algebra, frozen-base training, optimizer
state economy, serving composition, and mesh/packing integration."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.lora import (
    LoRAConfig,
    lora_init,
    lora_mask,
    lora_merge,
)


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=17)


def _rows(rng, n=64):
    return rng.integers(1, 64, (n, 17)).astype(np.int32)


def test_zero_init_merge_is_identity(rng):
    """B = 0 at init: the merged tree equals the base exactly, so step
    0 of a finetune reproduces the pretrained model."""
    params = tfm.init_params(jax.random.key(0), CFG)
    lcfg = LoRAConfig(rank=4, targets=("wq", "wk", "wv", "wo",
                                       "w1", "w2"))
    adapters = lora_init(jax.random.key(1), CFG, lcfg)
    merged = lora_merge(params, adapters, CFG, lcfg)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(merged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_merge_matches_manual_delta(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    lcfg = LoRAConfig(rank=3, alpha=6.0, targets=("wq",))
    adapters = lora_init(jax.random.key(1), CFG, lcfg)
    a = np.asarray(rng.normal(size=adapters["attn"]["wq"]["a"].shape),
                   np.float32)
    b = np.asarray(rng.normal(size=adapters["attn"]["wq"]["b"].shape),
                   np.float32)
    adapters = {"attn": {"wq": {"a": jnp.asarray(a), "b": jnp.asarray(b)}}}
    merged = lora_merge(params, adapters, CFG, lcfg)
    want = (np.asarray(params["layers"]["attn"]["wq"])
            + 2.0 * np.einsum("ldr,lrhk->ldhk", a, b))
    np.testing.assert_allclose(np.asarray(merged["layers"]["attn"]["wq"]),
                               want, atol=1e-5, rtol=1e-5)
    # Untargeted weights are the same objects, not copies.
    assert merged["layers"]["attn"]["wk"] is params["layers"]["attn"]["wk"]


def test_validation():
    with pytest.raises(ValueError, match="unknown LoRA targets"):
        lora_init(jax.random.key(0), CFG, LoRAConfig(targets=("bogus",)))
    with pytest.raises(ValueError, match="rank"):
        lora_init(jax.random.key(0), CFG, LoRAConfig(rank=0))
    with pytest.raises(ValueError, match="nothing to train"):
        lora_init(jax.random.key(0), CFG, LoRAConfig(targets=()))
    with pytest.raises(ValueError, match="duplicate"):
        lora_init(jax.random.key(0), CFG,
                  LoRAConfig(targets=("wq", "wq")))
    moe = dataclasses.replace(CFG, num_experts=4)
    with pytest.raises(ValueError, match="dense-FFN"):
        lora_init(jax.random.key(0), moe, LoRAConfig(targets=("w1",)))
    lora_init(jax.random.key(0), moe, LoRAConfig(targets=("wq",)))  # ok


def test_finetune_trains_adapters_and_freezes_base(rng):
    base = tfm.init_params(jax.random.key(0), CFG)
    base_copy = jax.tree.map(lambda x: np.asarray(x).copy(), base)
    rows = _rows(rng)
    tr = dk.LoRATrainer(CFG, base, lora_rank=4, learning_rate=5e-2,
                        batch_size=16, num_epoch=4)
    merged = tr.train(rows)
    assert tr.history[-1] < tr.history[0], tr.history
    # The base never moved...
    flat = {"/".join(map(str, p)): v for p, v in
            jax.tree_util.tree_flatten_with_path(base_copy)[0]}
    # (recover the trained base from the packed state via the trainer's
    # adapters: merged - delta == base)
    re_merged = lora_merge(
        jax.tree.map(np.asarray, base_copy), tr.adapters, CFG, tr.lora)
    for k, v in {"/".join(map(str, p)): v for p, v in
                 jax.tree_util.tree_flatten_with_path(re_merged)[0]
                 }.items():
        np.testing.assert_allclose(
            np.asarray(v),
            np.asarray({"/".join(map(str, p)): q for p, q in
                        jax.tree_util.tree_flatten_with_path(merged)[0]}[k]),
            atol=1e-6, err_msg=k)
    del flat
    # ...and the adapters did.
    assert float(jnp.abs(tr.adapters["attn"]["wq"]["b"]).sum()) > 0


def test_optimizer_state_excludes_base(rng):
    """The LoRA memory win: masked optimizer moments exist for the
    adapter leaves only (no [V, D] / [L, D, F] moment buffers)."""
    base = tfm.init_params(jax.random.key(0), CFG)
    tr = dk.LoRATrainer(CFG, base, lora_rank=4, learning_rate=1e-2,
                        batch_size=16)
    packed = tr.init_params()
    state = tr.optimizer.init(packed)
    n_adapter = sum(x.size for x in jax.tree.leaves(packed[0]))
    n_base = sum(x.size for x in jax.tree.leaves(packed[1]))
    n_state = sum(x.size for x in jax.tree.leaves(state)
                  if hasattr(x, "size"))
    # adamw: two moments per ADAPTER element plus scalars — and nothing
    # proportional to the (much larger at real scale) base.
    assert n_state < 3 * n_adapter + 10, (n_state, n_adapter)
    assert n_base > 10 * n_adapter  # the toy config still separates scales


def test_merged_model_serves(rng):
    """The finetuned artifact drops into generate + quantize + save."""
    from distkeras_tpu.models.generate import generate
    from distkeras_tpu.models.quant import quantize_params

    base = tfm.init_params(jax.random.key(0), CFG)
    rows = _rows(rng, 32)
    tr = dk.LoRATrainer(CFG, base, lora_rank=2, learning_rate=1e-2,
                        batch_size=16, num_epoch=1)
    merged = tr.train(rows)
    prompt = jnp.asarray(rows[:2, :4])
    out = generate(merged, prompt, CFG, 6)
    assert out.shape == (2, 10)
    q = quantize_params(merged)
    qout = generate(q, prompt, CFG, 6)
    assert qout.shape == (2, 10)


def test_lora_composes_with_tp_mesh_and_segments(devices, rng):
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = dataclasses.replace(CFG, rope=True)
    base = tfm.init_params(jax.random.key(0), cfg)
    docs = [rng.integers(1, 64, (int(n),)).tolist()
            for n in rng.integers(5, 30, 40)]
    rows, segs = dk.pack_documents(docs, seq_len=16)
    n = (len(rows) // 8) * 8
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    tr = dk.LoRATrainer(cfg, base, lora_rank=4, learning_rate=3e-2,
                        batch_size=8, num_epoch=3, mesh=mesh,
                        eval_every=4)
    tr.train(rows[:n], segments=segs[:n],
             eval_tokens=rows[:8], eval_segments=segs[:8])
    assert tr.history[-1] < tr.history[0]
    assert all(np.isfinite(v["loss"]) for _, v in tr.eval_history)


def test_lora_checkpoint_resume_matches_straight(tmp_path, rng):
    base = tfm.init_params(jax.random.key(0), CFG)
    rows = _rows(rng)
    common = dict(lora_rank=4, learning_rate=1e-2, batch_size=16)
    d = str(tmp_path / "ck")
    straight = dk.LoRATrainer(CFG, base, num_epoch=2, **common)
    want = straight.train(rows)
    dk.LoRATrainer(CFG, base, num_epoch=1, checkpoint_dir=d,
                   **common).train(rows)
    resumed = dk.LoRATrainer(CFG, base, num_epoch=2, checkpoint_dir=d,
                             resume=True, **common)
    got = resumed.train(rows)
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)
    assert len(resumed.history) == len(straight.history) // 2


def test_lora_mask_shape():
    lcfg = LoRAConfig(rank=2)
    adapters = lora_init(jax.random.key(0), CFG, lcfg)
    base = tfm.init_params(jax.random.key(0), CFG)
    mask = lora_mask((adapters, base))
    assert all(jax.tree.leaves(mask[0]))
    assert not any(jax.tree.leaves(mask[1]))


def test_train_rejects_params_argument(rng):
    base = tfm.init_params(jax.random.key(0), CFG)
    tr = dk.LoRATrainer(CFG, base, batch_size=16)
    with pytest.raises(ValueError, match="base_params"):
        tr.train(_rows(rng), params=base)
    with pytest.raises(ValueError, match="base_params"):
        dk.LoRATrainer(CFG, None)


def test_lora_merged_serves_speculatively(rng):
    """The full adapt-and-deploy composition: LoRA-finetuned merged
    tree serves via speculative decoding with its own int8 copy as the
    draft, matching generate's greedy rollout exactly."""
    from distkeras_tpu.models.generate import generate
    from distkeras_tpu.models.quant import quantize_params
    from distkeras_tpu.models.speculative import speculative_generate

    base = tfm.init_params(jax.random.key(0), CFG)
    rows = _rows(rng)
    tr = dk.LoRATrainer(CFG, base, lora_rank=4, learning_rate=3e-2,
                        batch_size=16, num_epoch=2)
    merged = tr.train(rows)
    draft = quantize_params(merged)
    prompt = jnp.asarray(rows[:4, :4])
    ref = np.asarray(generate(merged, prompt, CFG, 9))
    out, stats = speculative_generate(merged, draft, prompt, CFG, CFG,
                                      9, n_draft=3)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert float(stats["acceptance_rate"]) > 0.3  # int8 self-draft


def test_lora_grad_accum_matches_large_batch(rng):
    """grad_accum under the LoRA loss hook: accumulating microbatch
    gradients of the adapters equals one large-batch step (the
    masked-optimizer path composes with make_train_step's accum loop).
    """
    base = tfm.init_params(jax.random.key(0), CFG)
    rows = _rows(rng, 32)
    big = dk.LoRATrainer(CFG, base, lora_rank=4, learning_rate=1e-2,
                         batch_size=32, num_epoch=1)
    accum = dk.LoRATrainer(CFG, base, lora_rank=4, learning_rate=1e-2,
                           batch_size=16, grad_accum=2, num_epoch=1)
    want = big.train(rows)
    got = accum.train(rows)
    assert len(big.history) == len(accum.history) == 1
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
