"""Observability subsystem (distkeras_tpu/obs, docs/observability.md):
registry semantics, span/JSONL round-trip, the zero-overhead-when-
disabled contract (no trace file, no callbacks in jit, no extra
compiles), and end-to-end trainer + serving traces rendered by the
run-report machinery — the tier-1 obs smoke.
"""

import json
import os

import jax
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu import obs
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.obs.metrics import (MetricsRegistry,
                                        percentile_from_buckets)
from distkeras_tpu.obs.report import (build_report, load_report,
                                       render_compare, render_report)
from distkeras_tpu.obs.trace import EventTrace, read_trace

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=16)


def tokens(n=32, s=16, seed=0):
    return np.random.default_rng(seed).integers(
        0, 64, (n, s + 1)).astype(np.int32)


# ------------------------------------------------------------- registry


def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("requests", "total requests")
    c.inc()
    c.inc(2, status="ok")
    c.inc(1, status="timeout")
    assert c.value() == 1
    assert c.value(status="ok") == 2
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)

    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value() == 2
    g.set(7, lane="a")
    assert g.value(lane="a") == 7

    h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = reg.snapshot()["lat"]["series"][0]
    assert snap["count"] == 4 and snap["counts"] == [1, 1, 1, 1]
    assert snap["min"] == 0.005 and snap["max"] == 5.0
    # Bucket-interpolated percentiles land inside the winning bucket.
    assert 0.1 < percentile_from_buckets(snap, 0.7) <= 1.0

    # One name, one kind.
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("requests")
    with pytest.raises(ValueError, match="edges"):
        reg.histogram("lat", buckets=(1.0, 2.0))


def test_snapshot_isolation_and_render_text():
    reg = MetricsRegistry()
    reg.counter("a").inc(5)
    snap = reg.snapshot()
    reg.counter("a").inc(100)
    assert snap["a"]["series"][0]["value"] == 5  # decoupled
    text = reg.render_text()
    assert "# TYPE a counter" in text and "a 105.0" in text
    reg.histogram("h_s", buckets=(0.1, 1.0)).observe(0.05, kind="x")
    text = reg.render_text()
    assert 'h_s_bucket{kind="x",le="0.1"} 1' in text
    assert 'h_s_count{kind="x"} 1' in text


# ------------------------------------------------------- trace roundtrip


def test_span_nesting_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with EventTrace(path, run_id="r1") as tr:
        with tr.span("outer", phase="a"):
            tr.event("ping", x=1)
            with tr.span("inner"):
                pass
        with tr.span("outer2"):
            pass
    recs = read_trace(path)
    assert recs[0]["kind"] == "meta" and recs[0]["run"] == "r1"
    spans = {r["name"]: r for r in recs if r["kind"] == "span"}
    # inner closed first (spans are written at exit) and nests under
    # outer; outer2 is a fresh root.
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert spans["inner"]["depth"] == 1
    assert spans["outer"]["parent"] is None and spans["outer"]["depth"] == 0
    assert spans["outer2"]["parent"] is None
    assert spans["outer"]["dur"] >= spans["inner"]["dur"] >= 0
    ev = next(r for r in recs if r["kind"] == "event")
    assert ev["name"] == "ping" and ev["fields"] == {"x": 1}
    assert ev["span"] == spans["outer"]["id"]  # emitted inside outer
    # Torn final line (crashed writer) parses to the good prefix.
    with open(path, "a") as f:
        f.write('{"kind": "ev')
    assert read_trace(path) == recs


def test_session_singleton_and_final_metrics_record(tmp_path):
    path = str(tmp_path / "s.jsonl")
    with obs.session(trace_path=path) as sess:
        obs.count("x")
        with pytest.raises(RuntimeError, match="already active"):
            obs.enable()
        assert obs.active() is sess
    assert obs.active() is None
    recs = read_trace(path)
    metrics = [r for r in recs if r["kind"] == "metrics"]
    assert len(metrics) == 1
    assert metrics[0]["data"]["x"]["series"][0]["value"] == 1


# --------------------------------------------------- disabled is free


def test_noop_mode_writes_nothing(tmp_path):
    assert obs.active() is None
    before = set(os.listdir(tmp_path))
    obs.count("a")
    obs.gauge("b", 1)
    obs.observe("c", 0.5)
    obs.event("d")
    with obs.span("e"):
        pass
    assert set(os.listdir(tmp_path)) == before
    # The disabled span is one shared null context: no allocation.
    assert obs.span("x") is obs.span("y")


def test_no_host_callbacks_in_jit_with_obs_enabled(tmp_path):
    """The graph lint's host-callback rule over the REAL train step,
    telemetry ENABLED: obs never reaches inside a jitted program, so
    enabling it cannot add device->host round-trips (or change
    compile/comm budgets)."""
    from distkeras_tpu.analysis import ir_lint

    with obs.session(trace_path=str(tmp_path / "lint.jsonl")):
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8)
        (spec,) = t.traced_for_analysis()
        findings, _ = ir_lint.lint_trace(spec, compile_census=False)
    assert not [f.format() for f in findings
                if f.rule == "host-callback"]
    assert not [f.format() for f in findings if f.gating]


def test_obs_enabled_adds_no_compiles():
    """Enabling telemetry must not change what compiles: the same
    trainer session recompiles no MORE programs with a session active
    than without (the PR 3 compile-budget contract extends to obs)."""
    import jax.monitoring

    compiles = {"n": 0}

    def listener(event, duration, **kw):
        if event == "/jax/core/compile/backend_compile_duration":
            compiles["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(listener)

    def run():
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8)
        t.train(tokens())
        return t.history

    start = compiles["n"]
    h_plain = run()
    plain = compiles["n"] - start
    with obs.session():
        start = compiles["n"]
        h_obs = run()
        with_obs = compiles["n"] - start
    assert with_obs <= plain, (with_obs, plain)
    np.testing.assert_allclose(h_obs, h_plain, rtol=1e-6)


# ----------------------------------------------------------- end to end


def test_trainer_end_to_end_trace(tmp_path):
    path = str(tmp_path / "train.jsonl")
    with obs.session(trace_path=path) as sess:
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8)
        t.train(tokens())
    recs = read_trace(path)
    span_names = {r["name"] for r in recs if r["kind"] == "span"}
    assert {"train.h2d", "train.step"} <= span_names
    snap = sess.registry.compact()
    assert snap["train.rounds{trainer=LMTrainer}"] == len(t.history)
    assert snap["train.loss{trainer=LMTrainer}"] == pytest.approx(
        t.history[-1])
    rep = load_report(path)
    assert rep["phases"]["train.step"]["count"] == len(t.history)
    text = render_report(rep)
    assert "train.step" in text and "phase breakdown" in text


def test_serving_end_to_end_trace_and_compare(tmp_path):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)

    def serve(path, n_requests):
        with obs.session(trace_path=path):
            eng = dk.ContinuousBatcher(params, cfg, lanes=2,
                                       max_queue=4)
            rids = [eng.enqueue(rng.integers(0, 64, (5,)), 6)
                    for _ in range(n_requests)]
            while eng.running() or eng.queued:
                eng.step()
            res = eng.results()
            assert all(res[r].ok for r in rids)

    serve(str(tmp_path / "a.jsonl"), 3)
    serve(str(tmp_path / "b.jsonl"), 2)
    rep = load_report(str(tmp_path / "a.jsonl"))
    assert rep["scalars"]["serving.requests{status=ok}"] == 3
    lat = rep["latency"]["serving.request_s{status=ok}"]
    assert lat["count"] == 3
    assert lat["p50"] is not None and lat["p99"] >= lat["p50"] > 0
    assert "serving.step" in rep["phases"]
    rep_b = load_report(str(tmp_path / "b.jsonl"))
    out = render_compare(rep, rep_b)
    assert "serving.requests{status=ok}" in out
    assert "serving.step" in out and "->" in out
    assert rep_b["scalars"]["serving.requests{status=ok}"] == 2


def test_serving_rejects_and_deadline_metrics(tmp_path):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    clock = [0.0]
    with obs.session() as sess:
        eng = dk.ContinuousBatcher(params, cfg, lanes=1, max_queue=1,
                                   clock=lambda: clock[0])
        eng.enqueue(rng.integers(0, 64, (3,)), 4)
        # Queued with a deadline that expires before a lane frees.
        rid = eng.enqueue(rng.integers(0, 64, (3,)), 4, ttl=1.0)
        with pytest.raises(dk.QueueFull):
            eng.enqueue(rng.integers(0, 64, (3,)), 4)
        clock[0] = 5.0
        res = eng.shutdown()
        assert res[rid].timed_out
    snap = sess.registry.compact()
    assert snap["serving.rejected{reason=queue_full}"] == 1
    assert snap["serving.deadline_misses"] == 1
    assert snap["serving.requests{status=timeout}"] == 1


def test_chaos_and_supervisor_events_in_trace(tmp_path):
    """Satellite: fault injections and Supervisor restarts ride the
    obs event trace — the machine-readable fault/recovery timeline."""
    import tempfile

    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=64, seed=0)
    ds = dk.Dataset.from_arrays(x, y)
    path = str(tmp_path / "chaos.jsonl")
    with obs.session(trace_path=path):
        with tempfile.TemporaryDirectory() as d:
            t = dk.SingleTrainer(
                make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05,
                batch_size=16, num_epoch=2,
                checkpoint_dir=os.path.join(d, "c"),
                checkpoint_every=1, checkpoint_backend="pickle")
            sup = dk.Supervisor(t, max_retries=2, backoff=0.01,
                                max_backoff=0.01, jitter=0.0, seed=0)
            with dk.FaultPlan(0).fail("train.round", at=3):
                sup.run(ds)
    events = [r for r in read_trace(path) if r["kind"] == "event"]
    names = [e["name"] for e in events]
    assert "chaos.fault" in names
    fault = next(e for e in events if e["name"] == "chaos.fault")
    assert fault["fields"]["site"] == "train.round"
    attempts = [e for e in events if e["name"] == "supervisor.attempt"]
    assert [a["fields"]["outcome"] for a in attempts] == ["fault", "ok"]
    assert "supervisor.backoff" in names
    # Checkpoint persistence shows up as spans with durations.
    saves = [r for r in read_trace(path)
             if r["kind"] == "span" and r["name"] == "checkpoint.save"]
    assert saves and all(s["dur"] > 0 for s in saves)
    # The timeline renders (fault events included).
    text = render_report(build_report(read_trace(path)))
    assert "chaos.fault" in text


def test_speculative_accept_rate_counters():
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    draft = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_len=32)
    eng = dk.SpeculativeBatcher(
        tfm.init_params(jax.random.key(0), cfg),
        tfm.init_params(jax.random.key(1), draft),
        cfg, draft, lanes=2, n_draft=2)
    prompt = np.random.default_rng(0).integers(0, 64, (4,)).astype(
        np.int32)
    with obs.session() as sess:
        lane = eng.submit(prompt, 6)
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    snap = sess.registry.compact()
    assert snap["serving.spec.proposed"] > 0
    assert 0 <= snap["serving.spec.accepted"] <= snap[
        "serving.spec.proposed"]
    assert snap["serving.requests{status=ok}"] == 1


def test_prefetch_and_devicefeed_metrics():
    from distkeras_tpu.data.prefetch import DeviceFeed, Prefetcher

    batches = [np.ones((8, 4), np.float32) for _ in range(4)]
    with obs.session() as sess:
        for _ in Prefetcher(iter(batches), depth=2):
            pass
        for item in DeviceFeed(iter(batches), depth=2):
            jax.block_until_ready(item)
    snap = sess.registry.compact()
    assert snap["data.h2d.items"] == 4
    assert snap["data.h2d.bytes"] == 4 * 8 * 4 * 4
    assert "data.prefetch.occupancy" in snap


def test_zero1_bucket_geometry_recorded():
    with obs.session() as sess:
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8,
                         zero1=True)
        t.train(tokens())
    snap = sess.registry.compact()
    assert snap["zero1.buckets"] >= 1
    assert snap["zero1.pad_bytes"] == 0  # test model divides evenly
    # Exchange bytes == parameter bytes (the pad-free parity layout).
    pbytes = sum(np.prod(v.shape) * v.dtype.itemsize
                 for v in jax.tree.leaves(
                     jax.eval_shape(lambda: tfm.init_params(
                         jax.random.key(0), CFG))))
    assert snap["zero1.exchange_bytes"] == pbytes


def test_obs_report_cli(tmp_path):
    import subprocess
    import sys

    path = str(tmp_path / "cli.jsonl")
    with obs.session(trace_path=path):
        with obs.span("train.step"):
            pass
        obs.event("marker", k=1)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_report.py"),
         path], capture_output=True, text=True, timeout=120, cwd=root)
    assert r.returncode == 0, r.stderr
    assert "train.step" in r.stdout and "marker" in r.stdout
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_report.py"),
         path, "--compare", path, "--json"],
        capture_output=True, text=True, timeout=120, cwd=root)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["new"]["phases"]["train.step"][
        "count"] == 1
