"""Resilience subsystem (SURVEY.md §5: the reference dies whole-job).

Resume correctness is *bit-for-bit*: an injected kill at step N followed
by Supervisor auto-resume must reproduce the uninterrupted run's loss
trajectory exactly and land on identical parameters.  Serving deadlines
must never let an expired request occupy a decode lane, and a faulting
draft model must degrade to the plain decode path, not kill requests.
"""

import sys
import time

import jax
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.checkpoint import CheckpointManager
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate
from distkeras_tpu.resilience import (EngineClosed, FaultInjected,
                                      FaultPlan, Preempted, QueueFull,
                                      Supervisor, chaos)
from distkeras_tpu.serving import ContinuousBatcher, SpeculativeBatcher

from conftest import make_blobs, make_mlp

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)
DRAFT = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                              n_layers=1, d_ff=32, max_len=32)

COMMON = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="sgd", learning_rate=0.05,
              batch_size=16, num_epoch=2)  # 16 rounds over 128 blobs


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.key(0), CFG)


@pytest.fixture()
def fake_clock():
    t = [0.0]

    def clock():
        return t[0]

    clock.advance = lambda dt: t.__setitem__(0, t[0] + dt)
    return clock


def _weights(model):
    return [np.asarray(w) for w in model.get_weights()]


# ------------------------------------------------------------- chaos plans


def test_fault_plan_rejects_unknown_site():
    with pytest.raises(ValueError, match="unknown chaos site"):
        FaultPlan().fail("no.such.site")


def test_fault_plan_fires_at_step_and_respects_times():
    plan = FaultPlan().fail("train.round", at=3, times=2)
    with plan:
        for rnd in range(1, 6):
            if rnd == 3:
                with pytest.raises(FaultInjected):
                    chaos.probe("train.round", step=rnd)
            else:
                chaos.probe("train.round", step=rnd)
        # `at` pins to the counter value: round 3 already passed, so the
        # second allotted firing never triggers.
    assert plan.events == [("train.round", 3, "fail")]
    # inactive outside the with-block: probes are free no-ops
    chaos.probe("train.round", step=3)


def test_fault_plan_probabilistic_rules_are_seeded():
    def firings(seed):
        plan = FaultPlan(seed).fail("serving.step", times=None, p=0.5)
        with plan:
            for _ in range(32):
                try:
                    chaos.probe("serving.step")
                except FaultInjected:
                    pass
        return [n for (_, n, _) in plan.events]

    assert firings(7) == firings(7)
    assert firings(7) != firings(8)


def test_fault_plans_do_not_nest():
    with FaultPlan():
        with pytest.raises(RuntimeError, match="already active"):
            FaultPlan().__enter__()


# -------------------------------------------------- pickle checkpoint backend


def test_pickle_backend_roundtrip(tmp_path):
    import jax.numpy as jnp

    state = {"a": jnp.arange(12.0).reshape(3, 4),
             "step": jnp.asarray(7, jnp.int32)}
    with CheckpointManager(str(tmp_path / "c"), backend="pickle") as m:
        assert m.backend == "pickle"
        assert m.latest_step() is None
        m.save(state, step=3)
        m.wait_until_finished()
        out = m.restore({"a": jnp.zeros((3, 4)),
                         "step": jnp.asarray(0, jnp.int32)})
    np.testing.assert_array_equal(out["a"], state["a"])
    assert int(out["step"]) == 7


def test_pickle_backend_orbax_parity_semantics(tmp_path):
    import jax.numpy as jnp

    with CheckpointManager(str(tmp_path / "c"), backend="pickle",
                           max_to_keep=2) as m:
        for s in (1, 2, 3):
            m.save({"v": jnp.asarray(float(s))}, step=s, force=True)
        assert m.all_steps() == [2, 3]          # GC'd like orbax
        with pytest.raises(ValueError, match="already exists"):
            m.save({"v": jnp.asarray(9.0)}, step=3, force=True)
    with CheckpointManager(str(tmp_path / "empty"),
                           backend="pickle") as m:
        with pytest.raises(FileNotFoundError):
            m.restore({"x": np.zeros(2)})


def test_missing_orbax_raises_clearly_and_auto_falls_back(
        tmp_path, monkeypatch):
    monkeypatch.setitem(sys.modules, "orbax", None)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    with pytest.raises(ImportError, match="backend='pickle'"):
        CheckpointManager(str(tmp_path / "c"), backend="orbax")
    with CheckpointManager(str(tmp_path / "c"), backend="auto") as m:
        assert m.backend == "pickle"


@pytest.mark.chaos
def test_checkpoint_save_fault_injectable(tmp_path):
    import jax.numpy as jnp

    with CheckpointManager(str(tmp_path / "c"), backend="pickle") as m:
        with FaultPlan().fail("checkpoint.save"):
            with pytest.raises(FaultInjected):
                m.save({"v": jnp.asarray(1.0)}, step=1)
        m.save({"v": jnp.asarray(1.0)}, step=1)  # plan gone: save lands
        assert m.all_steps() == [1]


# --------------------------------------------------------------- supervisor


def test_supervisor_requires_durable_trainer(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_dir"):
        Supervisor(dk.SingleTrainer(make_mlp(), **COMMON))
    with pytest.raises(ValueError, match="checkpoint_every"):
        Supervisor(dk.SingleTrainer(
            make_mlp(), checkpoint_dir=str(tmp_path / "c"), **COMMON))


@pytest.mark.chaos
@pytest.mark.parametrize("kill_round,via_signal", [(7, False), (6, True)])
def test_kill_at_step_then_autoresume_bit_for_bit(tmp_path, kill_round,
                                                  via_signal):
    """The acceptance contract: injected kill at an arbitrary step ->
    Supervisor auto-resumes -> final parameters identical (allclose,
    CPU) to an uninterrupted run, resumed loss trajectory bit-for-bit.

    Exception kills die BEFORE the round commits (resume replays it);
    graceful SIGTERM forces a synchronous checkpoint of the preempted
    round first (resume continues one round later) — even at a round
    the periodic checkpoint_every cadence would have skipped.
    """
    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)

    straight = dk.SingleTrainer(make_mlp(), **COMMON)
    ref = straight.train(ds)

    every = 4 if via_signal else 1  # sigterm: prove the forced sync save
    t = dk.SingleTrainer(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                         checkpoint_every=every,
                         checkpoint_backend="pickle", **COMMON)
    sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    plan = FaultPlan()
    if via_signal:
        plan.preempt("train.round", at=kill_round, via_signal=True)
    else:
        plan.fail("train.round", at=kill_round)
    with plan:
        out = sup.run(ds)

    for wr, wo in zip(_weights(ref), _weights(out)):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    resume_at = kill_round if via_signal else kill_round - 1
    assert t.history == straight.history[resume_at:]
    outcomes = [a.outcome for a in sup.attempts]
    assert outcomes == (["preempted", "ok"] if via_signal
                        else ["fault", "ok"])
    if via_signal:
        # 6 is not a multiple of checkpoint_every=4: only the forced
        # preemption save can have committed it.
        assert sup.attempts[1].resumed_from == kill_round


@pytest.mark.chaos
def test_supervisor_retries_checkpoint_save_fault(tmp_path):
    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.SingleTrainer(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                         checkpoint_every=1, checkpoint_backend="pickle",
                         **COMMON)
    sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    with FaultPlan().fail("checkpoint.save", at=5):
        sup.run(ds)
    assert [a.outcome for a in sup.attempts] == ["fault", "ok"]
    assert sup.attempts[1].resumed_from == 4  # durable through round 4


@pytest.mark.chaos
def test_sigterm_interrupts_backoff_immediately(tmp_path):
    """A SIGTERM arriving during the backoff window must not ride out
    the sleep: the Supervisor's backoff waits on the preempt event, so
    the preemption cuts it short and the next attempt's first round
    boundary runs the normal forced-sync-checkpoint path.  The
    regression: a 30 s backoff used to delay the preemption checkpoint
    by the full 30 s — well past any eviction notice."""
    import threading

    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.SingleTrainer(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                         checkpoint_every=1, checkpoint_backend="pickle",
                         **COMMON)
    sup = Supervisor(t, max_retries=2, backoff=30.0, max_backoff=30.0,
                     jitter=0.0, handle_sigterm=False)
    # Deliver the "SIGTERM" around the fault retry's backoff window
    # (the chaos probe outranks the preempt check at a round boundary,
    # so the round-1 fault fires first in every interleaving).
    threading.Timer(0.5, sup.preempt_event.set).start()
    t0 = time.monotonic()
    with FaultPlan().fail("train.round", at=1):
        sup.run(ds)
    elapsed = time.monotonic() - t0
    assert elapsed < 15.0, (
        f"backoff was not interrupted: run took {elapsed:.1f}s against "
        "a 30s backoff")
    outcomes = [a.outcome for a in sup.attempts]
    # fault -> (interrupted backoff) -> preempted at the next round
    # boundary -> clean resumed finish.
    assert outcomes == ["fault", "preempted", "ok"], outcomes


@pytest.mark.chaos
def test_supervisor_exhausts_retries_and_reraises(tmp_path):
    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.SingleTrainer(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                         checkpoint_every=1, checkpoint_backend="pickle",
                         **COMMON)
    sup = Supervisor(t, max_retries=1, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    with FaultPlan().fail("train.round", at=1, times=None):
        with pytest.raises(FaultInjected):
            sup.run(ds)
    assert [a.outcome for a in sup.attempts] == ["fault", "fault"]


@pytest.mark.chaos
@pytest.mark.slow
def test_supervisor_wraps_lm_trainer(tmp_path):
    """The supervisor is trainer-family-wide: the flagship LMTrainer
    resumes through an injected kill to the same params as straight."""
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 64, (64, 17)).astype(np.int32)
    kw = dict(optimizer="sgd", learning_rate=0.05, batch_size=8,
              num_epoch=1, seed=3)

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=16)
    straight = dk.LMTrainer(cfg, **kw)
    ref_params = straight.train(rows)

    t = dk.LMTrainer(cfg, checkpoint_dir=str(tmp_path / "c"),
                     checkpoint_every=1, checkpoint_backend="pickle",
                     **kw)
    sup = Supervisor(t, max_retries=1, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    with FaultPlan().fail("train.round", at=5):
        out = sup.run(rows)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert t.history == straight.history[4:]


# ------------------------------------------------------- serving deadlines


def test_expired_deadline_never_occupies_a_lane(params, rng, fake_clock):
    eng = ContinuousBatcher(params, CFG, lanes=2, max_queue=2,
                            clock=fake_clock)
    prompt = rng.integers(0, 64, (4,)).astype(np.int32)
    rid = eng.enqueue(prompt, 5, ttl=0.0)
    res = eng.take(rid)
    assert res.timed_out and res.status == "timeout"
    np.testing.assert_array_equal(res.tokens, prompt)  # nothing decoded
    assert eng.free_lanes() == [0, 1]
    # bare submit() honors the same contract: no lane, structured result
    assert eng.submit(prompt, 5, ttl=-1.0) is None
    (res,) = eng.results().values()
    assert res.timed_out and eng.free_lanes() == [0, 1]


def test_midflight_deadline_evicts_lane_with_partial_result(
        params, rng, fake_clock):
    eng = ContinuousBatcher(params, CFG, lanes=2, clock=fake_clock)
    prompt = rng.integers(0, 64, (4,)).astype(np.int32)
    lane = eng.submit(prompt, 10, ttl=5.0)
    assert lane is not None
    eng.step()
    eng.step()
    fake_clock.advance(6.0)
    eng.step()                      # straddling window's tokens kept
    (res,) = eng.results().values()
    assert res.status == "timeout" and len(res.generated) == 3
    # evicted: the lane is immediately reusable
    assert eng.free_lanes() == [0, 1]
    # ... and the partial tokens match the solo run's prefix
    solo = np.asarray(generate(params, prompt[None], CFG, 10))[0]
    np.testing.assert_array_equal(res.tokens, solo[:len(res.tokens)])


def test_queued_request_expiring_before_admission_never_runs(
        params, rng, fake_clock):
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=2,
                            clock=fake_clock)
    ra = eng.enqueue(rng.integers(0, 64, (3,)), 4)
    rb = eng.enqueue(rng.integers(0, 64, (3,)), 4, ttl=1.0)  # queued
    fake_clock.advance(2.0)
    while eng.running() or eng.queued:
        eng.step()
    res = eng.results()
    assert res[ra].ok
    assert res[rb].timed_out
    assert len(res[rb].tokens) == 3  # prompt only: never decoded


# ---------------------------------------------------- queue / backpressure


def test_bounded_queue_backpressure_and_fifo_completion(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=2)
    prompts = [rng.integers(0, 64, (3,)).astype(np.int32)
               for _ in range(3)]
    rids = [eng.enqueue(p, 4) for p in prompts]
    assert eng.queued == 2
    with pytest.raises(QueueFull, match="max_queue"):
        eng.enqueue(prompts[0], 4)
    res = eng.shutdown()
    assert [res[r].ok for r in rids] == [True] * 3
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            res[rid].tokens, np.asarray(generate(params, p[None],
                                                 CFG, 4))[0])


def test_enqueue_keeps_fifo_order_over_freed_lanes(params, rng):
    """A new enqueue must not jump ahead of an already-queued request
    when a lane happens to be free at enqueue time."""
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=4)
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    ra = eng.enqueue(p, 2)
    rb = eng.enqueue(p, 2)          # queued behind ra
    while eng.poll(ra) is None:
        eng.step()                  # ra finishes; lane frees
    rc = eng.enqueue(p, 2)          # must queue BEHIND rb... or rb
    # must already hold the lane (enqueue pumps first) — either way rb
    # decodes before rc.
    while eng.poll(rc) is None:
        eng.step()
    res = eng.results()
    assert res[ra].ok and res[rb].ok and res[rc].ok
    assert res[rb].request_id < res[rc].request_id


def test_bare_submit_deadline_result_reachable_by_id(params, rng,
                                                     fake_clock):
    eng = ContinuousBatcher(params, CFG, lanes=1, clock=fake_clock)
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    assert eng.submit(p, 4, ttl=-1.0) is None
    rid = eng.last_request_id
    assert eng.take(rid).timed_out
    lane = eng.submit(p, 8, ttl=1.0)
    rid = eng.last_request_id
    # engine-full decline registers nothing: last_request_id must not
    # keep pointing at the previous request
    assert eng.submit(p, 4) is None and eng.last_request_id is None
    fake_clock.advance(2.0)
    eng.step()
    assert eng.take(rid).timed_out and lane not in eng.running()


def test_queued_request_failing_deferred_validation_reports_error(
        params, rng):
    """A queued request that fails engine-specific validation when its
    lane frees (the key-iff-sampling rule can only run at admission)
    must reach a terminal structured result, not crash the loop."""
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=2,
                            temperature=0.8)
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    ra = eng.enqueue(p, 3, key=jax.random.key(1))
    rb = eng.enqueue(p, 3)          # queued; missing key: invalid
    res = eng.shutdown()
    assert res[ra].ok
    assert res[rb].status == "error" and "key iff" in res[rb].error


def test_shutdown_lifecycle(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=4)
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    ra = eng.enqueue(p, 4)
    rb = eng.enqueue(p, 4)          # queued behind ra
    eng.begin_shutdown()
    with pytest.raises(EngineClosed):
        eng.enqueue(p, 2)
    with pytest.raises(EngineClosed):
        eng.submit(p, 2)
    res = eng.shutdown()            # drains lane AND queue
    assert res[ra].ok and res[rb].ok
    assert not eng.running() and eng.queued == 0


def test_shutdown_max_steps_cancels_structured(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=4)
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    ra = eng.enqueue(p, 8)
    rb = eng.enqueue(p, 8)
    res = eng.shutdown(max_steps=2)
    assert res[ra].status == "cancelled" and len(res[ra].generated) == 2
    assert res[rb].status == "cancelled" and len(res[rb].generated) == 0


# ------------------------------------------------- speculative degradation


@pytest.mark.chaos
def test_draft_fault_falls_back_and_completes_greedy_parity(rng):
    """Acceptance: a faulting draft model must not kill requests — the
    engine degrades to the plain decode path mid-flight and greedy
    outputs still match solo generate exactly."""
    tp = tfm.init_params(jax.random.key(0), CFG)
    dp = tfm.init_params(jax.random.key(9), DRAFT)
    pa = rng.integers(0, 64, (5,)).astype(np.int32)
    pb = rng.integers(0, 64, (3,)).astype(np.int32)
    eng = SpeculativeBatcher(tp, dp, CFG, DRAFT, lanes=2, n_draft=3)
    la = eng.submit(pa, 8)
    eng.step()                       # healthy speculative round first
    lb = eng.submit(pb, 6)
    plan = FaultPlan().fail("serving.draft")
    with plan:
        eng.step()                   # draft faults -> degrade, no loss
    # The plan's per-site call counter starts at ITS activation: this
    # is the first draft probe the plan sees.
    assert eng.degraded and ("serving.draft", 1, "fail") in plan.events
    assert isinstance(eng.degraded_error, FaultInjected)
    while eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(la), np.asarray(generate(tp, pa[None], CFG, 8))[0])
    np.testing.assert_array_equal(
        eng.drain(lb), np.asarray(generate(tp, pb[None], CFG, 6))[0])
    # degraded engines still admit and serve new requests
    lc = eng.submit(pa, 4)
    while lc in eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(lc), np.asarray(generate(tp, pa[None], CFG, 4))[0])


def test_enqueue_vs_shutdown_race_is_atomic(params, rng):
    """`begin_shutdown` racing in-flight `enqueue`s: the closed check
    and the queue insert are atomic under one lock, and EngineClosed
    wins — every enqueue either gets its request in (and shutdown's
    drain reaches a terminal result for it) or raises EngineClosed;
    QueueFull only ever comes from an engine that is open.  No request
    may be silently lost."""
    import threading

    prompt = rng.integers(0, 64, (3,)).astype(np.int32)
    for trial in range(4):
        # One lane, held busy by a long request, so racing enqueues
        # only ever touch the queue — the contended structure.
        eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=4)
        blocker = eng.enqueue(prompt, 25)
        outcomes: list = [None] * 8
        start = threading.Barrier(9)

        def worker(i):
            start.wait()
            try:
                outcomes[i] = ("rid", eng.enqueue(prompt, 2))
            except QueueFull:
                outcomes[i] = ("queue_full", None)
            except EngineClosed:
                outcomes[i] = ("closed", None)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        start.wait()
        eng.begin_shutdown()
        for t in threads:
            t.join()
        res = eng.shutdown(max_steps=3)
        assert all(o is not None for o in outcomes)
        accepted = [rid for kind, rid in outcomes if kind == "rid"]
        # Bounded queue held under the race...
        assert len(accepted) <= eng.max_queue
        # ...and EVERY accepted request reached a terminal result.
        assert blocker in res
        for rid in accepted:
            assert rid in res, f"request {rid} lost in the race"


# --------------------------------------------------- elastic lane tiers


def test_elastic_tiers_step_up_under_backpressure_and_back_down(
        params, rng):
    """The acceptance contract: sustained overload steps the lane tier
    up (with scale_up_after=1, ZERO QueueFull is raised — the overflow
    that would have raised is absorbed by the resize), requests all
    complete with exact solo parity, and a drained idle engine steps
    back down.  Tier moves are obs-visible."""
    from distkeras_tpu import obs

    prompts = [rng.integers(0, 64, (p,)).astype(np.int32)
               for p in (3, 5, 4, 6, 3)]
    with obs.session() as sess:
        eng = ContinuousBatcher(params, CFG, lane_tiers=(1, 2, 4),
                                max_queue=1, scale_up_after=1,
                                scale_down_after=2, prompt_buckets=(8,))
        assert eng.lanes == 1
        rids = [eng.enqueue(p, 6) for p in prompts]   # never raises
        assert eng.lanes == 4, "sustained overflow did not scale up"
        while any(eng.poll(r) is None for r in rids):
            eng.step()
        for _ in range(6):
            eng.step()               # idle: tier steps down 4->2->1
        assert eng.lanes == 1, "idle engine did not scale back down"
        snap = sess.registry.snapshot()
    res = {r: eng.take(r) for r in rids}
    for rid, p in zip(rids, prompts):
        assert res[rid].ok
        np.testing.assert_array_equal(
            res[rid].tokens,
            np.asarray(generate(params, p[None], CFG, 6))[0])
    resizes = {tuple(s["labels"].items()): s["value"]
               for s in snap["serving.resizes"]["series"]}
    assert resizes[(("direction", "up"),)] == 2
    assert resizes[(("direction", "down"),)] == 2
    assert "queue_full" not in str(snap.get("serving.rejected", ""))


def test_elastic_rejects_bare_submit_and_undeclared_windows(params, rng):
    eng = ContinuousBatcher(params, CFG, lane_tiers=(1, 2), max_queue=1,
                            prompt_buckets=(8,), step_windows=(1, 4))
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    with pytest.raises(ValueError, match="enqueue"):
        eng.submit(p, 4)
    rid = eng.enqueue(p, 4)
    with pytest.raises(ValueError, match="step_windows"):
        eng.step(3)
    eng.step(4)                      # declared window: fine
    while eng.poll(rid) is None:
        eng.step()
    assert eng.take(rid).ok
    with pytest.raises(ValueError, match=">= 2 distinct tiers"):
        ContinuousBatcher(params, CFG, lane_tiers=(4,), max_queue=1)
    with pytest.raises(ValueError, match="max_queue"):
        ContinuousBatcher(params, CFG, lane_tiers=(1, 2))
    with pytest.raises(ValueError, match="include 1"):
        ContinuousBatcher(params, CFG, lane_tiers=(1, 2), max_queue=1,
                          step_windows=(4,))


def test_elastic_scale_up_after_counts_strikes(params, rng):
    """scale_up_after=2: the first overflow raises QueueFull (strike
    one), the second resizes instead — backpressure must be SUSTAINED
    before the engine spends memory on a bigger tier."""
    eng = ContinuousBatcher(params, CFG, lane_tiers=(1, 2), max_queue=1,
                            scale_up_after=2, prompt_buckets=(8,))
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    ra = eng.enqueue(p, 4)
    rb = eng.enqueue(p, 4)           # queued
    with pytest.raises(QueueFull):
        eng.enqueue(p, 4)            # strike 1: still tier 1
    assert eng.lanes == 1
    rc = eng.enqueue(p, 4)           # strike 2: resize absorbs it
    assert eng.lanes == 2
    res = eng.shutdown()
    assert res[ra].ok and res[rb].ok and res[rc].ok


@pytest.mark.slow
def test_elastic_resize_preserves_inflight_requests(params, rng):
    """A tier move mid-decode must not disturb running lanes: requests
    admitted before, across, and after resizes all keep exact solo
    parity (the lane compaction gathers their device rows)."""
    eng = ContinuousBatcher(params, CFG, lane_tiers=(1, 2, 4),
                            max_queue=2, scale_up_after=1,
                            scale_down_after=2, prompt_buckets=(8,))
    pa = rng.integers(0, 64, (4,)).astype(np.int32)
    pb = rng.integers(0, 64, (6,)).astype(np.int32)
    ra = eng.enqueue(pa, 12)
    eng.step(); eng.step()           # ra decodes at tier 1
    rbs = [eng.enqueue(pb, 5) for _ in range(5)]  # forces tier up
    assert eng.lanes == 4
    while any(eng.poll(r) is None for r in [ra, *rbs]):
        eng.step()
    for _ in range(6):
        eng.step()                   # drain: tier steps back down 4->2->1
    assert eng.lanes == 1
    res = eng.results()
    np.testing.assert_array_equal(
        res[ra].tokens, np.asarray(generate(params, pa[None], CFG,
                                            12))[0])
    for r in rbs:
        np.testing.assert_array_equal(
            res[r].tokens, np.asarray(generate(params, pb[None], CFG,
                                               5))[0])


def test_speculative_deadline_and_queue(rng, fake_clock):
    tp = tfm.init_params(jax.random.key(0), CFG)
    dp = tfm.init_params(jax.random.key(9), DRAFT)
    eng = SpeculativeBatcher(tp, dp, CFG, DRAFT, lanes=1, n_draft=2,
                             max_queue=1, clock=fake_clock)
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    rid = eng.enqueue(p, 4, ttl=0.0)
    assert eng.take(rid).timed_out and eng.free_lanes() == [0]
    ra = eng.enqueue(p, 4)
    rb = eng.enqueue(p, 4)
    with pytest.raises(QueueFull):
        eng.enqueue(p, 4)
    res = eng.shutdown()
    assert res[ra].ok and res[rb].ok
