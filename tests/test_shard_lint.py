"""Shard lint: every plan-lint rule positive + negative, resharding
attribution (including the injected dropped-``with_sharding_constraint``
regression), the placement-census machinery, the shipped-plan dry-run
matrix, and the CLI mode-flag validation.

The compiled-census repo guards — placement budget vs
``scripts/shard_budget.json``, the no-unattributed-resharding
invariant, the memory-footprint cross-check — live in
``tests/test_budget_guards.py``, which compiles every standard target
once for the whole module (same split as graph lint:
test_graph_lint.py carries the rules, test_budget_guards.py the heavy
repo runs).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.analysis import shard_lint as sl
from distkeras_tpu.analysis.ir_lint import TraceSpec, trace_target
from distkeras_tpu.parallel import rules as pr
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings, only_gating=False):
    return {f.rule for f in findings if f.gating or not only_gating}


def _tree():
    return {
        "layers": {"attn": {"wq": jax.ShapeDtypeStruct((2, 32, 2, 16),
                                                       jnp.float32)},
                   "ffn": {"w1": jax.ShapeDtypeStruct((2, 32, 64),
                                                      jnp.float32)}},
        "tok_emb": jax.ShapeDtypeStruct((64, 32), jnp.float32),
    }


# ------------------------------------------------------ plan-lint rules


def test_dead_rule_positive_and_negative():
    pos = sl.lint_plan([("atn/wq$", P())], _tree(), name="t")
    assert "dead-rule" in rules_of(pos, only_gating=True)
    neg = sl.lint_plan([("attn/wq$", P())], _tree(), name="t")
    assert "dead-rule" not in rules_of(neg)
    # The finding names the offending (pattern, value) pair.
    f = next(f for f in pos if f.rule == "dead-rule")
    assert "atn/wq$" in f.message and "P()" in f.message


def test_shadowed_rule_positive_and_negative():
    pos = sl.lint_plan([("attn/.*", P(None, None, "model", None)),
                        ("attn/wq$", P())], _tree(), name="t")
    assert "shadowed-rule" in rules_of(pos, only_gating=True)
    f = next(f for f in pos if f.rule == "shadowed-rule")
    # ... naming the shadowed rule, the covering rule, and the leaves.
    assert "attn/wq$" in f.message and "attn/.*" in f.message
    assert "layers/attn/wq" in f.message
    # A later broader rule that still wins SOME leaf: no shadow.
    neg = sl.lint_plan([("attn/wq$", P()), ("(attn|ffn)/.*", P())],
                       _tree(), name="t")
    assert "shadowed-rule" not in rules_of(neg)
    # Union coverage shadows too: two narrow rules together cover a
    # later broad one.
    pos2 = sl.lint_plan([("attn/wq$", P()), ("ffn/w1$", P()),
                         ("(attn/wq|ffn/w1)$", P())], _tree(), name="t")
    assert "shadowed-rule" in rules_of(pos2)


def test_callable_decliner_does_not_shadow():
    # An earlier callable that declines every leaf leaves later rules
    # reachable — the decline-chain idiom must not read as shadowing.
    fs = sl.lint_plan([(".*", lambda n, l: None), ("attn/wq$", P())],
                      _tree(), name="t")
    assert "shadowed-rule" not in rules_of(fs)
    # ... but a callable that CLAIMS everything does shadow.
    fs = sl.lint_plan([(".*", lambda n, l: P()), ("attn/wq$", P())],
                      _tree(), name="t")
    assert "shadowed-rule" in rules_of(fs)


def test_duplicate_pattern_positive_and_negative():
    # lint_plan analyzes raw (uncompiled) lists, so the duplicate that
    # compile_rules would reject at build time is reported statically.
    pos = sl.lint_plan([("attn/wq$", P()), ("attn/wq$", P("data"))],
                       _tree(), name="t")
    assert "duplicate-pattern" in rules_of(pos, only_gating=True)
    # Repeat after a CALLABLE occurrence is the legal decline chain.
    neg = sl.lint_plan([(".*", lambda n, l: None), (".*", P())],
                       _tree(), name="t")
    assert "duplicate-pattern" not in rules_of(neg)


def test_duplicate_not_double_reported_as_shadowed_or_dead():
    # One authoring bug -> ONE finding: the duplicate error, not an
    # extra shadowed-rule warn (which would inflate ratchet counts).
    fs = sl.lint_plan([("attn/wq$", P()), ("attn/wq$", P("data"))],
                      _tree(), name="t")
    by_rule = [f.rule for f in fs]
    assert by_rule.count("duplicate-pattern") == 1
    assert "shadowed-rule" not in by_rule and "dead-rule" not in by_rule


def test_verbatim_extra_rule_overrides_stock_pattern():
    """The documented `serving_plan(extra_rules=...)` override idiom,
    spelled with the stock pattern VERBATIM: the stock copy is dropped
    (not left as a rejected duplicate), the override wins, and the
    composed plan lints clean."""
    from distkeras_tpu.parallel.sharding import serving_plan, tp_plan

    plan = serving_plan(extra_rules=[(r"attn/w[qkv]$", P())])
    assert plan.spec_for("layers/attn/wq") == P()
    assert sum(1 for p, _ in plan.rules
               if p.pattern == r"attn/w[qkv]$") == 1
    assert "duplicate-pattern" not in rules_of(
        sl.lint_plan(plan, _tree(), name="t"))
    tp_plan(extra_rules=[(r"(dense|mlp|fc)[^/]*/kernel$", P())])


def test_invalid_regex():
    fs = sl.lint_plan([("([unclosed", P())], _tree(), name="t")
    assert "invalid-regex" in rules_of(fs, only_gating=True)
    # The broken rule is skipped, not fatal: later rules still lint.
    assert "dead-rule" not in rules_of(
        sl.lint_plan([("([unclosed", P()), ("attn/wq$", P())],
                     _tree(), name="t"))


def test_axis_divisibility_positive_and_negative():
    rules = [("attn/wq$", P(None, None, "model", None))]
    # heads dim = 2: divisible by 2, not by 3.
    neg = sl.lint_plan(rules, _tree(), name="t",
                       axis_sizes={"model": 2})
    assert "axis-divisibility" not in rules_of(neg)
    pos = sl.lint_plan(rules, _tree(), name="t",
                       axis_sizes={"model": 3})
    assert "axis-divisibility" in rules_of(pos, only_gating=True)
    f = next(f for f in pos if f.rule == "axis-divisibility")
    assert "attn/wq$" in f.message and "'model'" in f.message
    # Tuple entries multiply the axis sizes.
    pos = sl.lint_plan([("tok_emb$", P(("data", "model"), None))],
                       _tree(), name="t",
                       axis_sizes={"data": 3, "model": 2})
    assert "axis-divisibility" in rules_of(pos)      # 64 % 6 != 0
    # Undeclared axes — and axis_sizes=None entirely — skip the check.
    assert "axis-divisibility" not in rules_of(sl.lint_plan(
        rules, _tree(), name="t", axis_sizes={"data": 3}))
    assert "axis-divisibility" not in rules_of(sl.lint_plan(
        rules, _tree(), name="t"))


def test_axis_divisibility_rank_overflow():
    fs = sl.lint_plan([("tok_emb$", P(None, None, "model"))], _tree(),
                      name="t", axis_sizes={"model": 2})
    f = next(f for f in fs if f.rule == "axis-divisibility")
    assert "rank" in f.message


def test_replicated_giant_threshold():
    tree = {"big": jax.ShapeDtypeStruct((1024, 1024), jnp.float32),
            "small": jax.ShapeDtypeStruct((4,), jnp.float32)}
    fs = sl.lint_plan([("nothing", P())], tree, name="t",
                      giant_bytes=1 << 20)
    giants = [f for f in fs if f.rule == "replicated-giant"]
    assert len(giants) == 1 and "big" in giants[0].message
    # A rule claiming the leaf silences it; so does a catch-all.
    assert not [f for f in sl.lint_plan([("big", P("data", None)),
                                         ("nothing2", P())],
                                        tree, name="t")
                if f.rule == "replicated-giant"]


def test_replicated_giant_respects_fsdp_axis():
    """A plan with fsdp_axis scatters unmatched leaves too
    (ShardingPlan.spec_for augments the P() fallback), so a big
    unmatched-but-divisible leaf must NOT warn; one FSDP declines
    (no divisible dim) still does."""
    from distkeras_tpu.parallel.sharding import ShardingPlan

    plan = ShardingPlan(rules=[("nothing", P())], fsdp_axis="data")
    sharded = {"big": jax.ShapeDtypeStruct((1024, 1024), jnp.float32)}
    fs = sl.lint_plan(plan, sharded, name="t", axis_sizes={"data": 8})
    assert "replicated-giant" not in rules_of(fs)
    # Undeclared axis size: replication is unprovable — no warn either.
    fs = sl.lint_plan(plan, sharded, name="t")
    assert "replicated-giant" not in rules_of(fs)
    # Indivisible everywhere: FSDP declines, the leaf really replicates.
    odd = {"big": jax.ShapeDtypeStruct((1023, 1023), jnp.float32)}
    fs = sl.lint_plan(plan, odd, name="t", axis_sizes={"data": 8})
    assert "replicated-giant" in rules_of(fs)
    # Same tree without fsdp_axis warns as before.
    fs = sl.lint_plan([("nothing", P())], sharded, name="t",
                      axis_sizes={"data": 8})
    assert "replicated-giant" in rules_of(fs)


def test_callable_rules_evaluated_and_namedsharding_specs():
    """The real ZeRO rule list shape: a shape-keyed callable ahead of a
    concrete catch-all, NamedSharding values — the lint evaluates the
    callable and reads the spec out of the sharding for divisibility."""
    mesh = make_mesh(MeshSpec())
    tree = {"view": jax.ShapeDtypeStruct((8, 6), jnp.float32),
            "count": jax.ShapeDtypeStruct((), jnp.int32)}
    sh = NamedSharding(mesh, P("data", None))

    def view_rule(name, leaf):
        return sh if getattr(leaf, "shape", ()) == (8, 6) else None

    rules = [(".*", view_rule), (".*", NamedSharding(mesh, P()))]
    assert not [f for f in sl.lint_plan(rules, tree, name="t",
                                        axis_sizes={"data": 8})
                if f.gating]
    # A view shape the axis cannot split is caught through the
    # callable's returned sharding.
    bad = {"view": jax.ShapeDtypeStruct((6, 6), jnp.float32)}

    def bad_rule(name, leaf):
        return sh if getattr(leaf, "shape", ()) == (6, 6) else None

    fs = sl.lint_plan([(".*", bad_rule), (".*", P())], bad, name="t",
                      axis_sizes={"data": 8})
    assert "axis-divisibility" in rules_of(fs)


# ------------------------------------- compile_rules / UnmatchedLeaf


def test_compile_rules_rejects_concrete_duplicate():
    with pytest.raises(ValueError, match="duplicate pattern"):
        pr.compile_rules([("a$", P()), ("a$", P("data"))])
    # The decline-chain idiom (callable first) stays legal — this is
    # exactly zero_state_rules' construction.
    pr.compile_rules([(".*", lambda n, l: None), (".*", P())])


def test_unmatched_leaf_error_lists_nearest_misses():
    tree = {"layers": {"attn": {"wq": jnp.ones((4, 4))}}}
    with pytest.raises(pr.UnmatchedLeafError) as ei:
        pr.match_partition_rules(
            [("atn/wq$", P()), ("ffn/w1$", P()), ("emb$", P())], tree)
    msg = str(ei.value)
    assert "nearest-miss" in msg
    # The typo'd pattern ranks first: its literal spine matches the
    # deepest prefix of the leaf path.
    near = msg.split("nearest-miss patterns")[1]
    assert near.index("atn/wq$") < near.index("emb$")


# ------------------------------------------- resharding attribution


def test_attribution_scopes_and_tails():
    # Declared scopes and explicit collective primitives attribute.
    assert sl.attributed("jit(f)/zero3/param_gather/concatenate")
    assert sl.attributed("jit(f)/exchange/merge/jit(shmap_body)/all_gather")
    assert sl.attributed("jit(f)/myscope/sharding_constraint")
    assert sl.attributed("jit(f)/jit(shmap_body)/psum")
    # GSPMD-inserted reshardings carry the consumer op: unattributed.
    assert not sl.attributed("jit(f)/jit(main)/dot_general")
    assert not sl.attributed("jit(f)/jit(main)/broadcast_in_dim")
    assert not sl.attributed("")


_SYNTH_HLO = """\
ENTRY %main (a: f32[8]) -> f32[8] {
  %a = f32[8]{0} parameter(0)
  %all-gather = f32[8]{0} all-gather(f32[8]{0} %a), metadata={op_name="jit(f)/jit(main)/mul"}
  %all-gather.1 = f32[8]{0} all-gather(f32[8]{0} %a), metadata={op_name="jit(f)/zero1/all_gather/jit(shmap_body)/all_gather"}
  %cp = f32[8]{0} collective-permute(f32[8]{0} %a), metadata={op_name="jit(f)/jit(main)/pad"}
  ROOT %r = f32[8]{0} add(f32[8]{0} %all-gather, f32[8]{0} %cp)
}
"""


def test_resharding_census_parses_and_attributes():
    census = sl.resharding_census(_SYNTH_HLO)
    assert [(r["op"], r["attributed"]) for r in census] == [
        ("all-gather", False), ("all-gather", True),
        ("collective-permute", False)]
    spec = TraceSpec(name="t", fn=None, args=())
    fs = sl.reshard_findings(spec, _SYNTH_HLO)
    assert len(fs) == 2 and all(
        f.rule == "resharding-collective" and f.severity == "warn"
        and f.gating for f in fs)


def test_dropped_sharding_constraint_detected():
    """The injected regression leg: the SAME program with and without
    its with_sharding_constraint.  Constrained, the resulting
    all-gather's name stack carries `sharding_constraint` (attributed,
    no finding); dropped, GSPMD inserts the gather against the
    consumer op and the gate flags it."""
    mesh = make_mesh(MeshSpec(data=4, model=2))
    w_sh = NamedSharding(mesh, P(None, "model"))
    rep = NamedSharding(mesh, P())

    def constrained(w, x):
        w = jax.lax.with_sharding_constraint(w, rep)
        return x @ w

    def dropped(w, x):
        return x @ w

    args = (jax.ShapeDtypeStruct((16, 32), jnp.float32),
            jax.ShapeDtypeStruct((8, 16), jnp.float32))
    for fn, expect in ((constrained, 0), (dropped, 1)):
        jitted = jax.jit(fn, in_shardings=(w_sh, rep),
                         out_shardings=rep)
        spec = TraceSpec(name="synthetic/drop_wsc", fn=jitted,
                         args=args)
        art = trace_target(spec)
        fs = sl.reshard_findings(spec, art.hlo)
        gating = [f for f in fs if f.gating]
        assert len(gating) == (0 if expect == 0 else len(gating))
        if expect:
            assert gating and any("all-gather" in f.message
                                  for f in gating), [f.format()
                                                     for f in fs]
        else:
            assert not gating, [f.format() for f in fs]


# --------------------------------------------------- placement census


def test_placement_census_args_consts_and_bytes():
    mesh = make_mesh(MeshSpec(data=4, model=2))
    w = jax.device_put(jnp.ones((16, 32)),
                       NamedSharding(mesh, P(None, "model")))

    def fn(batch):
        return {"out": batch["x"] @ w}

    jitted = jax.jit(
        fn, in_shardings=({"x": NamedSharding(mesh, P("data", None))},),
        out_shardings={"out": NamedSharding(mesh, P())})
    spec = TraceSpec(
        name="t", fn=jitted,
        args=({"x": jax.ShapeDtypeStruct((8, 16), jnp.float32)},))
    art = trace_target(spec)
    census = sl.placement_census(spec, art)
    t = census["tensors"]
    assert t["args/0/x"] == ["f32[8,16]", "P('data', None)",
                             8 * 16 * 4 // 4]
    # The closed-over weight: named const/<i>, sharded bytes 1/2.
    consts = {k: v for k, v in t.items() if k.startswith("const/")}
    assert list(consts.values()) == [
        ["f32[16,32]", "P(None, 'model')", 16 * 32 * 4 // 2]]
    assert census["bytes_per_device"] == 8 * 16 * 4 // 4 + 16 * 32 * 2
    assert census["bytes_global"] == 8 * 16 * 4 + 16 * 32 * 4
    # The census also pins the attribution counts: this toy program's
    # sharded operands gather for the replicated output with no
    # declared scope, and the ledger records that.
    assert census["resharding"]["unattributed"] >= 1


def test_check_shard_budget_positive_and_negative():
    entry = {"tensors": {"args/x": ["f32[4]", "P()", 16]},
             "bytes_global": 16, "bytes_per_device": 16,
             "resharding": {"attributed": 0, "unattributed": 0}}
    assert sl.check_shard_budget("t", entry, {"t": entry}) == []
    missing = sl.check_shard_budget("other", entry, {"t": entry})
    assert [f for f in missing if f.rule == "shard-budget" and f.gating]
    import copy

    drifted = copy.deepcopy(entry)
    drifted["tensors"]["args/x"][1] = "P('data')"
    drifted["tensors"]["args/x"][2] = 2
    bad = sl.check_shard_budget("t", drifted, {"t": entry})
    assert [f for f in bad if f.rule == "shard-budget" and f.gating]
    assert "args/x" in bad[0].message


# ------------------------------------------- the shipped-plan matrix


def test_repo_plan_matrix_names_every_shipped_constructor():
    names = {name for name, *_ in sl.plan_suite()}
    assert names >= {"serving_plan", "tp_rules", "fsdp_plan+tp_rules",
                     "zero1_plan/state_rules", "zero3_plan/state_rules",
                     "exchange_codec_rules"}


def test_repo_plans_run_clean():
    """The dry-run matrix: no shipped plan constructor carries a dead,
    shadowed, duplicate, or indivisible rule against the real
    ADAG/LM/serving trees — and a future model change that strands a
    rule fails here."""
    findings = sl.lint_repo_plans()
    gating = [f.format() for f in findings if f.gating]
    assert not gating, gating


def test_repo_plan_matrix_catches_injected_regressions():
    """A stranded (dead) rule and a newly-shadowing rule in the
    serving plan are both caught by the same lint the matrix runs."""
    from distkeras_tpu.analysis.targets import _lm_cfg
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.sharding import serving_plan

    cfg = _lm_cfg()
    tree = jax.eval_shape(
        lambda: tfm.init_params(jax.random.key(0), cfg))
    axes = {"data": 4, "model": 2}
    # Injected typo: the extra rule places nothing.
    fs = sl.lint_plan(serving_plan(extra_rules=[("atn/wq$", P())]),
                      tree, name="t", axis_sizes=axes)
    assert "dead-rule" in rules_of(fs, only_gating=True)
    # Injected shadow: a broad extra rule starves the shipped ones.
    fs = sl.lint_plan(serving_plan(
        extra_rules=[("attn/.*", P(None, None, "model", None))]),
        tree, name="t", axis_sizes=axes)
    assert "shadowed-rule" in rules_of(fs, only_gating=True)
    # Injected indivisibility: n_heads=2 cannot split 4 ways.
    fs = sl.lint_plan(serving_plan(), tree, name="t",
                      axis_sizes={"data": 1, "model": 4})
    assert "axis-divisibility" in rules_of(fs, only_gating=True)


# --------------------------------------------------- CLI mode flags


@pytest.mark.parametrize("argv,needle", [
    (["--shardings", "--source-only"], "cannot combine"),
    (["--shardings", "--ir-only"], "cannot combine"),
    (["--shardings", "--threads"], "cannot combine"),
    (["--shardings", "--update-budgets"], "both census files"),
    (["--shardings", "--update-baseline"], "full run"),
    # The symmetric pre-existing gap, closed alongside: a source-only
    # run never reaches run_ir, so a budget re-record would exit 0
    # having written nothing.
    (["--source-only", "--update-budgets"], "needs the IR pass"),
])
def test_graph_lint_cli_rejects_shardings_combos(argv, needle):
    """PR-9 gave --threads conflicting-combo rejection before the
    heavy import; --shardings gets the same parity (these subprocesses
    exit at argparse, in well under a second of work)."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "graph_lint.py")]
        + argv, capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode != 0 and needle in r.stderr, r.stderr
