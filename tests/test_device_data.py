"""Device-resident datasets, on-device preprocessing, and DeviceFeed —
the input-pipeline pieces that keep the host->device link off the
critical path (SURVEY.md §7.3 #4)."""

import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.data.prefetch import DeviceFeed
from helpers import make_blobs, make_mlp


def _dataset(blobs):
    feats, labels = blobs
    return dk.Dataset({"features": feats, "label": labels})


def test_device_data_matches_streaming(blobs):
    ds = _dataset(blobs)

    def run(**kw):
        t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                             worker_optimizer="sgd", learning_rate=0.05,
                             batch_size=16, num_epoch=2, steps_per_call=4,
                             **kw)
        t.train(ds)
        return t.history

    np.testing.assert_allclose(run(device_data=True), run(), rtol=1e-6)


def test_device_data_single_step_per_call(blobs):
    ds = _dataset(blobs)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         worker_optimizer="sgd", learning_rate=0.05,
                         batch_size=16, num_epoch=2, device_data=True)
    t.train(ds)
    assert t.history[-1] < t.history[0]


def test_device_data_checkpoint_resume(blobs, tmp_path):
    ds = _dataset(blobs)
    d = str(tmp_path / "ck")

    def make(num_epoch, **kw):
        return dk.SingleTrainer(
            make_mlp(), loss="sparse_categorical_crossentropy",
            worker_optimizer="sgd", learning_rate=0.05, batch_size=16,
            num_epoch=num_epoch, steps_per_call=4, device_data=True,
            checkpoint_dir=d, checkpoint_every=1, **kw)

    full = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                            worker_optimizer="sgd", learning_rate=0.05,
                            batch_size=16, num_epoch=2, steps_per_call=4,
                            device_data=True)
    full.train(ds)
    make(1).train(ds)
    resumed = make(2, resume=True)
    resumed.train(ds)
    n_first = len(full.history) // 2
    np.testing.assert_allclose(resumed.history, full.history[n_first:],
                               rtol=1e-6)


def test_preprocess_u8_matches_f32(blobs):
    """uint8 wire dtype + on-device normalize == host-normalized f32."""
    feats, labels = blobs
    # Quantize features to u8 so both paths see identical values.
    lo, hi = feats.min(), feats.max()
    q = np.round((feats - lo) / (hi - lo) * 255).astype(np.uint8)
    f32 = q.astype(np.float32) / 255.0

    def run(data, preprocess=None):
        from distkeras_tpu.models.adapter import ModelAdapter

        ad = ModelAdapter(make_mlp(), loss="sparse_categorical_crossentropy",
                          optimizer="sgd", learning_rate=0.05,
                          preprocess=preprocess)
        state = ad.init_state()
        step = ad.make_train_step()
        import jax

        jstep = jax.jit(step, donate_argnums=0)
        losses = []
        for i in range(0, 128, 16):
            state, loss = jstep(state, data[i:i + 16], labels[i:i + 16])
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(
        run(q, preprocess=lambda x: x.astype(jnp.float32) / 255.0),
        run(f32), rtol=1e-5)


def test_trainer_preprocess_passthrough(blobs):
    """SingleTrainer(preprocess=...) + device_data trains uint8 data
    identically to host-normalized f32 data."""
    feats, labels = blobs
    lo, hi = feats.min(), feats.max()
    q = np.round((feats - lo) / (hi - lo) * 255).astype(np.uint8)

    def run(data, preprocess=None):
        t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                             worker_optimizer="sgd", learning_rate=0.05,
                             batch_size=16, num_epoch=2, steps_per_call=4,
                             device_data=True, preprocess=preprocess)
        t.train(dk.Dataset({"features": data, "label": labels}))
        return t.history

    np.testing.assert_allclose(
        run(q, preprocess=lambda x: x.astype(jnp.float32) / 255.0),
        run(q.astype(np.float32) / 255.0), rtol=1e-5)


def test_stateless_apply_uses_preprocess(blobs):
    from distkeras_tpu.models.adapter import ModelAdapter

    feats, _ = blobs
    ad = ModelAdapter(make_mlp(), preprocess=lambda x: x * 0.5)
    plain = ModelAdapter(make_mlp(), )
    st = ad.init_state()
    out, _ = ad.stateless_apply(st.tv, st.ntv, feats[:8])
    ref, _ = plain.stateless_apply(st.tv, st.ntv, feats[:8] * 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_device_feed_order_and_depth(blobs):
    items = [(np.full((2, 2), i, np.float32), np.full((2,), i, np.int32))
             for i in range(7)]
    out = list(DeviceFeed(iter(items), depth=3))
    assert len(out) == 7
    for i, (x, y) in enumerate(out):
        assert float(np.asarray(x)[0, 0]) == i
        assert int(np.asarray(y)[0]) == i


def test_device_feed_rejects_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        DeviceFeed([], depth=0)


def test_adag_device_data_matches_streaming(devices, rng):
    """ADAG(device_data=True): rows gathered on device from the staged
    dataset produce EXACTLY the streaming path's weights and losses
    (same rows, same order, same accum step)."""
    import distkeras_tpu as dk

    X = rng.normal(0, 1, (256, 12)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 256)]
    ds = dk.Dataset({"features": X, "label": Y})

    def build():
        import keras

        m = keras.Sequential([keras.Input((12,)),
                              keras.layers.Dense(16, activation="relu"),
                              keras.layers.Dense(4)])
        return m

    kw = dict(loss="categorical_crossentropy", worker_optimizer="adam",
              learning_rate=1e-2, batch_size=8, num_epoch=2,
              communication_window=4, num_workers=8)
    m_ref, m_dev = build(), build()
    m_dev.set_weights(m_ref.get_weights())   # identical inits
    ref = dk.ADAG(m_ref, **kw)
    wref = ref.train(ds).get_weights()
    dev = dk.ADAG(m_dev, device_data=True, **kw)
    wdev = dev.train(ds).get_weights()
    assert len(ref.history) == len(dev.history) > 0
    np.testing.assert_allclose(ref.history, dev.history, rtol=1e-5,
                               atol=1e-6)
    for a, b in zip(wref, wdev):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    # The replica family accepts the knob too (round-4 verdict weak 5);
    # parity is covered by test_replica_device_data_matches_streaming.


@pytest.mark.parametrize("cls_kw", [
    ("AEASGD", dict(learning_rate=0.05, rho=1.0, communication_window=4)),
    ("DOWNPOUR", dict(learning_rate=0.05, communication_window=4)),
    ("EnsembleTrainer", dict(learning_rate=0.05, num_models=8, seed=3)),
], ids=lambda c: c[0])
def test_replica_device_data_matches_streaming(blobs, cls_kw):
    """device_data=True on the replica family reproduces the streaming
    run exactly: the staged per-replica streams + in-round gather feed
    the identical scan+sync the same rows in the same order."""
    name, kw = cls_kw
    cls = getattr(dk, name)
    ds = _dataset(blobs)

    def run(**extra):
        t = cls(make_mlp(), loss="sparse_categorical_crossentropy",
                batch_size=8, num_epoch=2, **kw, **extra)
        t.train(ds)
        return t.history

    np.testing.assert_allclose(run(device_data=True), run(), rtol=1e-6)
