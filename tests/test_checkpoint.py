"""Checkpoint/resume (SURVEY.md §5: capability the reference lacks).

Resume correctness is tested as *bit-for-bit determinism*: training N
epochs straight through must equal training 1 epoch, checkpointing, and
resuming for the remaining epochs from disk.
"""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.checkpoint import CheckpointManager

from conftest import make_blobs, make_mlp


def _weights(model):
    return [np.asarray(w) for w in model.get_weights()]


def test_manager_roundtrip(tmp_path):
    import jax.numpy as jnp

    state = {"a": jnp.arange(12.0).reshape(3, 4), "b": [jnp.zeros(5)],
             "step": jnp.asarray(7, jnp.int32)}
    with CheckpointManager(str(tmp_path / "ckpt")) as mngr:
        assert mngr.latest_step() is None
        mngr.save(state, step=3)
        mngr.wait_until_finished()
        assert mngr.latest_step() == 3
        template = {"a": jnp.zeros((3, 4)), "b": [jnp.ones(5)],
                    "step": jnp.asarray(0, jnp.int32)}
        out = mngr.restore(template)
    np.testing.assert_array_equal(out["a"], state["a"])
    np.testing.assert_array_equal(out["b"][0], state["b"][0])
    assert int(out["step"]) == 7


def test_manager_missing_raises(tmp_path):
    with CheckpointManager(str(tmp_path / "empty")) as mngr:
        with pytest.raises(FileNotFoundError):
            mngr.restore({"x": np.zeros(2)})


def test_manager_max_to_keep(tmp_path):
    import jax.numpy as jnp

    with CheckpointManager(str(tmp_path / "k"), max_to_keep=2) as mngr:
        for s in (1, 2, 3):
            mngr.save({"v": jnp.asarray(float(s))}, step=s, force=True)
        mngr.wait_until_finished()
        assert mngr.all_steps() == [2, 3]


@pytest.mark.parametrize("trainer_cls,kw", [
    (dk.SingleTrainer, {}),
    (dk.ADAG, {"communication_window": 2, "num_workers": 4}),
    (dk.AEASGD, {"communication_window": 2, "num_workers": 4}),
])
def test_resume_matches_straight_run(tmp_path, trainer_cls, kw):
    x, y = make_blobs(n=256)
    ds = dk.Dataset.from_arrays(x, y)
    common = dict(loss="sparse_categorical_crossentropy",
                  worker_optimizer="sgd", learning_rate=0.05, batch_size=16)

    straight = trainer_cls(make_mlp(), num_epoch=2, **common, **kw)
    ref = straight.train(ds)

    d = str(tmp_path / "ckpt")
    first = trainer_cls(make_mlp(), num_epoch=1, checkpoint_dir=d,
                        **common, **kw)
    first.train(ds)
    resumed = trainer_cls(make_mlp(), num_epoch=2, checkpoint_dir=d,
                          resume=True, **common, **kw)
    out = resumed.train(ds)

    for wr, wo in zip(_weights(ref), _weights(out)):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    # The resumed run only executed epoch 2's rounds.
    assert len(resumed.history) == len(straight.history) - len(first.history)


def test_resume_past_end_returns_trained_model(tmp_path):
    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    d = str(tmp_path / "ckpt")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16,
                  learning_rate=0.05)
    t1 = dk.SingleTrainer(make_mlp(), num_epoch=1, checkpoint_dir=d, **common)
    ref = t1.train(ds)
    t2 = dk.SingleTrainer(make_mlp(), num_epoch=1, checkpoint_dir=d,
                          resume=True, **common)
    out = t2.train(ds)  # nothing left to train; must not raise
    for wr, wo in zip(_weights(ref), _weights(out)):
        np.testing.assert_allclose(wr, wo, rtol=1e-6)


def test_final_round_collides_with_periodic(tmp_path):
    # checkpoint_every divides the round count: the final save must not
    # re-save the same step (orbax raises StepAlreadyExists otherwise).
    x, y = make_blobs(n=64)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         batch_size=16, num_epoch=1,
                         checkpoint_dir=str(tmp_path / "c"), checkpoint_every=4)
    t.train(ds)  # 4 rounds; round 4 is both periodic and final


def test_resume_with_unseeded_shuffle_rejected(tmp_path):
    with pytest.raises(ValueError, match="seed"):
        dk.SingleTrainer(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                         resume=True, shuffle=True)


def test_resume_without_dir_rejected():
    with pytest.raises(ValueError, match="checkpoint_dir"):
        dk.SingleTrainer(make_mlp(), resume=True)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        dk.SingleTrainer(make_mlp(), checkpoint_every=5)


def test_retrain_into_populated_dir_fails_fast(tmp_path):
    x, y = make_blobs(n=64)
    ds = dk.Dataset.from_arrays(x, y)
    d = str(tmp_path / "c")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16)
    dk.SingleTrainer(make_mlp(), checkpoint_dir=d, **common).train(ds)
    with pytest.raises(ValueError, match="resume=True"):
        dk.SingleTrainer(make_mlp(), checkpoint_dir=d, **common).train(ds)


def test_periodic_checkpoints_written(tmp_path):
    x, y = make_blobs(n=256)
    ds = dk.Dataset.from_arrays(x, y)
    d = str(tmp_path / "ckpt")
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         batch_size=16, num_epoch=1, checkpoint_dir=d,
                         checkpoint_every=5, max_checkpoints=100)
    t.train(ds)
    with CheckpointManager(d) as mngr:
        steps = mngr.all_steps()
    assert steps == [5, 10, 15, 16]  # every 5 rounds + final (16 rounds)
