"""Checkpoint/resume (SURVEY.md §5: capability the reference lacks).

Resume correctness is tested as *bit-for-bit determinism*: training N
epochs straight through must equal training 1 epoch, checkpointing, and
resuming for the remaining epochs from disk.
"""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.checkpoint import CheckpointManager

from conftest import make_blobs, make_mlp


def _weights(model):
    return [np.asarray(w) for w in model.get_weights()]


def test_manager_roundtrip(tmp_path):
    import jax.numpy as jnp

    state = {"a": jnp.arange(12.0).reshape(3, 4), "b": [jnp.zeros(5)],
             "step": jnp.asarray(7, jnp.int32)}
    with CheckpointManager(str(tmp_path / "ckpt")) as mngr:
        assert mngr.latest_step() is None
        mngr.save(state, step=3)
        mngr.wait_until_finished()
        assert mngr.latest_step() == 3
        template = {"a": jnp.zeros((3, 4)), "b": [jnp.ones(5)],
                    "step": jnp.asarray(0, jnp.int32)}
        out = mngr.restore(template)
    np.testing.assert_array_equal(out["a"], state["a"])
    np.testing.assert_array_equal(out["b"][0], state["b"][0])
    assert int(out["step"]) == 7


def test_manager_missing_raises(tmp_path):
    with CheckpointManager(str(tmp_path / "empty")) as mngr:
        with pytest.raises(FileNotFoundError):
            mngr.restore({"x": np.zeros(2)})


def test_manager_max_to_keep(tmp_path):
    import jax.numpy as jnp

    with CheckpointManager(str(tmp_path / "k"), max_to_keep=2) as mngr:
        for s in (1, 2, 3):
            mngr.save({"v": jnp.asarray(float(s))}, step=s, force=True)
        mngr.wait_until_finished()
        assert mngr.all_steps() == [2, 3]


@pytest.mark.parametrize("trainer_cls,kw", [
    (dk.SingleTrainer, {}),
    (dk.ADAG, {"communication_window": 2, "num_workers": 4}),
    (dk.AEASGD, {"communication_window": 2, "num_workers": 4}),
])
def test_resume_matches_straight_run(tmp_path, trainer_cls, kw):
    x, y = make_blobs(n=256)
    ds = dk.Dataset.from_arrays(x, y)
    common = dict(loss="sparse_categorical_crossentropy",
                  worker_optimizer="sgd", learning_rate=0.05, batch_size=16)

    straight = trainer_cls(make_mlp(), num_epoch=2, **common, **kw)
    ref = straight.train(ds)

    d = str(tmp_path / "ckpt")
    first = trainer_cls(make_mlp(), num_epoch=1, checkpoint_dir=d,
                        **common, **kw)
    first.train(ds)
    resumed = trainer_cls(make_mlp(), num_epoch=2, checkpoint_dir=d,
                          resume=True, **common, **kw)
    out = resumed.train(ds)

    for wr, wo in zip(_weights(ref), _weights(out)):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    # The resumed run only executed epoch 2's rounds.
    assert len(resumed.history) == len(straight.history) - len(first.history)


def test_resume_past_end_returns_trained_model(tmp_path):
    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    d = str(tmp_path / "ckpt")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16,
                  learning_rate=0.05)
    t1 = dk.SingleTrainer(make_mlp(), num_epoch=1, checkpoint_dir=d, **common)
    ref = t1.train(ds)
    t2 = dk.SingleTrainer(make_mlp(), num_epoch=1, checkpoint_dir=d,
                          resume=True, **common)
    out = t2.train(ds)  # nothing left to train; must not raise
    for wr, wo in zip(_weights(ref), _weights(out)):
        np.testing.assert_allclose(wr, wo, rtol=1e-6)


def test_final_round_collides_with_periodic(tmp_path):
    # checkpoint_every divides the round count: the final save must not
    # re-save the same step (orbax raises StepAlreadyExists otherwise).
    x, y = make_blobs(n=64)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         batch_size=16, num_epoch=1,
                         checkpoint_dir=str(tmp_path / "c"), checkpoint_every=4)
    t.train(ds)  # 4 rounds; round 4 is both periodic and final


def test_resume_with_unseeded_shuffle_rejected(tmp_path):
    with pytest.raises(ValueError, match="seed"):
        dk.SingleTrainer(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                         resume=True, shuffle=True)


def test_resume_without_dir_rejected():
    with pytest.raises(ValueError, match="checkpoint_dir"):
        dk.SingleTrainer(make_mlp(), resume=True)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        dk.SingleTrainer(make_mlp(), checkpoint_every=5)


def test_retrain_into_populated_dir_fails_fast(tmp_path):
    x, y = make_blobs(n=64)
    ds = dk.Dataset.from_arrays(x, y)
    d = str(tmp_path / "c")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16)
    dk.SingleTrainer(make_mlp(), checkpoint_dir=d, **common).train(ds)
    with pytest.raises(ValueError, match="resume=True"):
        dk.SingleTrainer(make_mlp(), checkpoint_dir=d, **common).train(ds)


def test_periodic_checkpoints_written(tmp_path):
    x, y = make_blobs(n=256)
    ds = dk.Dataset.from_arrays(x, y)
    d = str(tmp_path / "ckpt")
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         batch_size=16, num_epoch=1, checkpoint_dir=d,
                         checkpoint_every=5, max_checkpoints=100)
    t.train(ds)
    with CheckpointManager(d) as mngr:
        steps = mngr.all_steps()
    assert steps == [5, 10, 15, 16]  # every 5 rounds + final (16 rounds)


CRASH_CHILD = """
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
from helpers import make_blobs, make_mlp
import distkeras_tpu as dk

x, y = make_blobs(n=128)
ds = dk.Dataset.from_arrays(x, y)
t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                     worker_optimizer="sgd", learning_rate=0.05,
                     batch_size=16, num_epoch=100,
                     checkpoint_dir={ckdir!r}, checkpoint_every=1,
                     max_checkpoints=3)
t.train(ds)
print("CHILD FINISHED")  # the parent kills us long before this
"""


def _committed_steps(ckdir):
    import os

    if not os.path.isdir(ckdir):
        return []
    return sorted(int(d) for d in os.listdir(ckdir) if d.isdigit())


def test_sigkill_midrun_then_resume_matches_straight(tmp_path):
    """The SURVEY §5 failure story: durability comes from
    checkpoint/restart.  A training process is SIGKILLed mid-run (no
    cleanup, like a preemption); resuming from its checkpoints must land
    exactly where an uninterrupted run does."""
    import os
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo, "tests")
    ckdir = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # skip TPU-plugin init: faster
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    child = subprocess.Popen(
        [sys.executable, "-c",
         CRASH_CHILD.format(repo=repo, tests=tests, ckdir=ckdir)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if child.poll() is not None:
                out = child.stdout.read().decode(errors="replace")
                raise AssertionError(
                    f"child exited (rc={child.returncode}) before the kill "
                    f"— make the run longer.\n{out[-2000:]}")
            steps = _committed_steps(ckdir)
            if steps and steps[-1] >= 20:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint reached step 20 in time")
        child.send_signal(signal.SIGKILL)  # no atexit, no orbax cleanup
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=30)
    assert child.returncode == -signal.SIGKILL

    killed_at = _committed_steps(ckdir)[-1]
    assert 0 < killed_at < 800, "child was not killed mid-run"

    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    common = dict(loss="sparse_categorical_crossentropy",
                  worker_optimizer="sgd", learning_rate=0.05,
                  batch_size=16, num_epoch=100)
    ref = dk.SingleTrainer(make_mlp(), **common).train(ds)
    resumed = dk.SingleTrainer(make_mlp(), checkpoint_dir=ckdir, resume=True,
                               **common)
    out = resumed.train(ds)
    for wr, wo in zip(_weights(ref), _weights(out)):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    # The resume really started from the crash point, not from scratch.
    assert len(resumed.history) <= 800 - killed_at
