"""ZeRO stages 2/3 (docs/zero1.md) and the regex partition-rule engine
(parallel/rules.py): stage-2 sharded gradient accumulators and stage-3
gather-on-use parameters are math-identical to replicated DP on the
8-CPU mesh for both trainer families; stage-3 per-device
param+grad+opt bytes drop ~num_workers x (asserted from addressable
shards); the scattered state round-trips checkpoints and the
Supervisor's bit-for-bit resume; and the rule engine resolves
partition specs and per-bucket exchange codecs first-match-wins with
unmatched-leaf errors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel import collectives as cl
from distkeras_tpu.parallel import rules as pr
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.resilience import FaultPlan, Supervisor
from jax.sharding import NamedSharding, PartitionSpec as P


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)

# Same bound as tests/test_zero1.py: <= 1e-6 where reduction order
# legitimately differs, rtol on the well-scaled elements.
TOL = dict(rtol=2e-5, atol=1e-6)


def tokens(rng, n=64, s=16):
    return rng.integers(0, 64, (n, s + 1)).astype(np.int32)


def tree_close(a, b, **kw):
    kw = kw or TOL
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


# -------------------------------------------------------- rule engine


def test_match_partition_rules_first_match_wins():
    tree = {"tok_emb": jnp.ones((8, 4)),
            "layers": [{"wq": jnp.ones((4, 4)), "scale": jnp.ones((4,))}],
            "step": jnp.ones(())}
    specs = pr.match_partition_rules(
        [("emb", P("data", None)),
         (r"wq$", P(None, "model")),
         (r".*", P())], tree)
    assert specs["tok_emb"] == P("data", None)
    assert specs["layers"][0]["wq"] == P(None, "model")
    assert specs["layers"][0]["scale"] == P()
    # Scalars replicate even when an earlier rule would match them.
    specs2 = pr.match_partition_rules(
        [(r".*", P("data"))], {"s": jnp.ones(())})
    assert specs2["s"] == P()


def test_match_rules_unmatched_leaf_raises_naming_it():
    tree = {"layers": [{"wq": jnp.ones((4, 4))}], "tok_emb": jnp.ones((8,))}
    with pytest.raises(pr.UnmatchedLeafError, match="layers/0/wq"):
        pr.match_partition_rules([("emb", P())], tree)
    # Typos in patterns raise at compile, not mid-trace.
    with pytest.raises(Exception):
        pr.compile_rules([("([unclosed", P())])


def test_callable_rule_values_can_decline():
    calls = []

    def only_matrices(name, leaf):
        calls.append(name)
        return "mat" if len(leaf.shape) == 2 else None

    out = pr.match_rules([(r".*", only_matrices), (r".*", "other")],
                         {"w": jnp.ones((2, 2)), "b": jnp.ones((2,))})
    assert out == {"w": "mat", "b": "other"}


def test_zero_state_shardings_rules_match_legacy_rule(devices):
    """The rule-engine spelling reproduces the shape-keyed ZeRO state
    rule the plans used to hand-build."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    params = [jnp.ones((16, 8)), jnp.ones((24,))]
    opt = optax.adam(1e-3)
    layout = cl.Zero1Layout.for_tree(params, 8)
    state = jax.eval_shape(opt.init, layout.shard_views(params))
    sh = cl.zero1_state_shardings(params, state, mesh)
    for leaf, s in zip(jax.tree.leaves(state), jax.tree.leaves(sh)):
        want = (P("data", None) if tuple(leaf.shape) in layout.shard_shapes
                else P())
        assert s.spec == want, (leaf.shape, s.spec)


# ------------------------------------------------- ADAG stages 2 and 3


def _adag(blobs, **kw):
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    from helpers import make_mlp

    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", learning_rate=0.05,
                batch_size=8, num_epoch=2, communication_window=4, **kw)
    state = t._fit(ds)
    return t, state


def test_adag_zero2_matches_replicated(devices, blobs):
    base, s0 = _adag(blobs)
    z, s1 = _adag(blobs, zero=2)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(s1.tv, s0.tv)
    # The persistent optimizer state is the scattered view layout.
    for l in jax.tree.leaves(s1.opt_state):
        if hasattr(l, "addressable_shards") and l.ndim == 2:
            assert l.sharding.spec == P("data", None)


def test_adag_zero3_matches_replicated(devices, blobs):
    base, s0 = _adag(blobs)
    z, s1 = _adag(blobs, zero=3)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(z._zero_unview_state(s1).tv, s0.tv)


def test_adag_zero3_shards_param_and_opt_memory(devices, blobs):
    """Acceptance: stage-3 per-device params+opt bytes land ~n x below
    the replicated state, asserted from addressable shards (the
    transient in-scan grad accumulator is scattered by construction —
    the declared-exchange proof in test_budget_guards pins it)."""
    base, s0 = _adag(blobs)
    z, s1 = _adag(blobs, zero=3)

    def per_device(tree):
        return sum(l.addressable_shards[0].data.nbytes
                   for l in jax.tree.leaves(tree)
                   if hasattr(l, "addressable_shards"))

    rep = per_device([list(s0.tv), s0.opt_state])
    sharded = per_device([list(s1.tv), s1.opt_state])
    assert rep / sharded > 6.0, (rep, sharded)
    for l in jax.tree.leaves(list(s1.tv)):
        assert l.sharding.spec == P("data", None)
        assert l.addressable_shards[0].data.shape[0] == 1


def test_adag_zero_stages_device_data_match_streaming(devices, blobs):
    """The HBM-staged indexed data plane composes with stages 2 and 3:
    same math, same data order as streaming."""
    base, s0 = _adag(blobs)
    for stage in (2, 3):
        z, _ = _adag(blobs, zero=stage, device_data=True)
        np.testing.assert_allclose(z.history, base.history, **TOL)


@pytest.mark.chaos
def test_adag_zero3_supervisor_bit_for_bit(devices, tmp_path, blobs):
    """The resilience acceptance harness over the stage-3 path: an
    injected kill mid-run + Supervisor auto-resume reproduces the
    uninterrupted run's loss trajectory bit-for-bit — the scattered
    view params AND scattered optimizer state restore exactly."""
    from helpers import make_mlp

    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    kw = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="adam", learning_rate=0.05,
              batch_size=8, num_epoch=2, communication_window=4,
              zero=3)

    straight = dk.ADAG(make_mlp(), **kw)
    ref = straight.train(ds)

    t = dk.ADAG(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                checkpoint_every=1, checkpoint_backend="pickle", **kw)
    sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    with FaultPlan().fail("train.round", at=3):
        out = sup.run(ds)

    assert t.history == straight.history[2:]  # bit-for-bit
    for wr, wo in zip(ref.get_weights(), out.get_weights()):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    assert [a.outcome for a in sup.attempts] == ["fault", "ok"]


# --------------------------------------------------- LM stages 2 and 3


def _lm(mesh, rng, **kw):
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=2,
                     mesh=mesh, **kw)
    params = t.train(tokens(rng))
    return t, params


def test_lm_zero2_matches_dp(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base, p0 = _lm(mesh, np.random.default_rng(0))
    z, p1 = _lm(mesh, np.random.default_rng(0), zero=2)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)


def test_lm_zero3_matches_dp(devices):
    """Stage-3 parity AND layout: the trained tree comes back in
    parameter layout, while the persistent carry trained as scattered
    ``[n, cols]`` views."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base, p0 = _lm(mesh, np.random.default_rng(0))
    z, p1 = _lm(mesh, np.random.default_rng(0), zero=3)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0)):
        assert a.shape == b.shape


def test_lm_zero3_grad_accum_matches_dp(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base, p0 = _lm(mesh, np.random.default_rng(0), grad_accum=2)
    z, p1 = _lm(mesh, np.random.default_rng(0), grad_accum=2, zero=3)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)


def test_lm_zero3_clip_ema_matches_dp(devices):
    """The whole optax chain (global-norm clip + the EMA shadow) runs
    on shard views; ema_params comes back in parameter layout."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    kw = dict(grad_clip_norm=1.0, ema_decay=0.9)
    base, p0 = _lm(mesh, np.random.default_rng(0), **kw)
    z, p1 = _lm(mesh, np.random.default_rng(0), zero=3, **kw)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)
    tree_close(z.ema_params, base.ema_params)
    for a, b in zip(jax.tree.leaves(base.ema_params),
                    jax.tree.leaves(z.ema_params)):
        assert a.shape == b.shape


def test_lm_zero3_shards_param_grad_opt_memory(devices):
    """The acceptance criterion: per-device param+opt bytes of the
    stage-3 persistent state land ~n x (8-way mesh) below the
    replicated layout, measured from addressable shards built exactly
    the way train() builds them."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, mesh=mesh,
                     zero=3)
    params = t.init_params()
    layout = t._layout()
    opt_shapes = jax.eval_shape(
        lambda p: t.optimizer.init(layout.shard_views(p)), params)
    v_struct = jax.eval_shape(layout.shard_views, params)
    psh, osh = t._state_shardings(v_struct, opt_shapes)
    opt_state = jax.jit(lambda p: t.optimizer.init(layout.shard_views(p)),
                        out_shardings=osh)(params)
    views = jax.jit(layout.shard_views, out_shardings=psh)(params)

    n_param_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
    per_dev = sum(l.addressable_shards[0].data.nbytes
                  for l in jax.tree.leaves((views, opt_state))
                  if hasattr(l, "addressable_shards"))
    # adamw: params + mu + nu ~= 3x params replicated; the scattered
    # state must land near 3x/8 (pad costs a little).
    assert per_dev < 3 * n_param_bytes / 6.0, (per_dev, n_param_bytes)
    for l in jax.tree.leaves(views):
        assert l.sharding.spec == P("data", None)


def test_lm_zero3_device_data_matches_streaming(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base, p0 = _lm(mesh, np.random.default_rng(0))
    z, p1 = _lm(mesh, np.random.default_rng(0), zero=3,
                device_data=True)
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(p1, p0)


def test_lm_zero3_eval_matches_dp(devices):
    """The eval plane gathers the views per chunk (never per step):
    eval_history is identical to the replicated run's."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    data = tokens(np.random.default_rng(0))
    ev = tokens(np.random.default_rng(1), n=32)

    def run(**kw):
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                         num_epoch=1, mesh=mesh, eval_every=2, **kw)
        t.train(data, eval_tokens=ev)
        return t

    base, z = run(), run(zero=3)
    assert [r for r, _ in z.eval_history] == [r for r, _ in
                                              base.eval_history]
    for (_, m1), (_, m0) in zip(z.eval_history, base.eval_history):
        np.testing.assert_allclose(m1["loss"], m0["loss"], **TOL)


@pytest.mark.parametrize("backend", ["pickle", "orbax"])
def test_lm_zero3_checkpoint_resume(devices, tmp_path, backend):
    """The stage-3 view state round-trips: gather-on-save for the
    pickle backend, shard-native for orbax; the resumed run continues
    the uninterrupted run's loss trajectory."""
    if backend == "orbax":
        pytest.importorskip("orbax.checkpoint")
    d = str(tmp_path / "ck")
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    data = tokens(np.random.default_rng(0))
    kw = dict(learning_rate=1e-2, batch_size=16, mesh=mesh, zero=3,
              checkpoint_backend=backend)
    full = dk.LMTrainer(CFG, num_epoch=2, **{k: v for k, v in kw.items()
                                             if k != "checkpoint_backend"})
    full.train(data)

    first = dk.LMTrainer(CFG, num_epoch=1, checkpoint_dir=d,
                         checkpoint_every=1, **kw)
    first.train(data)
    resumed = dk.LMTrainer(CFG, num_epoch=2, checkpoint_dir=d,
                           checkpoint_every=1, resume=True, **kw)
    p2 = resumed.train(data)
    np.testing.assert_allclose(
        resumed.history, full.history[len(first.history):], rtol=1e-5)
    jax.block_until_ready(jax.tree.leaves(p2)[0])


# ----------------------------------------------- per-bucket codec rules


def test_adag_codec_rules_converge_and_mix_codecs(devices, blobs):
    """compress=[(pattern, codec)] rules: the Keras trainer resolves
    them over its VARIABLE PATHS (kernels int8, biases top-k here),
    buckets stay codec-homogeneous, and training converges with the
    replicated baseline within the lowcomm tolerance."""
    base, s0 = _adag(blobs)
    z, s1 = _adag(blobs, compress=[(r"kernel$", "int8"),
                                   (r".*", "topk")])
    assert abs(z.history[-1] - base.history[-1]) < 0.2
    from distkeras_tpu.parallel.exchange import exchange_layout

    layout = exchange_layout(
        [jax.ShapeDtypeStruct(tuple(v.shape), np.dtype(v.dtype))
         for v in z.adapter.model.trainable_variables],
        8, z.exchange, names=z.adapter.tv_paths)
    assert set(layout.bucket_groups) == {"int8", "topk"}
    # Residual geometry: e1 per codec'd bucket, e2 ONLY for the int8
    # buckets — a top-k bucket must not persist a dead bucket-sized
    # f32 e2 slot in the optimizer state.
    from distkeras_tpu.parallel.exchange import ExchangeState

    ex_states = [l for l in jax.tree.leaves(
        s1.opt_state,
        is_leaf=lambda x: isinstance(x, ExchangeState))
        if isinstance(l, ExchangeState)]
    assert len(ex_states) == 1
    n_int8 = sum(1 for g in layout.bucket_groups if g == "int8")
    assert len(ex_states[0].e1) == len(layout.bucket_cols)
    assert len(ex_states[0].e2) == n_int8 < len(layout.bucket_cols)


def test_codec_rules_unmatched_leaf_raises(devices, blobs):
    from helpers import make_mlp

    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", batch_size=8,
                communication_window=4,
                compress=[(r"kernel$", "int8")])
    with pytest.raises(pr.UnmatchedLeafError, match="bias"):
        t._fit(ds)


def test_codec_rules_config_validation():
    from distkeras_tpu.parallel.exchange import ExchangeConfig

    with pytest.raises(ValueError, match="codec"):
        ExchangeConfig(compress=[("x", "gzip")])
    with pytest.raises(ValueError, match="ambiguous"):
        ExchangeConfig(compress=[])
    cfg = ExchangeConfig(compress=[("emb", "topk"), (".*", "int8")])
    assert cfg.label() == "rulesef"
    # Rules never compose with the ZeRO stages.
    with pytest.raises(ValueError, match="ZeRO"):
        dk.LMTrainer(CFG, zero=1,
                     compress=[("emb", "topk"), (".*", "int8")])


def test_lm_codec_rules_wire_geometry():
    """The analytic wire model accounts per bucket: the rules layout's
    wire bytes sit between uniform-int8 (all buckets compressed 4x)
    and uniform-topk."""
    from distkeras_tpu.parallel import exchange as ex

    params = jax.eval_shape(
        lambda: tfm.init_params(jax.random.key(0), CFG))
    n = 8
    rules_cfg = ex.ExchangeConfig(compress=(("emb", "topk"),
                                            (".*", "int8")))
    int8_cfg = ex.ExchangeConfig(compress="int8")
    lay_rules = ex.exchange_layout(params, n, rules_cfg)
    lay_int8 = ex.exchange_layout(params, n, int8_cfg)
    f32_r, wire_r = ex.wire_bytes(lay_rules, rules_cfg)
    f32_i, wire_i = ex.wire_bytes(lay_int8, int8_cfg)
    assert f32_r == f32_i            # same gradient volume
    assert 0 < wire_r < f32_r        # compressed overall
    assert wire_r != wire_i          # but not the uniform-int8 wire


# --------------------------------------------------- guards / wiring


def test_zero_flag_wiring_and_rejections(devices, blobs):
    from helpers import make_mlp

    # zero1=True is the alias of zero=1 and cannot contradict zero=.
    with pytest.raises(ValueError, match="alias"):
        dk.ADAG(make_mlp(), zero1=True, zero=2)
    with pytest.raises(ValueError, match="alias"):
        dk.LMTrainer(CFG, zero1=True, zero=3)
    with pytest.raises(ValueError, match="zero must be"):
        dk.ADAG(make_mlp(), zero=4)
    with pytest.raises(ValueError, match="only one of"):
        dk.ADAG(make_mlp(), zero=2, fsdp=True)
    with pytest.raises(ValueError, match="exclusive"):
        dk.LMTrainer(CFG, zero=3, fsdp=True)
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    with pytest.raises(ValueError, match="data axis only"):
        dk.LMTrainer(CFG, mesh=mesh, zero=2)
    with pytest.raises(ValueError, match="zero"):
        dk.AEASGD(make_mlp(), zero=2)
    with pytest.raises(ValueError, match="zero"):
        dk.LoRATrainer(CFG, base_params=tfm.init_params(
            jax.random.key(0), CFG), zero=3)
    with pytest.raises(ValueError, match="zero_bucket_mb"):
        dk.ADAG(make_mlp(), zero_bucket_mb=8.0)
    with pytest.raises(ValueError, match="only one of zero_bucket_mb"):
        dk.ADAG(make_mlp(), zero=2, zero_bucket_mb=8.0,
                zero1_bucket_mb=8.0)


def test_zero3_plan_spelling_matches_flag(devices, blobs):
    """plan=zero3_plan() is the explicit spelling of zero=3."""
    base, s0 = _adag(blobs)
    z, s1 = _adag(blobs, plan=dk.zero3_plan())
    assert z.zero == 3
    np.testing.assert_allclose(z.history, base.history, **TOL)
    tree_close(z._zero_unview_state(s1).tv, s0.tv)


def test_construction_rejects_non_elementwise_naming_offender(blobs):
    """Satellite: the elementwise check runs at construction for every
    stage and names the offending optax transform."""
    from helpers import make_mlp

    for stage in (1, 2, 3):
        with pytest.raises(ValueError, match="scale_by_trust_ratio"):
            dk.LMTrainer(CFG, optimizer=optax.lamb(1e-3), zero=stage)
    with pytest.raises(ValueError, match="scale_by_trust_ratio"):
        dk.ADAG(make_mlp(), worker_optimizer=optax.lars(1e-1), zero=2)


def test_construction_recognizes_prebuilt_elementwise_chains():
    """A prebuilt adam/adamw (or clip+adam chain) is now verified
    elementwise by closure inspection — no warning; a transform the
    inspector cannot attribute still warns."""
    import warnings

    from distkeras_tpu.ops.optimizers import (zero1_compatible,
                                              zero1_offender)

    assert zero1_compatible(optax.adam(1e-3)) is True
    assert zero1_compatible(
        optax.chain(optax.clip_by_global_norm(1.0),
                    optax.adamw(1e-3))) is True
    assert zero1_compatible(optax.lamb(1e-3)) is False
    assert zero1_offender(optax.lamb(1e-3)) == "scale_by_trust_ratio"
    opaque = optax.GradientTransformation(
        lambda p: (), lambda g, s, p=None: (g, s))
    assert zero1_compatible(opaque) is None
    # The recipe must never conclude "safe" AROUND an uninspectable
    # nested transform: a chain of recognized factories plus one
    # opaque member is uninspectable, not safe (and a known-bad
    # member nested next to opaque bits is still named).
    assert zero1_compatible(
        optax.chain(optax.scale(1.0), opaque)) is None
    mixed = optax.chain(opaque, optax.lamb(1e-3))
    assert zero1_compatible(mixed) is False
    assert zero1_offender(mixed) == "scale_by_trust_ratio"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dk.LMTrainer(CFG, optimizer=optax.adam(1e-3), zero=1)
        assert not [x for x in w if "elementwise" in str(x.message)]
    with pytest.warns(UserWarning, match="elementwise"):
        dk.LMTrainer(CFG, optimizer=opaque, zero=2)


def test_exports():
    assert dk.zero3_plan is not None
    assert dk.match_partition_rules is pr.match_partition_rules
    assert dk.rules is pr
    from distkeras_tpu.parallel import Zero3Plan, gather_bucket

    assert Zero3Plan is not None
    assert gather_bucket is cl.gather_bucket
