"""Sharded decode: generate/beam_search under TP and FSDP param layouts.

The serve-a-model-bigger-than-one-chip scenario (the LM analogue of the
reference's sharded batch inference, reference: distkeras/predictors.py
ModelPredictor): the KV-cached decode loop runs under jit on a mesh
with parameters TP-sharded (Megatron layout over the ``model`` axis) or
FSDP-scattered (over ``data``), and must emit exactly the tokens the
single-device decode emits.  Cache and intermediate shardings are
propagated by GSPMD from the parameter/batch layout — no decode-specific
sharding code exists, which is the property under test.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import beam_search, generate
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.sharding import ShardingPlan
from jax.sharding import NamedSharding, PartitionSpec as P


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)


def _prompt(rng, b=8, p=5):
    return jnp.asarray(rng.integers(1, CFG.vocab_size, (b, p)), jnp.int32)


def _tp_layout(devices, params):
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    plan = ShardingPlan(rules=tfm.tp_rules())
    psh = plan.tree_shardings(mesh, params)
    return mesh, psh


def _fsdp_layout(devices, params):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    plan = ShardingPlan(rules=(), fsdp_axis="data")
    psh = plan.tree_shardings(mesh, params)
    # The layout must actually scatter something, or the test is vacuous.
    emb_spec = tuple(psh["tok_emb"].spec)
    assert "data" in emb_spec, emb_spec
    return mesh, psh


def _sharded_generate(params, prompt, mesh, psh, **kw):
    params_sh = jax.device_put(params, psh)
    prompt_sh = jax.device_put(prompt, NamedSharding(mesh, P("data", None)))
    fn = jax.jit(lambda pr, t: generate(pr, t, CFG, 10, **kw),
                 in_shardings=(psh, NamedSharding(mesh, P("data", None))))
    return np.asarray(fn(params_sh, prompt_sh))


def _sharded_beam(params, prompt, mesh, psh, **kw):
    params_sh = jax.device_put(params, psh)
    prompt_sh = jax.device_put(prompt, NamedSharding(mesh, P("data", None)))
    fn = jax.jit(lambda pr, t: beam_search(pr, t, CFG, 8, beam_width=4, **kw),
                 in_shardings=(psh, NamedSharding(mesh, P("data", None))))
    seqs, scores = fn(params_sh, prompt_sh)
    return np.asarray(seqs), np.asarray(scores)


def test_generate_greedy_tp_sharded_matches_single(devices, rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = _prompt(rng)
    ref = np.asarray(generate(params, prompt, CFG, 10))
    mesh, psh = _tp_layout(devices, params)
    out = _sharded_generate(params, prompt, mesh, psh)
    np.testing.assert_array_equal(out, ref)


def test_generate_sampled_tp_sharded_matches_single(devices, rng):
    # Sampling draws through the position-keyed fold_in stream; the
    # sharded run must reproduce the same tokens (categorical over
    # near-identical logits with the identical key).
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = _prompt(rng)
    key = jax.random.key(7)
    kw = dict(temperature=0.8, key=key, top_k=20)
    ref = np.asarray(generate(params, prompt, CFG, 10, **kw))
    mesh, psh = _tp_layout(devices, params)
    out = _sharded_generate(params, prompt, mesh, psh, **kw)
    np.testing.assert_array_equal(out, ref)


def test_generate_greedy_fsdp_scattered_matches_single(devices, rng):
    params = tfm.init_params(jax.random.key(1), CFG)
    prompt = _prompt(rng)
    ref = np.asarray(generate(params, prompt, CFG, 10))
    mesh, psh = _fsdp_layout(devices, params)
    out = _sharded_generate(params, prompt, mesh, psh)
    np.testing.assert_array_equal(out, ref)


def test_beam_search_tp_sharded_matches_single(devices, rng):
    params = tfm.init_params(jax.random.key(2), CFG)
    prompt = _prompt(rng, b=4)
    ref_seqs, ref_scores = beam_search(params, prompt, CFG, 8, beam_width=4)
    mesh, psh = _tp_layout(devices, params)
    seqs, scores = _sharded_beam(params, prompt, mesh, psh)
    np.testing.assert_array_equal(seqs, np.asarray(ref_seqs))
    np.testing.assert_allclose(scores, np.asarray(ref_scores),
                               atol=1e-4, rtol=1e-4)


def test_beam_search_fsdp_scattered_matches_single(devices, rng):
    params = tfm.init_params(jax.random.key(3), CFG)
    prompt = _prompt(rng, b=8)  # data=8 mesh: batch divisible by 8
    ref_seqs, ref_scores = beam_search(params, prompt, CFG, 8, beam_width=4,
                                       eos_token=3)
    mesh, psh = _fsdp_layout(devices, params)
    seqs, scores = _sharded_beam(params, prompt, mesh, psh, eos_token=3)
    np.testing.assert_array_equal(seqs, np.asarray(ref_seqs))
    np.testing.assert_allclose(scores, np.asarray(ref_scores),
                               atol=1e-4, rtol=1e-4)


def test_speculative_tp_sharded_matches_single(devices, rng):
    """Speculative decoding under a TP mesh: target and draft params
    both Megatron-sharded, tokens equal to the unsharded speculative
    run (which itself equals generate's greedy rollout)."""
    from distkeras_tpu.models.speculative import speculative_generate

    d_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_len=32)
    params = tfm.init_params(jax.random.key(4), CFG)
    draft = tfm.init_params(jax.random.key(5), d_cfg)
    prompt = _prompt(rng, b=4, p=4)
    ref, _ = speculative_generate(params, draft, prompt, CFG, d_cfg, 9,
                                  n_draft=3)

    mesh, psh = _tp_layout(devices, params)
    dsh = ShardingPlan(rules=tfm.tp_rules()).tree_shardings(mesh, draft)
    tsh = NamedSharding(mesh, P("data", None))
    fn = jax.jit(
        lambda tp, dp, pr: speculative_generate(tp, dp, pr, CFG, d_cfg,
                                                9, n_draft=3)[0],
        in_shardings=(psh, dsh, tsh))
    out = fn(jax.device_put(params, psh), jax.device_put(draft, dsh),
             jax.device_put(prompt, tsh))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_prompt_cache_decode_under_tp(devices, rng):
    """Prefix-cache reuse composes with TP-sharded params: the prefix
    cache built by sharded prefill + the suffix chunked pass emit
    exactly the single-device concatenated-prompt tokens."""
    from distkeras_tpu.models.generate import prefill

    params = tfm.init_params(jax.random.key(0), CFG)
    prefix = _prompt(rng, b=8, p=4)
    tail = _prompt(rng, b=8, p=3)
    full = jnp.concatenate([prefix, tail], axis=1)
    ref = np.asarray(generate(params, full, CFG, 8))[:, 4:]

    mesh, psh = _tp_layout(devices, params)
    params_sh = jax.device_put(params, psh)
    dsh = NamedSharding(mesh, P("data", None))
    cache = jax.jit(
        lambda pr, t: prefill(pr, t, CFG, last_logits=False)[0],
        in_shardings=(psh, dsh))(params_sh, jax.device_put(prefix, dsh))
    out = jax.jit(
        lambda pr, t, c: generate(pr, t, CFG, 8, prompt_cache=(c, 4)),
        in_shardings=(psh, dsh, None))(
        params_sh, jax.device_put(tail, dsh), cache)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_continuous_batcher_under_tp(devices, rng):
    """The serving engine runs with Megatron-TP-sharded params on the
    mesh — lane state sharding propagates via GSPMD — and every request
    matches its solo single-device generate run."""
    from distkeras_tpu.serving import ContinuousBatcher

    params = tfm.init_params(jax.random.key(0), CFG)
    prompts = [_prompt(rng, b=1, p=4)[0], _prompt(rng, b=1, p=7)[0]]
    refs = [np.asarray(generate(params, p[None], CFG, 6))[0]
            for p in prompts]

    mesh, psh = _tp_layout(devices, params)
    params_sh = jax.device_put(params, psh)
    eng = ContinuousBatcher(params_sh, CFG, lanes=2)
    lanes = [eng.submit(np.asarray(p), 6) for p in prompts]
    while eng.running():
        eng.step(2)
    for lane, ref in zip(lanes, refs):
        np.testing.assert_array_equal(eng.drain(lane), ref)


def test_beam_prompt_cache_under_tp(devices, rng):
    """Beam search over a reused prefix with TP-sharded params matches
    the single-device concatenated-prompt beam run."""
    from distkeras_tpu.models.generate import prefill

    params = tfm.init_params(jax.random.key(0), CFG)
    prefix = _prompt(rng, b=4, p=4)
    tail = _prompt(rng, b=4, p=3)
    full = jnp.concatenate([prefix, tail], axis=1)
    ref_s, _ = beam_search(params, full, CFG, 6, beam_width=2)
    ref_s = np.asarray(ref_s)[:, :, 4:]

    mesh, psh = _tp_layout(devices, params)
    params_sh = jax.device_put(params, psh)
    dsh = NamedSharding(mesh, P("data", None))
    cache = jax.jit(
        lambda pr, t: prefill(pr, t, CFG, last_logits=False)[0],
        in_shardings=(psh, dsh))(params_sh, jax.device_put(prefix, dsh))
    out, _ = jax.jit(
        lambda pr, t, c: beam_search(pr, t, CFG, 6, beam_width=2,
                                     prompt_cache=(c, 4)),
        in_shardings=(psh, dsh, None))(
        params_sh, jax.device_put(tail, dsh), cache)
    np.testing.assert_array_equal(np.asarray(out), ref_s)
