"""Native data-loader kernels: parity with numpy + integration paths."""

import numpy as np
import pytest

from distkeras_tpu import native
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.prefetch import Prefetcher


def test_native_library_builds():
    """g++ is part of this environment; the library must build."""
    assert native.available(), "native dataloader failed to build/load"


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int64, np.uint8])
def test_gather_rows_matches_numpy(rng, dtype):
    src = (rng.normal(0, 100, (257, 5, 3))).astype(dtype)
    idx = rng.integers(0, 257, 123)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_rows_out_buffer(rng):
    src = rng.normal(size=(64, 8)).astype(np.float32)
    idx = rng.integers(0, 64, 32)
    out = np.empty((32, 8), np.float32)
    res = native.gather_rows(src, idx, out=out)
    assert res is out
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_bounds_checked(rng):
    src = np.zeros((8, 4), np.float32)
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([8]))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([-1]))


def test_gather_normalize_u8(rng):
    src = rng.integers(0, 256, (100, 32, 32, 3)).astype(np.uint8)
    idx = rng.integers(0, 100, 40)
    out = native.gather_normalize_u8(src, idx, scale=1 / 255.0, bias=-0.5)
    ref = src[idx].astype(np.float32) / 255.0 - 0.5
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_dataset_shuffle_uses_gather(rng):
    x = rng.normal(size=(100, 7)).astype(np.float32)
    y = rng.integers(0, 5, 100)
    ds = Dataset.from_arrays(x, y).shuffle(seed=3)
    # Same permutation across columns, content preserved.
    perm = np.random.default_rng(3).permutation(100)
    np.testing.assert_array_equal(ds["features"], x[perm])
    np.testing.assert_array_equal(ds["label"], y[perm])


def test_gather_rows_rejects_bad_out(rng):
    src = rng.normal(size=(16, 8)).astype(np.float32)
    idx = np.arange(4)
    with pytest.raises(ValueError, match="mismatch"):
        native.gather_rows(src, idx, out=np.empty((4, 8), np.float64))
    big = np.empty((4, 16), np.float32)
    with pytest.raises(ValueError, match="contiguous"):
        native.gather_rows(src, idx, out=big[:, ::2])


def test_prefetcher_order_and_completion():
    items = list(range(50))
    assert list(Prefetcher(iter(items), depth=4)) == items


def test_prefetcher_exhausted_raises_stopiteration_again():
    it = Prefetcher(iter([1, 2]))
    assert list(it) == [1, 2]
    with pytest.raises(StopIteration):
        next(it)
    with pytest.raises(StopIteration):  # and again, like any iterator
        next(it)


def test_prefetcher_close_unblocks_producer():
    it = Prefetcher(iter(range(10_000)), depth=2)
    assert next(it) == 0
    it.close()
    it._thread.join(timeout=5)
    assert not it._thread.is_alive()
    with pytest.raises(StopIteration):
        next(it)


def test_prefetcher_propagates_exception():
    def bad():
        yield 1
        raise RuntimeError("boom")

    it = Prefetcher(bad())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_batches_prefetch_matches_plain(rng):
    x = rng.normal(size=(96, 4)).astype(np.float32)
    y = rng.integers(0, 3, 96)
    ds = Dataset.from_arrays(x, y)
    plain = list(ds.batches(16, window=2))
    pre = list(ds.batches(16, window=2, prefetch=2))
    assert len(plain) == len(pre) == 3
    for (xa, ya), (xb, yb) in zip(plain, pre):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_gather_empty_index(rng):
    """Empty index must return an empty result on both paths (the native
    path used to crash on reshape(0, -1))."""
    src = rng.normal(size=(5, 3)).astype(np.float32)
    empty = np.zeros(0, np.int64)
    assert native.gather_rows(src, empty).shape == (0, 3)
    # Empty *source* too (Dataset.shuffle on an empty dataset).
    assert native.gather_rows(np.empty((0, 3), np.float32), empty).shape == (0, 3)
    u8 = rng.integers(0, 256, (5, 4, 2)).astype(np.uint8)
    out = native.gather_normalize_u8(u8, empty, scale=1 / 255.0)
    assert out.shape == (0, 4, 2) and out.dtype == np.float32


def test_prefetcher_close_wakes_blocked_consumer():
    """close() must wake a consumer already blocked in __next__ (the
    drain used to swallow the producer's _DONE sentinel)."""
    import threading
    import time

    release = threading.Event()

    def slow():
        yield 1
        release.wait(timeout=30)  # producer stalls; consumer blocks
        yield 2

    it = Prefetcher(slow(), depth=1)
    assert next(it) == 1
    outcome = []

    def consume():
        try:
            next(it)
            outcome.append("item")
        except StopIteration:
            outcome.append("stop")

    th = threading.Thread(target=consume)
    th.start()
    time.sleep(0.2)  # let the consumer block in the queue get
    it.close()
    th.join(timeout=5)
    release.set()
    assert not th.is_alive(), "consumer still blocked after close()"
    assert outcome == ["stop"]
