"""Round-14 pod-sharded serving: ``plan=``/``mesh=`` on both engine
families (ROADMAP item 1's second half — one router replica is a whole
mesh).

The acceptance contract: a sharded engine on the 8-CPU mesh emits
BIT-EXACT greedy and seeded-sampled tokens vs the solo engine, holds
~n× fewer param+KV bytes per device (asserted from addressable
shards, the ``zero=3`` accounting), publishes the SAME residency
digests (host-side content hashes — the router never sees the mesh),
and serves behind the Router like any other replica.  Invalid plans
are rejected at construction naming the offending rule.
"""

import jax
import numpy as np
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate, prefill
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.rules import kv_slab_specs, serving_kv_axis
from distkeras_tpu.parallel.sharding import fsdp_plan, serving_plan
from distkeras_tpu.serving import (ContinuousBatcher, InProcessReplica,
                                   PagedBatcher, PrefixPool, Router,
                                   SpeculativeBatcher)
from jax.sharding import PartitionSpec as P

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32, rope=True)
BLOCK = 8


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def tp2(devices):
    """(mesh, plan) for the standard data=4 x model=2 serving layout."""
    return make_mesh(MeshSpec(data=4, model=2), devices=devices), \
        serving_plan()


def _prompts(rng, lens=(5, 9)):
    return [rng.integers(0, 64, (n,)).astype(np.int32) for n in lens]


def _serve(eng, prompts, new, keys=None):
    lanes = [eng.submit(p, new, key=None if keys is None else keys[i])
             for i, p in enumerate(prompts)]
    while eng.running():
        eng.step()
    return [eng.drain(lane) for lane in lanes]


# ------------------------------------------------------------- parity


def test_sharded_cb_greedy_bit_exact(params, tp2, rng):
    mesh, plan = tp2
    prompts = _prompts(rng)
    refs = [np.asarray(generate(params, p[None], CFG, 6))[0]
            for p in prompts]
    eng = ContinuousBatcher(params, CFG, lanes=2, prompt_buckets=(8,),
                            plan=plan, mesh=mesh)
    assert eng._kv_axis == "model"
    for out, ref in zip(_serve(eng, prompts, 6), refs):
        np.testing.assert_array_equal(out, ref)


def test_sharded_cb_sampled_bit_exact(params, tp2, rng):
    mesh, plan = tp2
    prompts = _prompts(rng)
    keys = [jax.random.key(3), jax.random.key(4)]
    kw = dict(temperature=0.8, top_k=20)
    refs = [np.asarray(generate(params, p[None], CFG, 6, key=k, **kw))[0]
            for p, k in zip(prompts, keys)]
    eng = ContinuousBatcher(params, CFG, lanes=2, prompt_buckets=(8,),
                            plan=plan, mesh=mesh, **kw)
    for out, ref in zip(_serve(eng, prompts, 6, keys=keys), refs):
        np.testing.assert_array_equal(out, ref)


def _paged(params, plan=None, mesh=None, **kw):
    kw.setdefault("prompt_buckets", (8,))
    return PagedBatcher(params, CFG, lanes=2, block=BLOCK,
                        n_blocks=2 * (CFG.max_len // BLOCK) + 1,
                        plan=plan, mesh=mesh, **kw)


def test_sharded_paged_greedy_and_sampled_bit_exact(params, tp2, rng):
    mesh, plan = tp2
    prompts = _prompts(rng, lens=(6, 11))
    grefs = [np.asarray(generate(params, p[None], CFG, 6))[0]
             for p in prompts]
    eng = _paged(params, plan=plan, mesh=mesh)
    for out, ref in zip(_serve(eng, prompts, 6), grefs):
        np.testing.assert_array_equal(out, ref)

    keys = [jax.random.key(7), jax.random.key(8)]
    kw = dict(temperature=0.7, top_k=16)
    srefs = [np.asarray(generate(params, p[None], CFG, 5, key=k,
                                 **kw))[0]
             for p, k in zip(prompts, keys)]
    se = _paged(params, plan=plan, mesh=mesh, **kw)
    for out, ref in zip(_serve(se, prompts, 5, keys=keys), srefs):
        np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_sharded_chunked_prefill_bit_exact(params, tp2, rng):
    """Chunked admission under the sharded layout: the continuation
    programs land sharded chunks, the parked lane un-parks, tokens
    identical to monolithic admission AND to solo generate."""
    mesh, plan = tp2
    long_p = rng.integers(0, 64, (21,)).astype(np.int32)
    short = rng.integers(0, 64, (4,)).astype(np.int32)
    ref_long = np.asarray(generate(params, long_p[None], CFG, 4))[0]
    ref_short = np.asarray(generate(params, short[None], CFG, 8))[0]
    eng = ContinuousBatcher(params, CFG, lanes=2, prefill_chunk=8,
                            prompt_buckets=(8,), plan=plan, mesh=mesh)
    ls = eng.submit(short, 8)
    eng.step()
    ll = eng.submit(long_p, 4)
    while eng.running():
        eng.step()
    np.testing.assert_array_equal(eng.drain(ll), ref_long)
    np.testing.assert_array_equal(eng.drain(ls), ref_short)


def test_sharded_prefix_pool_bit_exact(params, tp2, rng):
    """Pool slab placed with the engine's KV sharding: the pooled
    gather is a sharded device gather, parity vs
    generate(prompt_cache=...) exact."""
    mesh, plan = tp2
    pool = PrefixPool(CFG, slots=2, mesh=mesh, kv_axis="model")
    pref = rng.integers(0, 64, (1, 6)).astype(np.int32)
    cache, _ = prefill(params, pref, CFG, last_logits=False)
    pid = pool.put(cache, 6)
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    ref = np.asarray(generate(params, tail[None], CFG, 4,
                              prompt_cache=(cache, 6)))[0]
    eng = ContinuousBatcher(params, CFG, lanes=2, prefix_pool=pool,
                            prompt_buckets=(8,), plan=plan, mesh=mesh)
    lane = eng.submit(tail, 4, prefix_id=pid)
    while eng.running():
        eng.step()
    np.testing.assert_array_equal(eng.drain(lane), ref)


def test_fsdp_plan_serves_with_replicated_cache(params, devices, rng):
    """A pure-FSDP plan (no attention-head rule) derives NO KV axis:
    params scatter gather-on-use, the cache replicates, and tokens
    stay bit-exact — the plan spelling training's fsdp=True uses."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    plan = fsdp_plan()
    assert serving_kv_axis(plan, mesh, CFG) is None
    prompts = _prompts(rng)
    refs = [np.asarray(generate(params, p[None], CFG, 5))[0]
            for p in prompts]
    eng = ContinuousBatcher(params, CFG, lanes=2, prompt_buckets=(8,),
                            plan=plan, mesh=mesh)
    for out, ref in zip(_serve(eng, prompts, 5), refs):
        np.testing.assert_array_equal(out, ref)


# ----------------------------------------------------- bytes + digest


def test_per_device_bytes_drop_with_axis(params, tp2):
    mesh, plan = tp2
    sharded = ContinuousBatcher(params, CFG, lanes=2,
                                prompt_buckets=(8,), plan=plan,
                                mesh=mesh)
    solo = ContinuousBatcher(params, CFG, lanes=2, prompt_buckets=(8,))
    fs, fo = sharded.memory_footprint(), solo.memory_footprint()
    # Totals agree; the per-device split is the claim.
    assert fs["param_bytes"] == fo["param_bytes"]
    assert fs["kv_bytes"] == fo["kv_bytes"]
    # KV heads shard exactly 2x; params ~2x (norm scales replicate).
    assert fs["kv_bytes_per_device"] * 2 == fo["kv_bytes_per_device"]
    assert fs["param_bytes_per_device"] < 0.6 * fo["param_bytes"]
    # Solo engine: one device holds everything.
    assert fo["param_bytes_per_device"] == fo["param_bytes"]


def test_paged_residency_digest_equal_sharded_vs_solo(params, tp2,
                                                      rng):
    """Residency is host-side content hashing: the sharded paged
    engine publishes exactly the digests its solo twin does for the
    same served prompts — to the router, a pod-sharded engine is ONE
    mesh-agnostic replica handle."""
    mesh, plan = tp2
    prompts = [np.concatenate([rng.integers(0, 64, (8,)),
                               rng.integers(0, 64, (4,))]).astype(
                                   np.int32)
               for _ in range(2)]
    sharded = _paged(params, plan=plan, mesh=mesh)
    solo = _paged(params)
    for eng in (sharded, solo):
        _serve(eng, prompts, 4)
    r_sh, r_solo = sharded.residency(), solo.residency()
    assert sorted(r_sh["stem_hashes"]) == sorted(r_solo["stem_hashes"])
    assert r_sh["block"] == r_solo["block"] == BLOCK
    assert r_sh["model_shards"] == 2 and r_solo["model_shards"] == 1


def test_router_over_one_sharded_replica(params, tp2, rng):
    """A pod-sharded engine behind the Router: enqueue/poll/drain
    through the fleet surface, results keyed to fleet-wide ids,
    tokens bit-exact vs solo generate."""
    mesh, plan = tp2
    eng = _paged(params, plan=plan, mesh=mesh, max_queue=8)
    router = Router([InProcessReplica("pod0", eng)])
    prompts = _prompts(rng, lens=(6, 10, 7))
    rids = [router.enqueue(p, 5) for p in prompts]
    while any(router.poll(r) is None for r in rids):
        router.step()
    for rid, p in zip(rids, prompts):
        res = router.take(rid)
        assert res.ok and res.request_id == rid
        solo = np.asarray(generate(params, p[None], CFG, 5))[0]
        np.testing.assert_array_equal(res.tokens, solo)
    assert router.replicas_up() == ["pod0"]


# ------------------------------------- elastic x plan (round 17)


def test_sharded_elastic_cb_scales_with_parity(params, tp2, rng):
    """lane_tiers= composes with plan= (round 17): sustained overflow
    steps a pod-sharded engine's tier up through the pre-compiled
    sharded resize gather, every request keeps exact solo parity, and
    the drained engine steps back down."""
    mesh, plan = tp2
    eng = ContinuousBatcher(params, CFG, lane_tiers=(1, 2), max_queue=1,
                            scale_up_after=1, scale_down_after=2,
                            prompt_buckets=(8,), plan=plan, mesh=mesh)
    assert eng.lanes == 1
    prompts = _prompts(rng, lens=(5, 9, 7))
    rids = [eng.enqueue(p, 5) for p in prompts]
    assert eng.lanes == 2, "sharded elastic engine did not scale up"
    while any(eng.poll(r) is None for r in rids):
        eng.step()
    for _ in range(4):
        eng.step()
    assert eng.lanes == 1, "idle sharded engine did not scale down"
    res = eng.shutdown()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            res[rid].tokens,
            np.asarray(generate(params, p[None], CFG, 5))[0])


def test_sharded_elastic_paged_rows_only_resize(params, tp2, rng):
    """Elastic paged x plan: a tier move gathers only row metadata —
    the sharded slab stays put and the page tables remap host-side,
    so requests decoding ACROSS the move keep exact parity and the
    allocator drains clean.  fork() is rejected (lane ids are not
    stable across a resize)."""
    mesh, plan = tp2
    eng = PagedBatcher(params, CFG, block=BLOCK, lane_tiers=(1, 2),
                       max_queue=1, scale_up_after=1,
                       scale_down_after=2, prompt_buckets=(8,),
                       plan=plan, mesh=mesh)
    with pytest.raises(ValueError, match="elastic"):
        eng.fork(0, 1)
    prompts = _prompts(rng, lens=(6, 10, 7))
    ra = eng.enqueue(prompts[0], 6)
    eng.step()                        # ra decodes at tier 1...
    rbs = [eng.enqueue(p, 6) for p in prompts[1:]]   # ...resize here
    assert eng.lanes == 2
    rids = [ra, *rbs]
    while any(eng.poll(r) is None for r in rids):
        eng.step()
    res = eng.shutdown()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            res[rid].tokens,
            np.asarray(generate(params, p[None], CFG, 6))[0])
    assert eng.allocator.stats()["used"] == 0


# ------------------------------------ speculative x plan (round 17)

SPEC_DRAFT = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                   n_heads=2, n_layers=1, d_ff=32,
                                   max_len=32, rope=True)


def test_sharded_speculative_greedy_parity(params, tp2, rng):
    """plan= on the speculative engine (round 17): target sharded,
    draft replicated — greedy output stays bit-exact vs the solo
    pinned contract (greedy speculative IS greedy generate)."""
    mesh, plan = tp2
    draft = tfm.init_params(jax.random.key(8), SPEC_DRAFT)
    eng = SpeculativeBatcher(params, draft, CFG, SPEC_DRAFT, lanes=2,
                             n_draft=3, prompt_buckets=(8,),
                             plan=plan, mesh=mesh)
    prompts = _prompts(rng, lens=(5, 9))
    lanes = [eng.submit(p, 8) for p in prompts]
    while eng.running():
        eng.step()
    for lane, p in zip(lanes, prompts):
        np.testing.assert_array_equal(
            eng.drain(lane),
            np.asarray(generate(params, p[None], CFG, 8))[0])


def test_sharded_speculative_rejections(params, tp2):
    mesh, plan = tp2
    draft = tfm.init_params(jax.random.key(8), SPEC_DRAFT)
    with pytest.raises(ValueError, match="plan= and mesh= together"):
        SpeculativeBatcher(params, draft, CFG, SPEC_DRAFT, plan=plan)
    pool = PrefixPool(CFG, slots=1, draft_cfg=SPEC_DRAFT)
    with pytest.raises(ValueError, match="prefix_pool"):
        SpeculativeBatcher(params, draft, CFG, SPEC_DRAFT,
                           prefix_pool=pool, plan=plan, mesh=mesh)


# --------------------------------------------------- rejection matrix


def test_rejection_matrix(params, tp2, devices):
    mesh, plan = tp2
    with pytest.raises(ValueError, match="plan= and mesh= together"):
        ContinuousBatcher(params, CFG, plan=plan)
    with pytest.raises(ValueError, match="plan= and mesh= together"):
        ContinuousBatcher(params, CFG, mesh=mesh)

    # Head count not divisible by the model axis: the error names the
    # offending RULE, not just the numbers (2 heads, model=4).
    mesh4 = make_mesh(MeshSpec(data=2, model=4), devices=devices)
    with pytest.raises(ValueError, match=r"attn/w\[qkv\]") as e:
        ContinuousBatcher(params, CFG, plan=plan, mesh=mesh4)
    assert "not divisible" in str(e.value)
    with pytest.raises(ValueError, match="not divisible"):
        PagedBatcher(params, CFG, block=BLOCK, plan=plan, mesh=mesh4)

    with pytest.raises(ValueError, match="prompt_cache"):
        ContinuousBatcher(params, CFG, plan=plan, mesh=mesh,
                          prompt_cache=(jax.tree.map(
                              lambda a: a, prefill(
                                  params, np.zeros((1, 4), np.int32),
                                  CFG, last_logits=False)[0]), 4))
    wcfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=32,
                                 rope=True, attention_window=16)
    with pytest.raises(ValueError, match="full-cache"):
        ContinuousBatcher(params, wcfg, plan=plan, mesh=mesh)
    # Pool placement must match the engine's.
    with pytest.raises(ValueError, match="prefix_pool placement"):
        ContinuousBatcher(params, CFG, prefix_pool=PrefixPool(
            CFG, slots=1), plan=plan, mesh=mesh)

    # A callable rule claiming an attention path cannot drive the KV
    # derivation — rejected loudly, not silently skipped (review fix).
    from distkeras_tpu.parallel.sharding import ShardingPlan
    cplan = ShardingPlan(rules=[(r"attn/w[qkv]$",
                                 lambda name, leaf: None)])
    with pytest.raises(ValueError, match="concrete PartitionSpecs"):
        serving_kv_axis(cplan, mesh, CFG)


def test_equal_mesh_from_separate_make_mesh_accepted(params, devices,
                                                     rng):
    """Pool/engine mesh matching is by EQUALITY, not identity: a pool
    built against its own (equal) make_mesh call serves fine.  (jax
    interns Mesh objects, so equal constructions may also be
    identical — the engine check uses `!=` so the contract holds
    either way.)"""
    mesh_a = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    mesh_b = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    assert mesh_a == mesh_b
    pool = PrefixPool(CFG, slots=1, mesh=mesh_a, kv_axis="model")
    pref = rng.integers(0, 64, (1, 6)).astype(np.int32)
    cache, _ = prefill(params, pref, CFG, last_logits=False)
    pid = pool.put(cache, 6)
    eng = ContinuousBatcher(params, CFG, lanes=2, prefix_pool=pool,
                            prompt_buckets=(8,), plan=serving_plan(),
                            mesh=mesh_b)
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    ref = np.asarray(generate(params, tail[None], CFG, 4,
                              prompt_cache=(cache, 6)))[0]
    lane = eng.submit(tail, 4, prefix_id=pid)
    while eng.running():
        eng.step()
    np.testing.assert_array_equal(eng.drain(lane), ref)


def test_kv_slab_specs_layouts():
    """The shared KV-spec rule covers every slab layout in the repo:
    monolithic cache, paged block slab, pool slab (leading slots
    axis), int8 scale leaves included — heads dim sharded, everything
    else replicated."""
    cache = {"k": np.zeros((2, 3, 8, 2, 4)),
             "k_scale": np.zeros((2, 3, 8, 2))}
    specs = kv_slab_specs(cache, "model")
    assert specs["k"] == P(None, None, None, "model")
    assert specs["k_scale"] == P(None, None, None, "model")
    pool = {"v": np.zeros((4, 2, 1, 8, 2, 4))}
    assert kv_slab_specs(pool, "model")["v"] == P(
        None, None, None, None, "model")
    assert kv_slab_specs(cache, None)["k"] == P()
