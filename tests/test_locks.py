"""The runtime lock-order sanitizer (utils/locks.py) and the
concurrency gate built on it.

Covers: the disabled fast path (the factories return RAW stdlib
locks — zero wrapper overhead), cycle / double-acquire /
callback-under-lock detection with acquisition stacks, the obs
held-time/contention histograms, the PR-8 SLO-subscriber deadlock as
a *detected* (not timed-out) regression, thread-leak hygiene around
``obs.session``, and the multi-threaded serving stress under the
sanitizer (enqueue vs step vs /metrics scrape vs SLO tick vs
``begin_shutdown``; the elastic-resize variant is slow-gated).

Positive tests that deliberately provoke violations are marked
``expected_lock_violations`` so conftest's gate (which fails any test
recording one) stands down.
"""

import threading
import time
import urllib.request

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.utils import locks
from distkeras_tpu.utils.locks import (LockOrderViolation, TracedLock,
                                        TracedRLock, assert_unlocked)


@pytest.fixture(autouse=True)
def _sanitizer_on():
    """These tests need the sanitizer regardless of how the suite was
    launched (conftest enables it via DKT_LOCK_SANITIZER, but the
    driver may override)."""
    was = locks.sanitizer_enabled()
    locks.enable_sanitizer()
    yield
    if not was:
        locks.disable_sanitizer()


# ------------------------------------------------------------ fast path


def test_disabled_factories_return_raw_stdlib_locks():
    """Sanitizer-off overhead is pinned at literally zero: the
    factories hand back the raw stdlib lock type, not a wrapper."""
    was = locks.sanitizer_enabled()
    locks.disable_sanitizer()
    try:
        assert type(TracedLock()) is type(threading.Lock())
        assert type(TracedRLock()) is type(threading.RLock())
        # And the guards are no-ops.
        assert_unlocked("anywhere")
        assert locks.violations() == []
        assert locks.lock_report()["enabled"] is False
    finally:
        if was:
            locks.enable_sanitizer()


def test_enabled_locks_are_drop_in():
    lk = TracedLock("t.dropin")
    assert lk.acquire() is True
    assert lk.locked()
    lk.release()
    assert not lk.locked()
    assert lk.acquire(False) is True
    # Contended try-acquire fails without blocking (from a thread: the
    # sanitizer correctly refuses same-thread re-acquire instead).
    got = []
    t = threading.Thread(target=lambda: got.append(lk.acquire(False)))
    t.start()
    t.join()
    assert got == [False]
    lk.release()
    rl = TracedRLock("t.dropin.r")
    with rl:
        with rl:  # reentrant nesting is legal
            assert rl._inner._is_owned()


# ------------------------------------------------------------ detection


@pytest.mark.expected_lock_violations
def test_lock_order_cycle_detected_with_both_stacks():
    a, b = TracedLock("t.a"), TracedLock("t.b")
    with a:
        with b:
            pass
    before = locks.violation_count()
    with pytest.raises(LockOrderViolation) as ei:
        with b:
            with a:
                pass
    assert ei.value.kind == "cycle"
    new = locks.violations()[before:]
    assert len(new) == 1 and new[0].kind == "cycle"
    # Both acquisition stacks are in the report: the current attempt
    # AND the recorded first-observed opposite edge.
    labels = [label for label, _ in new[0].stacks]
    assert any("now" in lab for lab in labels)
    assert any("recorded" in lab for lab in labels)
    assert all(frames for _, frames in new[0].stacks)


@pytest.mark.expected_lock_violations
def test_cycle_across_threads_detected():
    """The order graph is global: thread 1 takes a->b, thread 2
    taking b->a is an inversion even though nothing ever deadlocked."""
    a, b = TracedLock("t.x1"), TracedLock("t.x2")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with pytest.raises(LockOrderViolation):
        with b:
            with a:
                pass


@pytest.mark.expected_lock_violations
def test_double_acquire_raises_instead_of_deadlocking():
    lk = TracedLock("t.double")
    t0 = time.monotonic()
    with pytest.raises(LockOrderViolation) as ei:
        with lk:
            with lk:
                pass
    assert ei.value.kind == "double-acquire"
    assert time.monotonic() - t0 < 5.0, "sanitizer blocked instead of raising"
    assert not lk.locked(), "outer hold was not released on the raise"


def test_failed_or_bounded_tryacquire_records_no_edge():
    """The deadlock-AVOIDANCE idiom must not poison the order graph:
    holding A and try-acquiring B (failed OR successful, non-blocking
    or bounded) records no A->B edge and raises nothing — only an
    unbounded blocking acquire can deadlock, so only it
    participates."""
    a, b = TracedLock("t.try1"), TracedLock("t.try2")
    hold = threading.Event()
    release = threading.Event()

    def holder():
        with b:
            hold.set()
            release.wait(5.0)

    th = threading.Thread(target=holder, name="dkt-test-tryholder")
    th.start()
    hold.wait(5.0)
    with a:
        assert b.acquire(False) is False       # failed trylock: no edge
        assert b.acquire(True, 0.05) is False  # bounded wait: no edge
    release.set()
    th.join(5.0)
    with a:                                    # successful trylock:
        assert b.acquire(False) is True        # still no edge
        b.release()
    # The opposite blocking order is therefore NOT an inversion.
    before = locks.violation_count()
    with b:
        with a:
            pass
    assert locks.violation_count() == before


def test_rlock_reentry_and_consistent_nesting_are_clean():
    outer, inner = TracedRLock("t.outer"), TracedLock("t.inner")
    before = locks.violation_count()
    for _ in range(3):
        with outer:
            with outer:
                with inner:
                    pass
    assert locks.violation_count() == before
    rep = locks.lock_report()
    assert rep["enabled"] and rep["locks"] >= 2 and rep["edges"] >= 1


@pytest.mark.expected_lock_violations
def test_assert_unlocked_guard():
    lk = TracedLock("t.guard")
    assert_unlocked("free thread")  # nothing held: fine
    with pytest.raises(LockOrderViolation) as ei:
        with lk:
            assert_unlocked("toy fire site")
    assert ei.value.kind == "held-in-callback"
    assert "t.guard" in str(ei.value)


# --------------------------------------------------------- obs export


def test_lock_histograms_reach_obs_registry():
    lk = TracedLock("t.histo")
    evt = threading.Event()

    def holder():
        with lk:
            evt.set()
            time.sleep(0.05)

    with obs.session() as sess:
        with lk:
            pass
        th = threading.Thread(target=holder, name="dkt-test-holder")
        th.start()
        evt.wait(5.0)
        with lk:   # contended: the holder still sleeps under it
            pass
        th.join(5.0)
        snap = sess.registry.snapshot()
    held = snap.get("lock.held_s")
    assert held is not None and any(
        s["labels"].get("lock") == "t.histo" and s["count"] >= 2
        for s in held["series"])
    wait = snap.get("lock.wait_s")
    assert wait is not None and any(
        s["labels"].get("lock") == "t.histo" and s["count"] >= 1
        for s in wait["series"])


# ------------------------------------------- the PR-8 deadlock shape


class _BuggyTicker:
    """The pre-hardening PR-8 SloEngine shape, as a toy: subscribers
    fire INSIDE the engine lock, and a subscriber calls back into the
    locked query API."""

    def __init__(self):
        self._lock = TracedLock("toy.slo")
        self._subscribers = []

    def windowed(self):
        with self._lock:
            return 42

    def tick_buggy(self):
        with self._lock:
            for fn in list(self._subscribers):  # dkt: ignore[lock-callback]
                fn()


@pytest.mark.expected_lock_violations
def test_pr8_subscriber_under_lock_is_detected_not_hung():
    """The regression that motivated this gate: a subscriber calling
    ``windowed()`` from inside the tick lock used to deadlock the
    ticker until a human caught it in review.  Under the sanitizer the
    same shape is a *reported violation* at the re-acquire site — no
    timeout involved."""
    toy = _BuggyTicker()
    toy._subscribers.append(toy.windowed)
    t0 = time.monotonic()
    with pytest.raises(LockOrderViolation) as ei:
        toy.tick_buggy()
    assert ei.value.kind == "double-acquire"
    assert time.monotonic() - t0 < 5.0
    # And the guard at a fire site catches the same shape BEFORE the
    # callback even runs:
    with pytest.raises(LockOrderViolation):
        with toy._lock:
            assert_unlocked("toy subscriber fire")


def test_real_slo_engine_subscriber_calls_windowed_cleanly():
    """The FIXED production shape stays fixed: a subscriber that calls
    ``SloEngine.windowed()`` runs with the engine lock released —
    under the sanitizer (which would fail this test on any
    regression), the tick completes and the callback sees a value."""
    from distkeras_tpu.obs.metrics import MetricsRegistry
    from distkeras_tpu.obs.slo import SloEngine, SloRule

    t = [0.0]
    reg = MetricsRegistry()
    rule = SloRule("serving.request_s", percentile=0.99,
                   threshold=0.1, window_s=5.0)
    eng = SloEngine(reg, [rule], clock=lambda: t[0])
    seen = []
    eng.subscribe(lambda r, v: seen.append(
        eng.windowed(r.metric, r.percentile, r.window_s)))
    hist = reg.histogram("serving.request_s")
    eng.tick()
    t[0] = 1.0
    hist.observe(0.5)
    eng.tick()
    assert seen and seen[0] is not None and seen[0] > rule.threshold


# ------------------------------------------------- session thread hygiene


def test_obs_session_close_stops_live_plane_threads():
    """The PR-8 EADDRINUSE class: closing the session must leave no
    dkt-telemetry / dkt-slo-tick thread running (conftest asserts this
    for every test; this pins the contract explicitly)."""
    rule = obs.SloRule("serving.request_s", percentile=0.5,
                       threshold=1.0, window_s=5.0)
    with obs.session(serve_port=0, slo_rules=[rule]) as sess:
        url = sess.server.url
        urllib.request.urlopen(url + "/metrics", timeout=5).read()
        live = {t.name for t in threading.enumerate()}
        assert "dkt-telemetry" in live and "dkt-slo-tick" in live
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        left = {t.name for t in threading.enumerate()
                if t.is_alive()
                and t.name in ("dkt-telemetry", "dkt-slo-tick")}
        if not left:
            break
        time.sleep(0.02)
    assert not left, f"live-plane threads survived session close: {left}"


# ------------------------------------------------- serving stress


def _stress(eng, *, submitters: int, per_thread: int, url,
            slo, tick: bool):
    """Shared driver: N submitter threads race the stepper, a
    /metrics scraper, the SLO ticker, and finally begin_shutdown.
    Returns per-thread errors (must be empty)."""
    errors = []
    stop = threading.Event()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, (5,)).astype(np.int32)
               for _ in range(per_thread)]
    rids = [[] for _ in range(submitters)]

    def submit(i):
        from distkeras_tpu.serving import EngineClosed, QueueFull

        try:
            for p in prompts:
                while True:
                    try:
                        rids[i].append(eng.enqueue(p, 4))
                        break
                    except QueueFull:
                        time.sleep(0.001)
                    except EngineClosed:
                        return
        except Exception as e:  # noqa: BLE001 — reported by the test
            errors.append(("submit", repr(e)))

    def step():
        try:
            while not stop.is_set():
                eng.step()
        except Exception as e:  # noqa: BLE001
            errors.append(("step", repr(e)))

    def scrape():
        try:
            while not stop.is_set():
                urllib.request.urlopen(url + "/metrics",
                                       timeout=5).read()
                time.sleep(0.002)
        except Exception as e:  # noqa: BLE001
            errors.append(("scrape", repr(e)))

    def ticker():
        try:
            while not stop.is_set():
                slo.tick()
                time.sleep(0.002)
        except Exception as e:  # noqa: BLE001
            errors.append(("tick", repr(e)))

    threads = [threading.Thread(target=submit, args=(i,),
                                name=f"dkt-test-submit{i}")
               for i in range(submitters)]
    threads += [threading.Thread(target=step, name="dkt-test-step"),
                threading.Thread(target=scrape, name="dkt-test-scrape")]
    if tick:
        threads.append(threading.Thread(target=ticker,
                                        name="dkt-test-tick"))
    for t in threads:
        t.start()
    for t in threads[:submitters]:   # submitters drain first
        t.join(120)
    eng.begin_shutdown()             # races the live stepper on purpose
    stop.set()
    for t in threads[submitters:]:
        t.join(120)
    assert not any(t.is_alive() for t in threads)
    results = eng.shutdown(max_steps=500)
    all_rids = [r for rs in rids for r in rs]
    assert all_rids, "no request was ever admitted"
    return errors, all_rids, results


def _stress_cfg():
    from distkeras_tpu.models import transformer as tfm

    return tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=32,
                                 rope=True)


def test_concurrency_stress_bounded():
    """Fast-gate stress: 2 submitters vs the decode stepper vs a live
    /metrics scraper vs explicit SLO ticks vs ``begin_shutdown``, all
    under the sanitizer.  Every request reaches a terminal structured
    result, no thread dies, no violation is recorded (conftest's gate
    re-asserts that)."""
    import jax

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import ContinuousBatcher

    cfg = _stress_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = ContinuousBatcher(params, cfg, lanes=2, max_queue=4,
                            prompt_buckets=(8,))
    rule = obs.SloRule("serving.request_s", percentile=0.99,
                       threshold=60.0, window_s=10.0)
    with obs.session(serve_port=0, slo_rules=[rule]) as sess:
        errors, rids, results = _stress(
            eng, submitters=2, per_thread=6, url=sess.server.url,
            slo=sess.slo, tick=True)
    assert not errors, errors
    for r in rids:
        res = results.get(r) or eng.poll(r)
        assert res is not None, f"request {r} has no terminal result"
        assert res.status in ("ok", "timeout", "cancelled"), res


@pytest.mark.slow
def test_concurrency_stress_elastic_resize():
    """Slow-gate stress: the elastic engine adds tier resizes to the
    race — sustained QueueFull steps lanes up mid-flight while the
    scraper, ticker, and shutdown race on.  The resize compacts the
    lane table under the admission lock; the sanitizer watches every
    acquisition."""
    import jax

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import ContinuousBatcher

    cfg = _stress_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = ContinuousBatcher(params, cfg, lane_tiers=(1, 2), max_queue=2,
                            scale_up_after=1, scale_down_after=2,
                            prompt_buckets=(8,))
    rule = obs.SloRule("serving.request_s", percentile=0.99,
                       threshold=60.0, window_s=10.0)
    with obs.session(serve_port=0, slo_rules=[rule]) as sess:
        errors, rids, results = _stress(
            eng, submitters=4, per_thread=8, url=sess.server.url,
            slo=sess.slo, tick=True)
    assert not errors, errors
    assert eng.tier_epoch >= 1, "backpressure never stepped a tier"
    for r in rids:
        res = results.get(r) or eng.poll(r)
        assert res is not None and res.status in ("ok", "timeout",
                                                  "cancelled"), (r, res)
