// Native input-pipeline kernels for distkeras_tpu.
//
// The reference framework's data plane is Spark: partition iterators in
// JVM executors feed Python workers row by row (reference:
// distkeras/workers.py batching rows out of mapPartitions iterators).
// The TPU rebuild's data plane is host-local numpy columns; its hot
// path is forming shuffled batches — a strided gather — and converting
// uint8 image bytes to normalized float32.  Both are memory-bandwidth
// problems that single-threaded numpy leaves on the table, so they live
// here as a small C++ library driven over ctypes
// (distkeras_tpu/native/__init__.py), with numpy as the fallback when
// no compiler is present.
//
// Build: g++ -O3 -shared -fPIC -pthread dataloader.cc -o libdkt_data.so

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(first_row, last_row) over [0, n) split across n_threads.
template <typename F>
void parallel_rows(int64_t n, int n_threads, F fn) {
  if (n_threads <= 1 || n < 2 * n_threads) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

// dst[i, :] = src[idx[i], :] for float32 rows.
void dkt_gather_f32(const float* src, const int64_t* idx, float* dst,
                    int64_t n_out, int64_t row_elems, int n_threads) {
  parallel_rows(n_out, n_threads, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * row_elems, src + idx[i] * row_elems,
                  row_elems * sizeof(float));
    }
  });
}

// Generic byte-wise row gather (any fixed row size, any dtype).
void dkt_gather_bytes(const uint8_t* src, const int64_t* idx, uint8_t* dst,
                      int64_t n_out, int64_t row_bytes, int n_threads) {
  parallel_rows(n_out, n_threads, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
    }
  });
}

// dst[i, :] = float(src[idx[i], :]) * scale + bias — fused gather +
// uint8->f32 normalize (the CIFAR/ImageNet decode hot path).
void dkt_gather_u8_normalize(const uint8_t* src, const int64_t* idx,
                             float* dst, int64_t n_out, int64_t row_elems,
                             float scale, float bias, int n_threads) {
  parallel_rows(n_out, n_threads, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* s = src + idx[i] * row_elems;
      float* d = dst + i * row_elems;
      for (int64_t j = 0; j < row_elems; ++j) {
        d[j] = static_cast<float>(s[j]) * scale + bias;
      }
    }
  });
}

}  // extern "C"
