// Byte-level BPE tokenizer for distkeras_tpu's LM data path.
//
// The reference framework has no text tokenizer at all — its examples
// consume pre-vectorized Spark DataFrames (reference: workflow.ipynb
// feature columns).  The TPU rebuild's flagship is a causal LM, so the
// framework owes the text->tokens edge of the pipeline; it lives here
// as a small C++ library (ctypes-driven, numpy/python fallback in
// distkeras_tpu/data/tokenizer.py) because encoding is the CPU-hot
// part of any real text pipeline.
//
// Algorithm: byte-level BPE (GPT-2 family).  Base vocabulary is the
// 256 bytes; training greedily merges the most frequent adjacent pair
// for n_merges rounds; encoding applies merges in rank order via a
// linked-list + heap in O(len log len) — not the naive O(merges*len)
// rescan.
//
// Build: g++ -O3 -shared -fPIC -pthread tokenizer.cc -o libdkt_bpe.so

#include <cstdint>
#include <cstring>
#include <map>
#include <queue>
#include <utility>
#include <vector>

namespace {

using Pair = std::pair<int32_t, int32_t>;

// Count adjacent pairs in `toks`, return the most frequent (ties break
// toward the smaller pair for determinism).  Returns count 0 if empty.
int64_t most_frequent_pair(const std::vector<int32_t>& toks, Pair* best) {
  std::map<Pair, int64_t> counts;
  for (size_t i = 0; i + 1 < toks.size(); ++i) {
    ++counts[{toks[i], toks[i + 1]}];
  }
  int64_t best_count = 0;
  for (const auto& kv : counts) {
    if (kv.second > best_count) {
      best_count = kv.second;
      *best = kv.first;
    }
  }
  return best_count;
}

void merge_inplace(std::vector<int32_t>* toks, Pair pair, int32_t new_id) {
  size_t w = 0;
  for (size_t r = 0; r < toks->size(); ++r) {
    if (r + 1 < toks->size() && (*toks)[r] == pair.first &&
        (*toks)[r + 1] == pair.second) {
      (*toks)[w++] = new_id;
      ++r;
    } else {
      (*toks)[w++] = (*toks)[r];
    }
  }
  toks->resize(w);
}

}  // namespace

extern "C" {

// Learn `n_merges` byte-level BPE merges from `corpus`.
// out_merges: [n_merges * 2] int32 (left, right) in merge order; token
// id of merge i is 256 + i.  Returns the number of merges actually
// learned (< n_merges when the corpus runs out of repeated pairs).
int32_t dkt_bpe_train(const uint8_t* corpus, int64_t len, int32_t n_merges,
                      int32_t* out_merges) {
  std::vector<int32_t> toks(corpus, corpus + len);
  int32_t learned = 0;
  for (int32_t m = 0; m < n_merges; ++m) {
    Pair best;
    if (most_frequent_pair(toks, &best) < 2) break;  // nothing repeats
    out_merges[2 * m] = best.first;
    out_merges[2 * m + 1] = best.second;
    merge_inplace(&toks, best, 256 + m);
    ++learned;
  }
  return learned;
}

// Encode `text` with `n_merges` ranked merges. out: caller-allocated
// [len] int32 (worst case: no merge applies). Returns encoded length.
int64_t dkt_bpe_encode(const int32_t* merges, int32_t n_merges,
                       const uint8_t* text, int64_t len, int32_t* out) {
  if (len == 0) return 0;
  // rank lookup: pair -> (rank, new_id)
  std::map<Pair, std::pair<int32_t, int32_t>> rank;
  for (int32_t m = 0; m < n_merges; ++m) {
    rank[{merges[2 * m], merges[2 * m + 1]}] = {m, 256 + m};
  }
  // Doubly linked list over token slots.
  std::vector<int32_t> tok(text, text + len);
  std::vector<int64_t> prev(len), next(len);
  for (int64_t i = 0; i < len; ++i) {
    prev[i] = i - 1;
    next[i] = i + 1 < len ? i + 1 : -1;
  }
  std::vector<uint8_t> dead(len, 0);

  // Min-heap of (rank, left_pos); stale entries are skipped on pop by
  // re-checking that the pair at left_pos still matches the rank.
  using Item = std::pair<int32_t, int64_t>;
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> heap;
  auto push_pair = [&](int64_t i) {
    if (i < 0 || dead[i]) return;
    int64_t j = next[i];
    if (j < 0) return;
    auto it = rank.find({tok[i], tok[j]});
    if (it != rank.end()) heap.push({it->second.first, i});
  };
  for (int64_t i = 0; i + 1 < len; ++i) push_pair(i);

  while (!heap.empty()) {
    auto [r, i] = heap.top();
    heap.pop();
    if (dead[i]) continue;
    int64_t j = next[i];
    if (j < 0 || dead[j]) continue;
    auto it = rank.find({tok[i], tok[j]});
    if (it == rank.end() || it->second.first != r) continue;  // stale
    // Merge j into i.
    tok[i] = it->second.second;
    dead[j] = 1;
    next[i] = next[j];
    if (next[j] >= 0) prev[next[j]] = i;
    // New neighbours form new candidate pairs.
    push_pair(prev[i]);
    push_pair(i);
  }

  int64_t w = 0;
  for (int64_t i = 0; i >= 0; i = next[i]) {
    if (!dead[i]) out[w++] = tok[i];
  }
  return w;
}

// Decode `ids` back to bytes.  out: caller-allocated buffer of
// capacity `out_cap`; returns bytes written, or -1 if out_cap is too
// small or an id is out of range.
int64_t dkt_bpe_decode(const int32_t* merges, int32_t n_merges,
                       const int32_t* ids, int64_t n_ids, uint8_t* out,
                       int64_t out_cap) {
  // Expand each merge id to its byte string once, memoized bottom-up.
  std::vector<std::vector<uint8_t>> table(256 + n_merges);
  for (int32_t b = 0; b < 256; ++b) table[b] = {static_cast<uint8_t>(b)};
  for (int32_t m = 0; m < n_merges; ++m) {
    int32_t l = merges[2 * m], r = merges[2 * m + 1];
    if (l < 0 || l >= 256 + m || r < 0 || r >= 256 + m) return -1;
    table[256 + m] = table[l];
    table[256 + m].insert(table[256 + m].end(), table[r].begin(),
                          table[r].end());
  }
  int64_t w = 0;
  for (int64_t i = 0; i < n_ids; ++i) {
    int32_t id = ids[i];
    if (id < 0 || id >= 256 + n_merges) return -1;
    const auto& bytes = table[id];
    if (w + static_cast<int64_t>(bytes.size()) > out_cap) return -1;
    std::memcpy(out + w, bytes.data(), bytes.size());
    w += bytes.size();
  }
  return w;
}

}  // extern "C"
