"""Headline benchmark: CIFAR-CNN training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The north-star target (BASELINE.json) is >=10x samples/sec vs an
8-executor Spark CPU baseline on the CIFAR-10 small CNN.  The reference
publishes no numbers, so the baseline is the measured proxy from
scripts/measure_cpu_baseline.py: a single-process Keras
``train_on_batch`` CPU loop (the reference worker's exact hot path,
reference: distkeras/workers.py) x 8 executors, charging the reference
nothing for its parameter-server overhead.  Measured on this machine
2026-07-29: 267.1 samples/sec single-process -> 2137 samples/sec
8-executor proxy (see BASELINE.md).

Measurement methodology lives in ONE place — scripts/bench_suite.py
(bf16 policy, jitted donated-state step, device-resident data,
float(loss) barrier); this driver just wraps its cifar_cnn config with
the vs_baseline ratio.
"""

import json
import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "scripts"))

SPARK8_CPU_PROXY_SPS = 2137.0  # samples/sec; provenance in module docstring


def _probe_with_retries(attempts=3, probe_s=120, backoff_s=60):
    """Device probe that survives a FLAPPING tunnel.

    A hung backend init cannot be retried in-process (the second
    ``jax.devices()`` blocks on the first's init lock), so each attempt
    probes from a fresh subprocess; only after one succeeds does this
    process initialize its own backend.  Worst case ~(probe+backoff) x
    attempts, then the error line.  Returns the error string or None.
    """
    import time

    from distkeras_tpu.utils.misc import probe_device_count_subprocess

    err = "no probe attempt ran"
    for i in range(attempts):
        try:
            probe_device_count_subprocess(deadline_s=probe_s)
            return None
        except Exception as e:  # TimeoutError / RuntimeError from probe
            err = str(e)[:220]
        if i + 1 < attempts:
            time.sleep(backoff_s)
    return err


def main():
    # Fail loud, not hung: the relay's backend init can block forever
    # when the tunnel is down — record an error line instead of
    # stalling the driver's bench step (and give a flapping tunnel a
    # few minutes to come back before giving up).
    err = _probe_with_retries()
    if err is not None:
        # A dead accelerator tunnel is an ENVIRONMENT outage, not a
        # regression in this repo: emit a structured skip record and
        # exit 0 so the driver's bench step records "skipped" instead
        # of a failure (BENCH_r05: the rc=1 poisoned the whole run).
        # Keys keep the documented one-line contract; null value
        # signals "no measurement" to contract-parsing consumers, and
        # ``last_green`` carries the most recent PRIOR green
        # measurement (clearly labeled) so the artifact holds evidence
        # through the outage instead of only nulls while the real
        # numbers live in BASELINE.md prose.
        line = {"metric": "cifar_cnn_train_throughput",
                "value": None, "unit": "samples/sec/chip",
                "vs_baseline": None, "status": "skipped", "error": err}
        from bench_suite import read_last_green

        prior = read_last_green("cifar_cnn_train_throughput")
        if prior is not None:
            line["last_green"] = {
                "note": "prior green measurement, NOT this run", **prior}
        print(json.dumps(line))
        sys.exit(0)

    from bench_suite import bench_cifar_cnn, peak_flops, update_last_green

    sps, step_s, step_flops = bench_cifar_cnn()[:3]
    line = {
        "metric": "cifar_cnn_train_throughput",
        "value": round(sps, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / SPARK8_CPU_PROXY_SPS, 2),
    }
    peak = peak_flops()
    if peak and step_flops:
        line["mfu"] = round(step_flops / step_s / peak, 4)
    print(json.dumps(line))
    import jax

    if jax.default_backend() == "tpu":
        update_last_green(line, device=jax.devices()[0].device_kind)


if __name__ == "__main__":
    main()
