"""Headline benchmark: CIFAR-CNN training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The north-star target (BASELINE.json) is >=10x samples/sec vs an
8-executor Spark CPU baseline on the CIFAR-10 small CNN.  The reference
publishes no numbers, so the baseline is the measured proxy from
scripts/measure_cpu_baseline.py: a single-process Keras
``train_on_batch`` CPU loop (the reference worker's exact hot path,
reference: distkeras/workers.py) x 8 executors, charging the reference
nothing for its parameter-server overhead.  Measured on this machine
2026-07-29: 267.1 samples/sec single-process -> 2137 samples/sec
8-executor proxy (see BASELINE.md).

TPU-side setup: bf16 compute (MXU-native), batch 1024, jitted
train step with donated state, synthetic device-resident data so the
measurement is pure training throughput.
"""

import json
import os
import time

os.environ.setdefault("KERAS_BACKEND", "jax")

SPARK8_CPU_PROXY_SPS = 2137.0  # samples/sec; provenance in module docstring

BATCH = 1024
WARMUP = 10
ITERS = 300


def main():
    import jax
    import numpy as np
    import keras

    keras.mixed_precision.set_global_policy("mixed_bfloat16")

    from distkeras_tpu.models.adapter import ModelAdapter
    from distkeras_tpu.models.zoo import cifar_cnn

    model = cifar_cnn(seed=0)
    adapter = ModelAdapter(model, loss="sparse_categorical_crossentropy",
                           optimizer="sgd", learning_rate=0.01)
    state = adapter.init_state()
    step = jax.jit(adapter.make_train_step(), donate_argnums=0)

    rng = np.random.default_rng(0)
    x = jax.device_put(rng.normal(size=(BATCH, 32, 32, 3)).astype(np.float32))
    y = jax.device_put(rng.integers(0, 10, BATCH))

    for _ in range(WARMUP):
        state, loss = step(state, x, y)
    float(loss)  # device->host transfer: a true barrier (the axon
    # relay's block_until_ready returns before remote execution drains)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, loss = step(state, x, y)
    float(loss)  # barrier through the sequential state dependency chain
    dt = time.perf_counter() - t0

    sps = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "cifar_cnn_train_throughput",
        "value": round(sps, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / SPARK8_CPU_PROXY_SPS, 2),
    }))


if __name__ == "__main__":
    main()
