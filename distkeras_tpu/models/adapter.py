"""Stateless functional view of a Keras 3 model (JAX backend).

This is the L1' substrate from SURVEY.md §7.1.  The reference keeps a
*stateful* Keras model inside each Spark worker and mutates it with
``model.train_on_batch`` (reference: distkeras/workers.py).  On TPU the
idiomatic unit is a *pure function over pytrees*: we extract the model's
variables once, and every train/predict step is

    loss, (tv, ntv, opt_state) = step(tv, ntv, opt_state, batch)

built from ``model.stateless_call`` — fully traceable, so the whole
epoch compiles to one XLA program per shape, and ``jax.sharding``
annotations on the pytrees drive data/tensor parallelism with collectives
inserted by the compiler (this replaces the reference's
parameter-server pull/commit protocol, distkeras/parameter_servers.py).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.ops.losses import resolve_loss
from distkeras_tpu.ops.optimizers import resolve_optimizer
from distkeras_tpu.utils.serialization import (
    deserialize_keras_model,
    serialize_keras_model,
)


@flax.struct.dataclass
class TrainState:
    """Pure pytree holding everything a train step needs.

    ``tv``/``ntv`` are the trainable / non-trainable variable values, in
    the order Keras reports them.  ``opt_state`` is the optax state over
    ``tv``.  ``step`` is the global step counter (device scalar, so the
    whole state lives on-device between steps).
    """

    tv: Any
    ntv: Any
    opt_state: Any
    step: jnp.ndarray


class ModelAdapter:
    """Wraps a Keras 3 model into stateless apply / train-step builders.

    One adapter instance owns the (traced-once) Keras object; all actual
    compute flows through pure functions that close over the model's
    *structure* but take variables as explicit pytree arguments.
    """

    def __init__(self, keras_model, loss="categorical_crossentropy",
                 optimizer="sgd", learning_rate: float | None = None,
                 metrics: Sequence[str] = (),
                 preprocess: Callable | None = None):
        import keras  # deferred so KERAS_BACKEND is already forced

        if keras.backend.backend() != "jax":  # pragma: no cover
            raise RuntimeError(
                "distkeras_tpu requires the Keras JAX backend, but keras is "
                "running on %r. Import distkeras_tpu before keras, or set "
                "KERAS_BACKEND=jax." % keras.backend.backend())
        self.model = keras_model
        if not keras_model.built:
            raise ValueError(
                "Keras model must be built (call it once or pass an Input "
                "layer) before wrapping in ModelAdapter")
        self.loss_fn = resolve_loss(loss)
        self.optimizer = resolve_optimizer(optimizer, learning_rate)
        self.metrics = tuple(metrics)
        unknown = [m for m in self.metrics if m != "accuracy"]
        if unknown:  # fail at construction, not after a whole run
            raise ValueError(
                f"unknown metric(s) {unknown}; known: ['accuracy']")
        # On-device input transform, traced into every step/predict
        # program (e.g. ``lambda x: x.astype("float32") / 255``).  Lets
        # the host ship the smallest wire dtype — uint8 pixels are 4x
        # fewer h2d bytes than the normalized f32 — and XLA fuses the
        # expansion into the first consumer.  The reference normalizes
        # host-side in Spark transformers (reference:
        # distkeras/transformers.py MinMaxTransformer), which quadruples
        # its wire traffic; on TPU the link is the scarce resource.
        self.preprocess = preprocess
        # Variable paths, for sharding rules keyed on names.
        self.tv_paths = [v.path for v in keras_model.trainable_variables]
        self.ntv_paths = [v.path for v in keras_model.non_trainable_variables]

    # ---------------------------------------------------------------- state

    def init_state(self) -> TrainState:
        """Snapshot the Keras variables into a fresh TrainState.

        A real copy, not ``asarray``'s alias: the train loops donate
        their state buffers, so an aliasing snapshot would consume the
        Keras variables on the first step and a second ``train`` on the
        same trainer (the Supervisor's retry path) would read deleted
        arrays."""
        tv = [jnp.array(v.value, copy=True)
              for v in self.model.trainable_variables]
        ntv = [jnp.array(v.value, copy=True)
               for v in self.model.non_trainable_variables]
        return TrainState(
            tv=tv,
            ntv=ntv,
            opt_state=self.optimizer.init(tv),
            step=jnp.zeros((), jnp.int32),
        )

    def write_back(self, state: TrainState) -> None:
        """Copy trained values from a TrainState back into the Keras model."""
        for var, val in zip(self.model.trainable_variables, state.tv):
            var.assign(np.asarray(val))
        for var, val in zip(self.model.non_trainable_variables, state.ntv):
            var.assign(np.asarray(val))

    def export_model(self, state: TrainState):
        """Return a *new* Keras model holding the trained weights.

        Mirrors the reference trainers returning a fresh deserialized
        model to the driver (distkeras/trainers.py Trainer.train).

        When the adapter has a ``preprocess`` hook the exported Keras
        model does NOT contain it (it is a jax transform, not a layer):
        callers must apply the same transform to inputs — or predict
        through :meth:`make_predict_fn` / ModelPredictor built from
        this adapter, which do.  A warning marks the hazard.
        """
        if self.preprocess is not None:
            import warnings

            warnings.warn(
                "export_model: the trained weights expect inputs "
                "transformed by this adapter's preprocess hook, but the "
                "exported Keras model does not embed it. Apply the same "
                "transform before model.predict, or run inference "
                "through the adapter's predict fn.", UserWarning,
                stacklevel=2)
        self.write_back(state)
        return deserialize_keras_model(serialize_keras_model(self.model))

    # ---------------------------------------------------------------- fns

    def stateless_apply(self, tv, ntv, x, training: bool = False):
        """Pure forward pass: returns (outputs, updated_ntv)."""
        if self.preprocess is not None:
            x = self.preprocess(x)
        out, ntv2 = self.model.stateless_call(tv, ntv, x, training=training)
        return out, ntv2

    def make_loss_fn(self) -> Callable:
        """Pure ``f(tv, ntv, x, y) -> (loss, ntv')`` for value_and_grad.

        Rematerialization note: checkpointing this whole function would
        be a peak-memory no-op (the backward's recompute materializes
        every residual at once); useful remat needs sub-function
        granularity, which requires model structure — the functional
        transformer does it per block (models/transformer.py
        TransformerConfig.remat).
        """
        model, loss_fn, pre = self.model, self.loss_fn, self.preprocess

        def compute_loss(tv, ntv, x, y):
            if pre is not None:
                x = pre(x)
            preds, ntv2 = model.stateless_call(tv, ntv, x, training=True)
            return loss_fn(y, preds), ntv2

        return compute_loss

    def make_train_step(self) -> Callable:
        """Build ``step(state, x, y) -> (state', loss)`` (not yet jitted).

        The caller decides how to jit/shard it — SingleTrainer jits it
        plain; distributed trainers wrap it with shardings over a mesh.
        """
        compute_loss = self.make_loss_fn()
        optimizer = self.optimizer

        def train_step(state: TrainState, x, y):
            grad_fn = jax.value_and_grad(compute_loss, has_aux=True)
            (loss, ntv2), grads = grad_fn(state.tv, state.ntv, x, y)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.tv)
            tv = jax.tree.map(lambda p, u: p + u, state.tv, updates)
            return TrainState(tv=tv, ntv=ntv2, opt_state=opt_state,
                              step=state.step + 1), loss

        return train_step

    def make_accum_train_step(self, window: int,
                              value_and_grad: Callable | None = None,
                              grad_axis_size: int | None = None,
                              probe: bool = False) -> Callable:
        """Build a gradient-accumulation step over ``window`` microbatches.

        ``step(state, xs, ys)`` with ``xs: [window, B, ...]`` scans the
        microbatches, accumulating gradients, then applies one optimizer
        update on the mean gradient.  This is the synchronous semantics of
        the reference's ``communication_window`` commit cadence
        (distkeras/workers.py: workers accumulate for N batches then
        commit to the parameter server) — see SURVEY.md §7.4.

        ``value_and_grad`` (default ``jax.value_and_grad``) is the
        gradient-construction hook, same contract as the transformer's
        (models/transformer.make_train_step): it receives the loss fn
        and must return a ``(loss, aux), grads``-shaped callable.  The
        distributed trainers' gradient-exchange configurations pass a
        shard_map-local construction that returns STACKED per-replica
        gradients (leading axis ``grad_axis_size``) for the exchange
        optimizer to merge (parallel/exchange.py).

        ``probe=True``: the step returns ``(state, (loss, aux))`` with
        ``aux = {"grad_norm": ...}`` computed in-graph (the opt-in
        diagnostics probe; same program count — the trainers declare
        the compile-budget delta, which is zero extra programs).
        """
        compute_loss = self.make_loss_fn()
        optimizer = self.optimizer
        vag = (jax.value_and_grad if value_and_grad is None
               else value_and_grad)

        def train_step(state: TrainState, xs, ys):
            grad_fn = vag(compute_loss, has_aux=True)
            if grad_axis_size is None:
                zero = jax.tree.map(jnp.zeros_like, state.tv)
            else:
                zero = jax.tree.map(
                    lambda v: jnp.zeros((grad_axis_size,) + v.shape,
                                        v.dtype), state.tv)

            def micro(carry, batch):
                g_acc, ntv, loss_acc = carry
                x, y = batch
                (loss, ntv2), grads = grad_fn(state.tv, ntv, x, y)
                g_acc = jax.tree.map(jnp.add, g_acc, grads)
                return (g_acc, ntv2, loss_acc + loss), None

            (g_sum, ntv2, loss_sum), _ = jax.lax.scan(
                micro, (zero, state.ntv, jnp.zeros(())), (xs, ys))
            grads = jax.tree.map(lambda g: g / window, g_sum)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.tv)
            tv = jax.tree.map(lambda p, u: p + u, state.tv, updates)
            out_state = TrainState(tv=tv, ntv=ntv2, opt_state=opt_state,
                                   step=state.step + 1)
            loss = loss_sum / window
            if probe:
                import optax

                return out_state, (loss,
                                   {"grad_norm": optax.global_norm(grads)})
            return out_state, loss

        return train_step

    def zero_layout(self, n: int, bucket_mb: float | None = None):
        """The ZeRO fusion-bucket layout of this model's trainable
        variables (shapes/dtypes only — nothing materializes).  The ONE
        geometry the stage-2/3 step builders, the trainers' view
        conversion, and the sharding plans share, so an accumulator
        bucket and its optimizer-state mirror can never disagree."""
        from distkeras_tpu.parallel.collectives import (
            DEFAULT_BUCKET_MB, Zero1Layout)

        structs = [jax.ShapeDtypeStruct(tuple(v.shape), np.dtype(v.dtype))
                   for v in self.model.trainable_variables]
        return Zero1Layout.for_tree(
            structs, n,
            DEFAULT_BUCKET_MB if bucket_mb is None else bucket_mb)

    def make_zero_accum_step(self, window: int, mesh, inner,
                             stage: int, bucket_mb: float | None = None,
                             probe: bool = False) -> Callable:
        """The gradient-accumulation step for ZeRO stages 2 and 3
        (docs/zero1.md): same contract as :meth:`make_accum_train_step`
        — ``step(state, xs, ys)`` scanning ``window`` microbatches —
        but the gradient accumulator is the SCATTERED fusion-bucket
        layout: each microbatch's gradient is packed per bucket and
        reduce-scattered INTO the accumulation scan (the
        ``collectives.scatter`` constraint on the carry), so a replica
        only ever persists its ``1/n`` gradient shard.  The update
        then runs on the shard views directly via ``inner`` (the
        UNWRAPPED optax transform, whose state the trainers init over
        shard views).

        Stage 2 keeps parameters replicated and all-gathers the update
        (RS-per-microbatch + one AG — *less* wire than the per-
        microbatch all-reduce it replaces).  Stage 3 additionally takes
        ``state.tv`` as ``[n, cols]`` shard views and re-materializes
        parameters per fusion bucket just-in-time inside the loss
        (``collectives.gather_bucket``: all-gather forward, reduce-
        scatter backward); the update output IS the new view state — no
        parameter all-gather leg at all.
        """
        from distkeras_tpu.parallel.collectives import (all_gather,
                                                        gather_bucket,
                                                        scatter)

        if stage not in (2, 3):
            raise ValueError(f"stage must be 2 or 3, got {stage}")
        n = int(mesh.shape["data"])
        layout = self.zero_layout(n, bucket_mb)
        compute_loss = self.make_loss_fn()

        def loss_of_views(v, ntv, x, y):
            with jax.named_scope("zero3/param_gather"):
                buckets = [gather_bucket(b, mesh)
                           for b in layout.pack_views(v)]
            return compute_loss(layout.unpack(buckets), ntv, x, y)

        def train_step(state: TrainState, xs, ys):
            grad_fn = jax.value_and_grad(
                loss_of_views if stage >= 3 else compute_loss,
                has_aux=True)
            scope = ("zero3/grad_accum" if stage >= 3
                     else "zero2/accum_scatter")

            def micro(carry, batch):
                bks, ntv, loss_acc = carry
                x, y = batch
                (loss, ntv2), g = grad_fn(state.tv, ntv, x, y)
                g_bks = (layout.pack_views(g) if stage >= 3
                         else layout.pack(g))
                with jax.named_scope(scope):
                    bks = [scatter(a + b, mesh)
                           for a, b in zip(bks, g_bks)]
                return (bks, ntv2, loss_acc + loss), None

            (bks, ntv2, loss_sum), _ = jax.lax.scan(
                micro, (layout.zero_buckets(), state.ntv, jnp.zeros(())),
                (xs, ys))
            g_views = layout.views_from_buckets(
                [b / window for b in bks])
            p_views = (state.tv if stage >= 3
                       else layout.shard_views(state.tv))
            with jax.named_scope(f"zero{stage}/update"):
                u_views, opt_state = inner.update(
                    g_views, state.opt_state, p_views)
            if stage >= 3:
                tv = jax.tree.map(lambda p, u: p + u, state.tv, u_views)
            else:
                with jax.named_scope("zero2/all_gather"):
                    u_buckets = [all_gather(b, mesh)
                                 for b in layout.pack_views(u_views)]
                tv = jax.tree.map(lambda p, u: p + u, state.tv,
                                  layout.unpack(u_buckets))
            out_state = TrainState(tv=tv, ntv=ntv2, opt_state=opt_state,
                                   step=state.step + 1)
            loss = loss_sum / window
            if probe:
                import optax

                return out_state, (loss,
                                   {"grad_norm": optax.global_norm(
                                       g_views)})
            return out_state, loss

        return train_step

    def make_localsgd_accum_step(self, window: int, sync_every: int,
                                 mesh, config, axis: str = "data"
                                 ) -> Callable:
        """Local-SGD over the accumulation step (parallel/exchange.py):
        ``step(state, xs, ys)`` with ``xs: [sync_every, window, GB, ...]``
        runs, per replica INSIDE a shard_map over ``axis``,
        ``sync_every`` purely-local rounds (each a ``window``-microbatch
        accumulation + local optimizer update on this replica's batch
        shard), then ONE cross-replica merge: parameter deltas by the
        configured rule (mean / adasum) and floating optimizer-state
        leaves averaged (the momentum-aware sync).  Collective
        frequency drops to 1/``sync_every`` of the synchronous step's.

        Loss reported is the cross-replica mean of the per-replica mean
        losses over the period.  Requires a model whose non-trainable
        variables do not update cross-batch (BatchNorm is rejected by
        the trainers): a replica-local ntv update would diverge.
        """
        from distkeras_tpu.parallel.compat import shard_map as smap
        from distkeras_tpu.parallel.exchange import (merge_local_params,
                                                     sync_local_tree)
        from jax.sharding import PartitionSpec as P

        compute_loss = self.make_loss_fn()
        optimizer = self.optimizer
        n = int(mesh.shape[axis])

        def train_step(state: TrainState, xs, ys):
            def local_run(tv0, ntv0, opt0, xs, ys):
                grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

                def local_round(carry, batch):
                    tv, ntv, opt = carry
                    xw, yw = batch          # [window, b_local, ...]
                    zero = jax.tree.map(jnp.zeros_like, tv)

                    def micro(c, b):
                        g_acc, ntv_c, loss_acc = c
                        x, y = b
                        (loss, ntv2), g = grad_fn(tv, ntv_c, x, y)
                        return (jax.tree.map(jnp.add, g_acc, g), ntv2,
                                loss_acc + loss), None

                    (g_sum, ntv2, loss_sum), _ = jax.lax.scan(
                        micro, (zero, ntv, jnp.zeros(())), (xw, yw))
                    grads = jax.tree.map(lambda g: g / window, g_sum)
                    u, opt = optimizer.update(grads, opt, tv)
                    tv = jax.tree.map(lambda p, q: p + q, tv, u)
                    return (tv, ntv2, opt), loss_sum / window

                (tv, ntv, opt), losses = jax.lax.scan(
                    local_round, (tv0, ntv0, opt0), (xs, ys))
                tv = merge_local_params(tv0, tv, config, axis, n)
                opt = sync_local_tree(opt, config, axis, n)
                ntv = sync_local_tree(ntv, config, axis, n)
                return tv, ntv, opt, jax.lax.pmean(
                    jnp.mean(losses), axis)

            tv, ntv, opt, loss = smap(
                local_run, mesh=mesh,
                in_specs=(P(), P(), P(), P(None, None, axis),
                          P(None, None, axis)),
                out_specs=(P(), P(), P(), P()),
                check_vma=False)(list(state.tv), list(state.ntv),
                                 state.opt_state, xs, ys)
            return TrainState(tv=tv, ntv=ntv, opt_state=opt,
                              step=state.step + sync_every), loss

        return train_step

    def make_multi_train_step(self, n_steps: int) -> Callable:
        """Build ``step(state, xs, ys) -> (state', losses)`` running
        ``n_steps`` *optimizer updates* in one XLA call.

        ``xs: [n_steps, B, ...]`` — one minibatch per scanned step (NOT
        gradient accumulation; compare make_accum_train_step, which
        takes one update over its window).  Amortizes per-call host
        dispatch, which dominates for small models (the reference pays
        a py4j+pickle round trip per batch — reference:
        distkeras/workers.py; here even the jit dispatch can be folded
        away).  Returns the per-step losses ``[n_steps]``.
        """
        train_step = self.make_train_step()

        def multi(state: TrainState, xs, ys):
            def body(state, batch):
                state, loss = train_step(state, *batch)
                return state, loss

            return jax.lax.scan(body, state, (xs, ys))

        return multi

    def make_indexed_train_step(self, n_steps: int) -> Callable:
        """Build ``step(state, X, Y, idx) -> (state', losses)`` for
        device-resident datasets.

        ``X``/``Y`` are the *whole* dataset staged in HBM (ship them
        once, in their wire dtype — uint8 pixels cost 4x less than f32
        and ``preprocess`` expands on device); ``idx: [n_steps, B]``
        selects each scanned step's minibatch with an on-device gather.
        Per window only the tiny index block crosses the host->device
        link, so epoch shuffling costs ~nothing no matter how slow the
        link is.  This inverts the reference's data plane — Spark ships
        every batch to the worker as pickled rows (reference:
        distkeras/workers.py iterating mapPartitions) — into the
        TPU-native form: data parked in HBM, the program comes to it.
        """
        train_step = self.make_train_step()

        def window(state: TrainState, X, Y, idx):
            if idx.shape[0] != n_steps:
                raise ValueError(
                    f"index block carries {idx.shape[0]} steps but this "
                    f"program was built for n_steps={n_steps}; the step "
                    "counter and checkpoint-round bookkeeping depend on "
                    "them agreeing")

            def body(st, ix):
                st, loss = train_step(
                    st, jnp.take(X, ix, axis=0), jnp.take(Y, ix, axis=0))
                return st, loss

            return jax.lax.scan(body, state, idx)

        return window

    def make_indexed_accum_train_step(self, window: int,
                                      accum: Callable | None = None
                                      ) -> Callable:
        """``make_accum_train_step`` over a device-resident dataset:
        ``step(state, X, Y, idx)`` with ``idx: [window, GB]`` gathers
        each microbatch from the staged ``X``/``Y`` on device, then
        accumulates exactly like the streaming accum step.  The
        distributed trainers' device_data path (per round, only the
        index block crosses the link; the mesh gathers its own rows).
        ``accum`` overrides the wrapped accumulation step (the ZeRO
        stage-2/3 trainers pass :meth:`make_zero_accum_step`'s)."""
        accum = accum if accum is not None \
            else self.make_accum_train_step(window)

        def step(state: TrainState, X, Y, idx):
            if idx.shape[0] != window:
                raise ValueError(
                    f"index block carries {idx.shape[0]} microbatches "
                    f"but this program accumulates window={window}")
            xs = jnp.take(X, idx.reshape(-1), axis=0).reshape(
                (*idx.shape, *X.shape[1:]))
            ys = jnp.take(Y, idx.reshape(-1), axis=0).reshape(
                (*idx.shape, *Y.shape[1:]))
            return accum(state, xs, ys)

        return step

    def make_eval_fn(self) -> Callable:
        """Pure ``f(tv, ntv, x, y) -> {"loss": ..., metric...}``.

        Inference-mode loss plus every metric named in ``metrics``
        (currently ``"accuracy"``: argmax match for multiclass logits,
        0.5-threshold for a single binary logit).  The trainers jit this
        for their ``eval_every`` hook — the reference's only mid-train
        signal is the worker-side loss history (reference:
        distkeras/workers.py yielding training histories).
        """
        model, loss_fn, pre = self.model, self.loss_fn, self.preprocess
        names = self.metrics

        def class_labels(y, preds):
            """Integer class per row from sparse, one-hot, or [N,1]
            binary labels — explicit, so no shape ever broadcasts to
            [N, N] garbage (same hazard ops/losses.py _align guards)."""
            if y.ndim == preds.ndim and y.shape[-1] == preds.shape[-1] > 1:
                return y.argmax(-1)  # one-hot
            if y.ndim == preds.ndim and y.shape[-1] == 1:
                y = y[..., 0]  # [N, 1] binary/sparse
            if y.ndim != preds.ndim - 1:
                raise ValueError(
                    f"label shape {y.shape} incompatible with prediction "
                    f"shape {preds.shape} for accuracy")
            return y.astype(jnp.int32)

        def evaluate(tv, ntv, x, y):
            if pre is not None:
                x = pre(x)
            preds, _ = model.stateless_call(tv, ntv, x, training=False)
            out = {"loss": loss_fn(y, preds)}
            if "accuracy" in names:  # names validated in __init__
                labels = class_labels(y, preds)
                if preds.shape[-1] == 1:
                    hit = (preds[..., 0] > 0).astype(jnp.int32) == labels
                else:
                    hit = preds.argmax(-1) == labels
                out["accuracy"] = jnp.mean(hit.astype(jnp.float32))
            return out

        return evaluate

    def make_predict_fn(self) -> Callable:
        """Pure ``f(tv, ntv, x) -> outputs`` (inference mode)."""
        model, pre = self.model, self.preprocess

        def predict(tv, ntv, x):
            if pre is not None:
                x = pre(x)
            out, _ = model.stateless_call(tv, ntv, x, training=False)
            return out

        return predict
