"""int8 weight quantization for the decode path.

The sequential decode loop re-reads every matmul weight from HBM once
per generated token at batch sizes far too small to amortize it —
decode is weight-bandwidth-bound, the opposite regime from training.
Storing the weights as int8 with per-output-channel scales halves the
bytes vs bf16 (4x vs f32); the dequantize (one multiply) happens
*inside* the decode step so XLA fuses it into the consuming matmul's
operand read — int8 comes off HBM, full-precision math happens in
registers.

This is a decode-time serving optimization (lossy: ~1/254 relative
rounding per channel); training is untouched.  The reference has no
inference-optimization story at all (its ModelPredictor runs the
training forward, reference: distkeras/predictors.py) — this module is
TPU-first surplus.

Usage::

    qparams = quantize_params(params)           # host-side, once
    out = generate(qparams, prompt, cfg, ...)   # decode reads int8

``generate`` detects quantized leaves and keeps the sequential path
(prefill would run the batched training forward, which wants the
full-precision weights; pass the f32 params for prompt-heavy work).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QTensor:
    """int8 values + f32 per-output-channel scales.

    ``q * s`` reconstructs the weight; ``s`` broadcasts against ``q``
    (kept at the same rank, size 1 on contraction axes).
    """

    q: jax.Array  # int8
    s: jax.Array  # f32, broadcastable to q.shape

    @property
    def shape(self):
        return self.q.shape

    def dequant(self, dtype=jnp.float32):
        return (self.q.astype(jnp.float32) * self.s).astype(dtype)

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _quantize(w, contract_axes: tuple[int, ...]) -> QTensor:
    """Symmetric absmax int8 over the contraction axes.

    Scales are per *output* channel: the max is taken over the axes the
    consuming matmul sums over, so each output channel rounds
    independently (the standard weight-only scheme).
    """
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=contract_axes, keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


# Weight name -> axes the decode-step matmul contracts over (leading
# [L] stack axis excluded; it is never contracted).
_CONTRACT = {
    "wq": (1,), "wk": (1,), "wv": (1,),   # [L, d, h, hd]: sum over d
    "wo": (1, 2),                          # [L, h, hd, d]: sum over h, hd
    "w1": (1,), "w2": (1,),                # [L, d, f] / [L, f, d]
}


def quantize_params(params):
    """Quantize the decode-relevant matmul weights of a transformer
    parameter tree (models/transformer.init_params layout) to int8.

    Quantized: attention projections, dense-FFN mats, and ``tok_emb``
    (per-vocab-row scales — the unembedding's output channel, which is
    also exactly what a gathered embedding row needs).  Left in f32:
    RMSNorm scales (tiny, precision-critical) and MoE tensors (the
    decode MoE path gathers per-token expert slabs; quantizing those is
    future work).  Returns a tree of the same structure with
    :class:`QTensor` leaves where quantized.
    """
    params = dict(params)
    layers = dict(params["layers"])
    if "moe" in layers:
        raise ValueError(
            "quantize_params supports dense-FFN configs only (decode-time "
            "MoE gathers per-token expert slabs; see module docstring)")
    attn = {k: _quantize(v, _CONTRACT[k])
            for k, v in layers["attn"].items()}
    ffn = {k: _quantize(v, _CONTRACT[k])
           for k, v in layers["ffn"].items()}
    layers["attn"] = attn
    layers["ffn"] = ffn
    params["layers"] = layers
    # tok_emb [V, d]: scale per vocab row (axis 1 is contracted by the
    # unembed x @ emb^T; a gathered row dequants with its own scale).
    params["tok_emb"] = _quantize(params["tok_emb"], (1,))
    return params


def is_quantized(params) -> bool:
    return isinstance(params.get("tok_emb"), QTensor)


def deq(w, dtype=None):
    """Dequantize-if-needed: QTensor -> dense (f32 or ``dtype``),
    anything else passes through.  The decode step routes every weight
    read through here, so quantized and plain trees share one code
    path and the multiply sits next to its consuming matmul for XLA to
    fuse."""
    if isinstance(w, QTensor):
        return w.dequant(dtype or jnp.float32)
    return w if dtype is None else jnp.asarray(w).astype(dtype)


def unembed_logits(x, tok_emb, dtype):
    """Unembedding head ``x [..., d] @ tok_emb^T [V, d] -> [..., V]``.

    Quantized path: contract against the raw int8 table and apply the
    per-vocab-row scale to the [B, V] *result* — algebraically identical
    (the scale is constant over the contracted ``d`` axis) but the [V, d]
    HBM operand is int8 **by construction**: the only op between the
    table and the MXU is a dtype convert, which XLA always fuses into
    the operand read.  The alternative (dequantize then einsum) leaves a
    full-precision [V, d] temporary unless XLA happens to fuse the
    multiply — for the usually-dominant vocab head we don't want to
    depend on that.  int8 values are exact in bf16 (|q| <= 127 < 2^8),
    so converting q to the compute dtype loses nothing.
    """
    if isinstance(tok_emb, QTensor):
        out = jnp.einsum("...d,vd->...v", x, tok_emb.q.astype(x.dtype))
        return out.astype(jnp.float32) * tok_emb.s[:, 0]
    return jnp.einsum("...d,vd->...v", x,
                      jnp.asarray(tok_emb).astype(dtype))


def embed_rows(tok_emb, tokens, dtype):
    """Embedding lookup that gathers int8 rows THEN dequantizes (the
    gather touches B rows, not the whole [V, d] table)."""
    if isinstance(tok_emb, QTensor):
        rows = tok_emb.q[tokens].astype(jnp.float32)
        return (rows * tok_emb.s[tokens]).astype(dtype)
    return tok_emb[tokens].astype(dtype)


def quantize_kv(x):
    """Per-token, per-kv-head symmetric int8 quantization of decode-time
    K/V rows ``[..., kv_heads, head_dim]`` -> ``(int8, scale)`` with
    ``scale [..., kv_heads]`` = absmax / 127 over head_dim.

    The int8 KV cache halves the cache-byte term that dominates batched
    decode once the loop is at the HBM roofline (docs/perf_serving.md
    finding 1 — only byte reduction goes faster).  Scales stay f32:
    they are head_dim x smaller than the data.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return q.astype(jnp.int8), scale
