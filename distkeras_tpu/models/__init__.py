from distkeras_tpu.models.adapter import ModelAdapter, TrainState

__all__ = ["ModelAdapter", "TrainState"]
