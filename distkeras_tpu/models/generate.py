"""Autoregressive decoding for the transformer LM (KV-cached).

Inference counterpart of models/transformer.py — the LM analogue of
the reference's ModelPredictor batch-inference path (reference:
distkeras/predictors.py), which only covers fixed-shape feedforward
outputs.  Decoding is XLA-shaped: the KV cache is a static [B, max_len,
H, D] buffer per layer, the loop is ``lax.scan`` over positions (one
compiled program regardless of prompt/output length), and sampling is
functional over an explicit PRNG key.

Decoding strategies: greedy, temperature sampling with top-k / top-p
(nucleus) / min-p filtering (:func:`generate`), and beam search
(:func:`beam_search`).  Uniform prompts run the prefill/decode split
(:func:`prefill`; MoE configs use decode-parity dense routing there);
int8-quantized trees (models/quant) decode on the sequential path.  Batch decoding shards over the mesh ``data``
axis like every other batch op.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.transformer import (
    TransformerConfig,
    _moe_dense_block,
    _moe_gates,
    _rms_norm,
    _unembed,
    block_apply,
    rope_angles,
    rope_rotate,
)
from distkeras_tpu.models.quant import (
    deq,
    embed_rows,
    is_quantized,
    quantize_kv,
    unembed_logits,
)
from distkeras_tpu.ops.attention import flash_attention


def init_cache(cfg: TransformerConfig, batch: int, dtype=None,
               kv_int8: bool = False):
    """Per-layer KV buffers [L, B, max_len, kv_heads, head_dim].

    Under GQA (cfg.n_kv_heads < n_heads) the cache carries only the
    shared K/V heads — the n_heads/kv_heads memory and HBM-bandwidth
    saving that is the point of GQA at decode time.

    ``kv_int8``: store K/V as int8 with per-token per-kv-head f32
    scales (``k_scale``/``v_scale`` [L, B, max_len, kv_heads] —
    head_dim x smaller than the data; see quant.quantize_kv).  Halves
    the cache-byte term that dominates batched decode at the HBM
    roofline.  The presence of the scale leaves is what switches the
    decode attention onto the dequantizing einsums.
    """
    dtype = jnp.int8 if kv_int8 else (dtype or jnp.dtype(cfg.dtype))
    shape = (cfg.n_layers, batch, cfg.max_len, cfg.kv_heads, cfg.head_dim)
    cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if kv_int8:
        cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    return cache


def prefill(params, prompt, cfg: TransformerConfig,
            last_logits: bool = True, kv_int8: bool = False):
    """Fill the KV cache for all prompt positions in ONE parallel pass.

    The sequential decode loop costs one ``_decode_step`` per prompt
    position; this runs the training-style batched forward (flash
    attention over [B, P], through the SAME ``block_apply`` body as
    training — ``return_kv=True``) and writes every position's K/V into
    the cache at once.  Prompt processing drops from P sequential steps
    to a single MXU-friendly program, the standard prefill/decode split.

    Returns ``(cache, last [B, V] or None)`` — ``last_logits=False``
    skips the final norm + unembed (``generate`` re-derives the last
    position's logits inside its scan; under jit XLA DCE would prune
    the unused head anyway, the flag keeps eager callers cheap too).

    MoE configs prefill with the same capacity-free dense top-k
    routing as ``_decode_step`` (``_moe_gates`` — Switch top-1 or
    renormalized top-2) — every expert runs on every token (E x the
    dense-FFN compute; prefill happens once) and the selected experts'
    outputs are gathered, so prefilled and sequential prompt
    processing match exactly (the train/decode MoE divergence caveat in
    ``generate`` is unchanged).
    """
    dtype = jnp.dtype(cfg.dtype)
    b, p_len = prompt.shape
    if p_len > cfg.max_len:
        raise ValueError(
            f"prompt length {p_len} exceeds max_len={cfg.max_len} "
            "(the KV cache size)")
    x = params["tok_emb"][prompt].astype(dtype)
    rope_ang = None
    if cfg.rope:
        rope_ang = rope_angles(jnp.arange(p_len), cfg.head_dim,
                               cfg.rope_theta)[None, :, None, :]
    else:
        x = x + params["pos_emb"][:p_len][None].astype(dtype)

    attention_fn = lambda q, k, v: flash_attention(
        q, k, v, True, window=cfg.attention_window)
    cache = init_cache(cfg, b, kv_int8=kv_int8)
    ks, vs = [], []
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        # moe_dense_routing: MoE configs run the capacity-free
        # decode-parity FFN (transformer._moe_dense_block) so prefilled
        # and sequential prompts match; dense configs are unaffected.
        x, _, (k, v) = block_apply(lp, x, cfg, attention_fn, rope_ang,
                                   return_kv=True,
                                   moe_dense_routing=True)
        if kv_int8:  # quantized after the fact, not cast
            ks.append(k)
            vs.append(v)
        else:
            ks.append(k.astype(cache["k"].dtype))
            vs.append(v.astype(cache["v"].dtype))

    if kv_int8:
        kq, k_s = quantize_kv(jnp.stack(ks))  # [L, B, P, C, D]
        vq, v_s = quantize_kv(jnp.stack(vs))
        cache = {
            "k": cache["k"].at[:, :, :p_len].set(kq),
            "v": cache["v"].at[:, :, :p_len].set(vq),
            "k_scale": cache["k_scale"].at[:, :, :p_len].set(k_s),
            "v_scale": cache["v_scale"].at[:, :, :p_len].set(v_s),
        }
    else:
        cache = {
            "k": cache["k"].at[:, :, :p_len].set(jnp.stack(ks)),
            "v": cache["v"].at[:, :, :p_len].set(jnp.stack(vs)),
        }
    if not last_logits:
        return cache, None
    x = _rms_norm(x, params["ln_f_scale"])
    return cache, _unembed(x[:, -1:], params, cfg)[:, 0]


def _ancestry_attend(qg, ck, cv, anc_oh, mask_b, cfg: TransformerConfig,
                     w_beams: int, kv_scales=None):
    """Beam ancestry attention for ONE position, shared by the
    full-cache chunk body and the windowed ring-buffer body.

    ``qg [B, kv_heads, groups, hd]`` f32 queries (beam lanes tiled
    batch-major, B = bt * W), ``ck/cv [B, S, kv_heads, hd]`` the
    per-lane cache, ``anc_oh [bt, W, S, W]`` f32 one-hot ancestor map
    (SLOT s of lane w reads from lane ``anc[b, w, s]`` — slot ==
    position while total <= max_len, and under rolling decode the
    beam body retires a reused slot's ancestry in the same step that
    overwrites its K/V), ``mask_b [bt, W, S]`` bool valid-slot mask
    (position mask full-cache, band mask windowed — the only
    difference between the two callers).  Scores every
    (query-lane, source-lane) pair — the cache is read once, W x the
    tiny decode attention FLOPs — then the one-hot selects each
    position's true ancestor.  ``kv_scales=(cks, cvs) [B, S, kv]``:
    int8-KV dequant scales (slot-indexed, so ring caches compose).
    Returns ``attn [B, n_heads, hd]`` f32.
    """
    b = qg.shape[0]
    s_len = ck.shape[1]
    bt = b // w_beams
    qb = qg.reshape(bt, w_beams, cfg.kv_heads, -1, cfg.head_dim)
    kb = ck.astype(jnp.float32).reshape(
        bt, w_beams, s_len, cfg.kv_heads, cfg.head_dim)
    vb = cv.astype(jnp.float32).reshape(
        bt, w_beams, s_len, cfg.kv_heads, cfg.head_dim)
    la = jnp.einsum("bwcgk,bvsck->bwcgvs", qb, kb)
    if kv_scales is not None:
        # [B, S, C] -> [bt, 1, C, 1, v(=w), S] over la's dims.
        bsc = lambda sc: sc.reshape(
            bt, w_beams, s_len, cfg.kv_heads).transpose(
            0, 3, 1, 2)[:, None, :, None, :, :]
        la = la * bsc(kv_scales[0])
    logits = jnp.einsum("bwcgvs,bwsv->bwcgs", la, anc_oh)
    logits = logits / jnp.sqrt(jnp.float32(cfg.head_dim))
    logits = jnp.where(mask_b[:, :, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    pm = jnp.einsum("bwcgs,bwsv->bwcgvs", probs, anc_oh)
    if kv_scales is not None:
        pm = pm * bsc(kv_scales[1])
    return jnp.einsum("bwcgvs,bvsck->bwcgk", pm, vb).reshape(
        b, cfg.n_heads, cfg.head_dim)


def _decode_step(params, cache, tokens, pos, cfg: TransformerConfig,
                 pad_lens=None, beam_anc=None):
    """One position: tokens [B] at position ``pos`` -> (logits [B, V], cache).

    Attention reads the cache up to ``pos`` with a position mask (static
    shapes; masked slots contribute exp(NEG_INF-ish) = 0).

    ``pad_lens [B]`` supports left-padded batches (ragged prompts
    aligned at their ends): positions < pad_lens[i] are excluded from
    row i's attention forever, and position *ids* (rotary angles /
    pos_emb rows) count from the row's true start, so each row decodes
    exactly as it would alone.

    The plain path (no window, no padding) delegates to
    :func:`_decode_chunk` with T = 1 — ONE layer-body definition for
    both; only the ring-buffer slot arithmetic and the ragged pad
    masking below justify a separate body.
    """
    dtype = jnp.dtype(cfg.dtype)
    b = tokens.shape[0]
    if cfg.attention_window is None and pad_lens is None:
        out, cache = _decode_chunk(params, cache, tokens[:, None],
                                   jnp.full((b,), pos, jnp.int32), cfg,
                                   uniform_pos=True, beam_anc=beam_anc)
        return out[:, 0], cache
    if beam_anc is not None and pad_lens is not None:
        raise ValueError("beam ancestry attention does not compose with "
                         "pad_lens (beam search is uniform-prompt only)")
    if beam_anc is not None:
        anc, w_beams = beam_anc
        anc_oh = jax.nn.one_hot(anc, w_beams, dtype=jnp.float32)
    kv_q = "k_scale" in cache                   # int8 KV cache
    x = embed_rows(params["tok_emb"], tokens, dtype)  # [B, D]
    if pad_lens is None:
        pos_ids = jnp.full((b,), pos)
    else:
        pos_ids = jnp.maximum(pos - pad_lens, 0)
    rope_ang = None
    if cfg.rope:
        # [B, half] per-row angles; broadcast over heads.
        rope_ang = rope_angles(pos_ids, cfg.head_dim,
                               cfg.rope_theta)[:, None, :]
    else:
        x = x + params["pos_emb"][pos_ids].astype(dtype)

    ck_all, cv_all = cache["k"], cache["v"]     # [L, B, S, kv, hd]
    if kv_q:
        cks_all, cvs_all = cache["k_scale"], cache["v_scale"]
    # [B, S, C] scale -> broadcast over the [B, C, G, S] logits.
    sc_b = lambda s: s.transpose(0, 2, 1)[:, :, None, :]
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        h = _rms_norm(x, lp["ln1_scale"])
        # deq: int8 weights dequantize here (fused into the matmul
        # read); plain trees pass through untouched.
        q = jnp.einsum("bd,dhk->bhk", h, deq(lp["attn"]["wq"]))
        # Cache dtype: the einsum promotes bf16 activations x f32 weights
        # to f32; the cache stays in the compute dtype.
        k = jnp.einsum("bd,dhk->bhk", h, deq(lp["attn"]["wk"]))
        v = jnp.einsum("bd,dhk->bhk", h, deq(lp["attn"]["wv"]))
        if rope_ang is not None:
            # Keys cache post-rotation (each key's rotation depends only
            # on its own position), matching the training forward.
            q, k = rope_rotate(q, rope_ang), rope_rotate(k, rope_ang)
        if kv_q:  # post-rotation, like the bf16 cache
            k, k_s = quantize_kv(k)               # scale [B, C]
            v, v_s = quantize_kv(v)
        # Windowed configs write the ring-buffer slot pos % C (identical
        # to pos while pos < C): with window <= C the cache then
        # supports generation beyond max_len (rolling decode) — the
        # int8 scales ride the same slot arithmetic.
        slot = jnp.asarray(pos % cfg.max_len if cfg.attention_window
                           else pos, jnp.int32)
        ck_all = _layer_slab_update(ck_all, i, k[:, None], slot)
        cv_all = _layer_slab_update(cv_all, i, v[:, None], slot)
        ck, cv = ck_all[i], cv_all[i]
        if kv_q:
            cks_all = _layer_slab_update(cks_all, i, k_s[:, None], slot)
            cvs_all = _layer_slab_update(cvs_all, i, v_s[:, None], slot)
            cks, cvs = cks_all[i], cvs_all[i]

        # GQA: grouped einsums read only the kv-head cache — never
        # materialize an expanded per-query-head copy (that repeat
        # would forfeit the cache-bandwidth saving that is GQA's point).
        groups = cfg.n_heads // cfg.kv_heads
        qg = q.astype(jnp.float32).reshape(
            b, cfg.kv_heads, groups, cfg.head_dim)
        span = jnp.arange(cfg.max_len)
        if cfg.attention_window is not None:
            # Ring-buffer band: slot s holds global position
            # g = pos - ((pos - s) mod C).  Keep iff the position is
            # real (g >= 0 — this also excludes every future slot while
            # pos < C, so prefilled prompts stay causal) and inside the
            # window (delta < W).  For pos < C this reduces exactly to
            # span in (pos - W, pos]; for pos >= C it implements the
            # rolling window.  Distances are pad-invariant, so the
            # ragged pad mask below composes unchanged.
            delta = jnp.mod(pos - span, cfg.max_len)
            row_mask = (delta < cfg.attention_window) & (pos - delta >= 0)
        else:
            row_mask = span <= pos
        if beam_anc is not None:
            # Windowed beam ancestry: the ancestor map is SLOT-indexed
            # (identical to positions until the ring wraps; under
            # rolling decode the beam body retires stale entries as
            # slots are rewritten) and only the band mask differs from
            # the full-cache path.
            bt = b // w_beams
            mask_b = jnp.broadcast_to(row_mask[None, None, :],
                                      (bt, w_beams, cfg.max_len))
            attn = _ancestry_attend(qg, ck, cv, anc_oh, mask_b, cfg,
                                    w_beams,
                                    kv_scales=(cks, cvs) if kv_q
                                    else None)
        else:
            logits = jnp.einsum("bcgk,bsck->bcgs", qg,
                                ck.astype(jnp.float32))
            if kv_q:
                logits = logits * sc_b(cks)
            logits = logits / jnp.sqrt(jnp.float32(cfg.head_dim))
            mask = row_mask[None, None, None, :]
            if pad_lens is not None:  # left-pad slots never attend
                mask = mask & (span[None, :] >= pad_lens[:, None]
                               )[:, None, None, :]
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            attn = jnp.einsum("bcgs,bsck->bcgk",
                              probs * sc_b(cvs) if kv_q else probs,
                              cv.astype(jnp.float32)).reshape(
                b, cfg.n_heads, cfg.head_dim)
        x = x + jnp.einsum("bhk,hkd->bd", attn.astype(dtype),
                           deq(lp["attn"]["wo"]))

        h = _rms_norm(x, lp["ln2_scale"])
        if cfg.num_experts:
            # Decode-time MoE: dense top-k without capacity (batch is
            # small; correctness over dispatch efficiency).  Same
            # gate rule as training/prefill via _moe_gates.
            router = jnp.einsum("bd,de->be", h.astype(jnp.float32),
                                lp["moe"]["wg"])
            probs = jax.nn.softmax(router, axis=-1)
            gates, expert = _moe_gates(probs, cfg)   # [B, k]
            w1 = lp["moe"]["w1"][expert]  # [B, k, D, F]
            w2 = lp["moe"]["w2"][expert]  # [B, k, F, D]
            hk = jax.nn.gelu(jnp.einsum("bd,bkdf->bkf", h,
                                        w1.astype(dtype)))
            yk = jnp.einsum("bkf,bkfd->bkd", hk, w2.astype(dtype))
            y = jnp.einsum("bkd,bk->bd", yk, gates.astype(dtype))
        else:
            y = jnp.einsum(
                "bf,fd->bd",
                jax.nn.gelu(jnp.einsum("bd,df->bf", h,
                                       deq(lp["ffn"]["w1"]))),
                deq(lp["ffn"]["w2"]))
        x = x + y

    x = _rms_norm(x, params["ln_f_scale"])
    # Vocab head: int8 trees contract the raw q table and scale the
    # result (int8 stays the HBM operand by construction — see
    # quant.unembed_logits), instead of dequantizing [V, d] per step.
    out = unembed_logits(x, params["tok_emb"], dtype)
    cache = {"k": ck_all, "v": cv_all}
    if kv_q:
        cache["k_scale"], cache["v_scale"] = cks_all, cvs_all
    return out.astype(jnp.float32), cache


def _rows_update(cache_layer, rows, pos0):
    """Write ``rows [B, T, kv, hd]`` into ``cache_layer [B, S, kv, hd]``
    at per-row offsets ``pos0 [B]`` (a batched dynamic_update_slice —
    XLA lowers the vmap to a scatter).  Callers clamp pos0 to S - T;
    dynamic_update_slice would silently shift an out-of-range write."""
    return jax.vmap(
        lambda c, r, p: jax.lax.dynamic_update_slice(
            c, r.astype(c.dtype),
            (p,) + (0,) * (c.ndim - 1)))(cache_layer, rows, pos0)


def _rows_update_ring(cache_layer, rows, pos0, max_len):
    """Per-row T-span write at ring slots ``(pos0[b] + t) % max_len`` —
    the modular generalization of :func:`_rows_update` for windowed
    chunks that may WRAP mid-chunk (speculative decoding's divergent
    per-row positions on a ring cache).  A per-row gather-scatter
    (``c.at[idx].set``): a dynamic_update_slice span cannot wrap."""
    t_len = rows.shape[1]
    idx = (pos0[:, None] + jnp.arange(t_len)) % max_len    # [B, T]
    return jax.vmap(lambda c, r, ix: c.at[ix].set(
        r.astype(c.dtype)))(cache_layer, rows, idx)


def _layer_slab_update(cache_all, i, rows, pos):
    """Write ``rows [B, T, kv, hd]`` (all rows at position ``pos``) into
    layer ``i`` of the stacked cache ``[L, B, S, kv, hd]`` — WITHOUT
    slicing the layer out and restacking.

    The decode loop is bandwidth-bound and the cache is its largest
    buffer; the old per-layer ``cache[i]`` + ``jnp.stack`` pattern made
    XLA materialize a full cache copy every step (measured ~6.5 ms per
    tensor per step at batch 64 on v5e — the dominant term of the
    serving b64 cliff in docs/perf_serving.md), where this slab
    dynamic_update_slice stays in place (~0.1 ms; serving table went
    3.2k -> 17.9k tok/s at b64, 83% of the HBM roofline).

    Uniform-position writes only.  Per-row offsets (speculative
    decoding) keep the per-layer ``_rows_update`` + one final stack:
    scatters addressed through axis 1 of the stacked array compile to
    layouts that cost MORE than the single stack copy (measured —
    speculative throughput dropped 2.8x when routed through a
    batch-axis vmap over the stacked cache).
    """
    zero = jnp.int32(0)
    starts = (jnp.int32(i), zero, pos) + (zero,) * (cache_all.ndim - 3)
    return jax.lax.dynamic_update_slice(
        cache_all, rows.astype(cache_all.dtype)[None], starts)


def _decode_chunk(params, cache, tokens, pos0, cfg: TransformerConfig,
                  uniform_pos: bool = False, beam_anc=None):
    """Process T new tokens per row against the cache in ONE pass:
    ``tokens [B, T]`` at global positions ``pos0[b] + (0..T-1)`` ->
    ``(logits [B, T, V] f32, cache)``.

    The chunked generalization of :func:`_decode_step` (T = 1 is the
    same math): queries attend every cached position <= their own
    global position — in-chunk causality included — and the chunk's
    K/V land in the cache at per-row offsets, so rows at different
    positions (speculative decoding's per-row accept divergence) share
    one compiled program.

    Windowed (``attention_window``) configs run in three shapes:
    (a) the per-row path with T == 1 — each row writes its ring slot
    ``pos0[b] % max_len`` and attends under the per-row band mask,
    which is the rolling-decode arithmetic vectorized over rows at
    DIFFERENT positions (the serving engine's decode step); (b) the
    uniform_pos chunk path under the caller contract that the chunk
    does not wrap (``pos0[0] % max_len + T <= max_len`` — admission
    prefills satisfy it by bucket construction; unverifiable here
    because pos0 is traced); (c) per-row MULTI-token chunks (round-5,
    speculative decoding on a ring): writes go through a modular
    scatter that may wrap mid-chunk, guarded by
    ``T + window <= max_len`` so in-chunk future positions and
    rejected-tail garbage always alias OUTSIDE every live query's
    band.  Windowed x kv_int8 composes on all three shapes: the scale
    slabs take the same ring-slot updates as the K/V they scale
    (round-5; parity vs the bf16-cache run in tests/test_serving.py
    and test_generate.py).

    Stale cache slots beyond a row's final position are harmless by
    construction: the position mask excludes them (for ring caches the
    band-mask's implied-position formula sends slots the row has not
    reached to negative positions), and every slot is rewritten before
    the row's position passes it.

    ``uniform_pos`` (static): promise that every row of ``pos0`` holds
    the same value, so the cache write is one slab update instead of a
    per-row scatter (see _layer_slab_update).  The plain decode loop
    and prefix warm-up qualify; speculative decoding (per-row accept
    divergence) does not.

    ``beam_anc = (anc [B/W, W, S] int32, W)``: beam-search ancestry
    attention (requires T == 1, uniform_pos, no window).  Rows are
    beam lanes (batch-major tiling b*W + w); each lane writes its own
    cache lane in place, and attention resolves lane ``w``'s history
    through ``anc`` — position ``s`` is read from lane ``anc[b, w,
    s]`` — by computing every (query-lane, source-lane) score and
    folding a one-hot of ``anc`` into the softmax/PV einsums.  The
    cache is read ONCE per step with no beam-reorder rewrite; the
    price is score intermediates of ``B/W x W^2 x n_heads x S`` f32
    per layer — ~4 MB at the benched config (b8 W4 S1025 H8), but
    quadratic in beam width (b64 W8 S2048 H16 would be ~1 GB/layer;
    at that scale revisit before trusting this path).  This replaced
    the physical parent-gather of the cache, which cost more than the
    whole attention read (docs/perf_serving.md finding 4).
    """
    dtype = jnp.dtype(cfg.dtype)
    b, t_len = tokens.shape
    x = embed_rows(params["tok_emb"], tokens, dtype)        # [B, T, D]
    pos_ids = pos0[:, None] + jnp.arange(t_len)[None, :]    # [B, T]
    rope_ang = None
    if cfg.rope:
        rope_ang = rope_angles(pos_ids, cfg.head_dim,
                               cfg.rope_theta)[:, :, None, :]
    else:
        x = x + params["pos_emb"][pos_ids].astype(dtype)

    kv_q = "k_scale" in cache                   # int8 KV cache
    win = cfg.attention_window is not None
    if (win and t_len > 1 and not uniform_pos
            and t_len + cfg.attention_window > cfg.max_len):
        # A per-row ring chunk may WRAP, and then two invariants need
        # chunk + window <= max_len: an in-chunk future position
        # q > t wrapped onto slot q % C must alias to implied position
        # q - C with delta C - (q - t) >= window (masked), and a
        # speculative chunk's rejected-tail garbage must never fall
        # inside a live query's band (speculative._validate states the
        # same bound with T = n_draft + 1).  uniform_pos chunks are
        # exempt: their no-wrap caller contract keeps every future
        # slot at implied position q - C < 0, masked unconditionally.
        raise ValueError(
            f"windowed per-row chunk of {t_len} tokens + "
            f"attention_window={cfg.attention_window} exceeds the ring "
            f"size (max_len={cfg.max_len}); shrink the chunk or grow "
            "the ring")
    ck_all, cv_all = cache["k"], cache["v"]     # [L, B, S, kv, hd]
    if kv_q:
        cks_all, cvs_all = cache["k_scale"], cache["v_scale"]
        new_ks, new_vs = [], []
    new_k, new_v = [], []                       # per-row path accumulates
    span = jnp.arange(cfg.max_len)
    if win:
        # Ring band mask, per row (see _decode_step's windowed body for
        # the slot->implied-position derivation; here pos differs per
        # row/chunk position).
        delta = jnp.mod(pos_ids[:, :, None] - span[None, None, :],
                        cfg.max_len)
        mask = ((delta < cfg.attention_window)
                & (pos_ids[:, :, None] - delta >= 0)
                )[:, :, None, None, :]            # [B, T, 1, 1, S]
        wr_pos = pos0 % cfg.max_len               # ring write slots
    else:
        mask = (span[None, None, :] <= pos_ids[:, :, None]
                )[:, :, None, None, :]            # [B, T, 1, 1, S]
        wr_pos = pos0
    # [B, S, C] scale -> broadcast over the [B, T, C, G, S] logits.
    sc_b = lambda s: s.transpose(0, 2, 1)[:, None, :, None, :]
    if beam_anc is not None:
        anc, w_beams = beam_anc
        if t_len != 1 or not uniform_pos or cfg.attention_window:
            raise ValueError("beam ancestry attention requires T == 1, "
                             "uniform positions, and no window")
        # One-hot over source lanes, f32 for the einsum contractions.
        anc_oh = jax.nn.one_hot(anc, w_beams, dtype=jnp.float32)
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        h = _rms_norm(x, lp["ln1_scale"])
        q = jnp.einsum("btd,dhk->bthk", h, deq(lp["attn"]["wq"]))
        k = jnp.einsum("btd,dhk->bthk", h, deq(lp["attn"]["wk"]))
        v = jnp.einsum("btd,dhk->bthk", h, deq(lp["attn"]["wv"]))
        if rope_ang is not None:
            q, k = rope_rotate(q, rope_ang), rope_rotate(k, rope_ang)
        if kv_q:  # post-rotation, like the bf16 cache
            k, k_s = quantize_kv(k)
            v, v_s = quantize_kv(v)
        if uniform_pos:
            ck_all = _layer_slab_update(ck_all, i, k, wr_pos[0])
            cv_all = _layer_slab_update(cv_all, i, v, wr_pos[0])
            ck, cv = ck_all[i], cv_all[i]
            if kv_q:
                cks_all = _layer_slab_update(cks_all, i, k_s, wr_pos[0])
                cvs_all = _layer_slab_update(cvs_all, i, v_s, wr_pos[0])
                cks, cvs = cks_all[i], cvs_all[i]
        else:
            if win and t_len > 1:
                # A multi-token ring chunk at divergent row positions
                # can wrap mid-chunk: modular per-element scatter.
                upd = lambda c, r: _rows_update_ring(c, r, pos0,
                                                     cfg.max_len)
            else:
                upd = lambda c, r: _rows_update(c, r, wr_pos)
            ck = upd(ck_all[i], k)
            cv = upd(cv_all[i], v)
            new_k.append(ck)
            new_v.append(cv)
            if kv_q:
                cks = upd(cks_all[i], k_s)
                cvs = upd(cvs_all[i], v_s)
                new_ks.append(cks)
                new_vs.append(cvs)

        groups = cfg.n_heads // cfg.kv_heads
        qg = q.astype(jnp.float32).reshape(
            b, t_len, cfg.kv_heads, groups, cfg.head_dim)
        if beam_anc is not None:
            # Ancestry attention (shared body: _ancestry_attend) — the
            # cache is read once, W x the (tiny) decode attention
            # FLOPs, and the one-hot selects each position's true
            # ancestor lane.
            bt = b // w_beams
            mask_b = mask[:, 0, 0, 0, :].reshape(bt, w_beams,
                                                 cfg.max_len)
            attn = _ancestry_attend(
                qg[:, 0], ck, cv, anc_oh, mask_b, cfg, w_beams,
                kv_scales=(cks, cvs) if kv_q else None,
            )[:, None]  # restore T = 1
        else:
            logits = jnp.einsum("btcgk,bsck->btcgs", qg,
                                ck.astype(jnp.float32))
            if kv_q:
                logits = logits * sc_b(cks)
            logits = logits / jnp.sqrt(jnp.float32(cfg.head_dim))
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            attn = jnp.einsum("btcgs,bsck->btcgk",
                              probs * sc_b(cvs) if kv_q else probs,
                              cv.astype(jnp.float32)).reshape(
                b, t_len, cfg.n_heads, cfg.head_dim)
        x = x + jnp.einsum("bthk,hkd->btd", attn.astype(dtype),
                           deq(lp["attn"]["wo"]))

        h = _rms_norm(x, lp["ln2_scale"])
        if cfg.num_experts and t_len > 1:
            # Multi-token chunks take the batched dense-routing block
            # (all experts on all tokens, one-hot combine): peak memory
            # is [B, T, E, F] ACTIVATIONS, where the per-token weight
            # gather below would materialize B*T*k copies of the [D, F]
            # expert mats — GBs per layer at warm-chunk T.  Same math
            # (_moe_gates shared), same decode-parity semantics.
            y = _moe_dense_block(lp["moe"], h, cfg)
        elif cfg.num_experts:
            # T = 1 (the decode step): gather the k selected experts'
            # slabs per row — fewer HBM bytes than all E at small
            # batch, which is what the bandwidth-bound loop wants.
            router = jnp.einsum("btd,de->bte", h.astype(jnp.float32),
                                lp["moe"]["wg"])
            gates, expert = _moe_gates(jax.nn.softmax(router, -1), cfg)
            w1 = lp["moe"]["w1"][expert]          # [B, T, k, D, F]
            w2 = lp["moe"]["w2"][expert]
            hk = jax.nn.gelu(jnp.einsum("btd,btkdf->btkf", h,
                                        w1.astype(dtype)))
            yk = jnp.einsum("btkf,btkfd->btkd", hk, w2.astype(dtype))
            y = jnp.einsum("btkd,btk->btd", yk, gates.astype(dtype))
        else:
            y = jnp.einsum(
                "btf,fd->btd",
                jax.nn.gelu(jnp.einsum("btd,df->btf", h,
                                       deq(lp["ffn"]["w1"]))),
                deq(lp["ffn"]["w2"]))
        x = x + y

    x = _rms_norm(x, params["ln_f_scale"])
    out = unembed_logits(x, params["tok_emb"], dtype)
    if not uniform_pos:
        ck_all, cv_all = jnp.stack(new_k), jnp.stack(new_v)
        if kv_q:
            cks_all, cvs_all = jnp.stack(new_ks), jnp.stack(new_vs)
    cache = {"k": ck_all, "v": cv_all}
    if kv_q:
        cache["k_scale"], cache["v_scale"] = cks_all, cvs_all
    return out.astype(jnp.float32), cache


def top_k_mask(logits, k: int, exact: bool = False):
    """Keep the k highest logits per row; the rest go to -inf.

    Static ``k`` (a Python int): the mask is a compare against the k-th
    value from a top-k reduction — no dynamic shapes, scan/jit
    friendly.

    By default the k-th value comes from ``lax.approx_max_k`` (recall
    0.99): on TPU the exact ``lax.top_k`` over a [B, 32k] vocab costs
    more than the whole rest of a decode step (~7.8 ms vs 0.7 ms at
    batch 64 on v5e — measured, docs/perf_serving.md finding 6), while
    the approximate threshold misidentifies only logits in a ~1% band
    around the k-th value — sampling-support noise far below the
    sampling noise itself.  Pass ``exact=True`` (or
    ``generate(..., exact_top_k=True)``) to restore the exact
    semantics of releases before round 3.
    """
    if k < 1:
        raise ValueError(f"top_k must be >= 1, got {k}")
    if exact or k > logits.shape[-1] // 2:
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
    else:
        kth = jax.lax.approx_max_k(logits, k, recall_target=0.99,
                                   aggregate_to_topk=True)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _validate_unit_interval(name, p, zero_ok: bool = False):
    """Range-check a sampling filter value whenever it is CONCRETE —
    scalars and per-row arrays alike; only tracers pass through (their
    values are validated by the caller: the serving engine's
    submit/constructor, generate's argument checks).

    Round-6 fix: non-scalar concrete values used to skip validation
    entirely, so a direct ``top_p_mask``/``min_p_mask`` caller with an
    out-of-range array (e.g. a negative min_p) got silent NaN masking
    instead of an error.  ``zero_ok`` admits 0.0 in per-row ARRAYS
    only — the serving engines' explicit "no min-p filter" slot value
    (log 0 = -inf keeps every token); a scalar 0.0 stays an error (the
    scalar no-op spelling is None), and top_p keeps the open lower
    bound everywhere (a 0.0 nucleus would mask every token).
    """
    if isinstance(p, jax.core.Tracer):
        return
    vals = np.asarray(p)
    zero_ok = zero_ok and vals.ndim > 0
    lo_ok = (vals >= 0.0) if zero_ok else (vals > 0.0)
    if not np.all(lo_ok & (vals <= 1.0)):
        lo = "[0, 1]" if zero_ok else "(0, 1]"
        raise ValueError(
            f"{name} must be in {lo}, got "
            f"{p if np.ndim(p) == 0 else vals}")


def min_p_mask(logits, min_p):
    """Keep tokens whose probability is at least ``min_p`` times the
    top token's probability; the rest go to -inf.

    The entropy-adaptive filter (min-p sampling): permissive when the
    model is uncertain (flat distribution -> many tokens clear the
    relative bar), strict when confident.  Static shapes; the top token
    always survives (ratio 1 >= min_p).

    ``min_p`` may be a per-row ``[B, 1]`` array (the serving engine's
    per-request path); a row of 0.0 is a no-op (log 0 = -inf keeps
    everything).  Concrete values are range-checked here (arrays
    [0, 1]; scalars (0, 1] — the scalar no-op spelling is None);
    traced values are validated by the caller.
    """
    _validate_unit_interval("min_p", min_p, zero_ok=True)
    # log p_i - log p_max >= log(min_p), computed on logits directly
    # (the softmax normalizer cancels in the difference).
    gap = logits - logits.max(axis=-1, keepdims=True)
    return jnp.where(gap >= jnp.log(min_p), logits, -jnp.inf)


def top_p_mask(logits, p: float):
    """Nucleus filtering: keep the smallest set of tokens whose
    probability mass reaches ``p``; the rest go to -inf.

    Sort-based with an exclusive cumulative sum, so the top token is
    always kept (exclusive mass 0 < p) — static shapes throughout.

    ``p`` may be a per-row ``[B, 1]`` array (the serving engine's
    per-request path); a row of 1.0 is a no-op.  Concrete values —
    scalar or array — are range-checked here ((0, 1]); traced values
    are validated by the caller.
    """
    _validate_unit_interval("top_p", p)
    sl = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    probs = jax.nn.softmax(sl, axis=-1)
    exclusive = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive < p
    thr = jnp.min(jnp.where(keep, sl, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < thr, -jnp.inf, logits)


def _device_tree(params):
    """Coerce a host-numpy tree (load_lm output) to jnp leaves: a raw
    numpy leaf cannot be fancy-indexed by the scan's traced tokens
    (TracerArrayConversionError); asarray is a no-op for leaves already
    on device, so placed/sharded trees pass through untouched."""
    return jax.tree.map(jnp.asarray, params)


def rolling_eligible(cfg: TransformerConfig) -> bool:
    """Can this config decode past ``max_len`` on the ring-buffer
    cache?  Rope (positions beyond max_len have no learned-table
    embedding) + a window that fits the ring.  The ONE definition —
    generate/beam_search budgets and the serving engine's rolling-lane
    gate must never drift (the engine's contract is exact parity with
    solo runs)."""
    return (cfg.rope and cfg.attention_window is not None
            and cfg.attention_window <= cfg.max_len)


def _check_decode_budget(p: int, max_new_tokens: int,
                         cfg: TransformerConfig,
                         eos_token: int | None,
                         rolling_ok: bool = False) -> int:
    """Shared prompt/length/eos validation for generate and beam_search;
    returns ``total``.

    ``rolling_ok``: a rope + attention_window config decodes past
    ``max_len`` on a ring-buffer cache (the window must fit the cache),
    so the total-length cap is waived for eligible callers.
    """
    if p < 1:
        raise ValueError(
            "prompt must contain at least one token (decoding starts from "
            "its last position; pass a BOS token for unconditional samples)")
    total = p + max_new_tokens
    rolling = rolling_ok and rolling_eligible(cfg)
    if total > cfg.max_len and not rolling:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_len={cfg.max_len}" + (
                "" if cfg.attention_window is None or not cfg.rope else
                " (rolling decode past max_len needs rope=True, an "
                "attention_window <= max_len, and a uniform-length "
                "generate() or beam_search() call without "
                "prompt_cache)"))
    _check_eos(eos_token, cfg)
    return total


def _check_eos(eos_token, cfg: TransformerConfig) -> None:
    """ONE eos_token range check — generate, beam_search, and
    speculative_generate share it (duplicates drift)."""
    if eos_token is not None and not 0 <= eos_token < cfg.vocab_size:
        raise ValueError(
            f"eos_token must be in [0, vocab_size={cfg.vocab_size}), "
            f"got {eos_token}")


def _resolve_prefill(params, cfg: TransformerConfig, p: int,
                     use_prefill: bool | None, ragged: bool) -> bool:
    """Shared prefill-eligibility rule (ONE definition: generate and
    beam_search must not drift)."""
    can = (not ragged and 1 < p <= cfg.max_len
           and not is_quantized(params))
    if use_prefill is None:
        return can
    if use_prefill and not can:
        raise ValueError(
            "use_prefill=True needs a uniform-length (no prompt_lengths) "
            "prompt of >= 2 tokens that fits the cache (p <= max_len; "
            "longer rolling prompts teacher-force sequentially) and "
            "full-precision params (the batched prefill forward wants "
            "the training weights — quantize for decode-heavy work)")
    return use_prefill


def _resolve_prompt_cache(prompt_cache, cfg, b, p, max_new_tokens,
                          kv_int8, use_prefill):
    """ONE definition of the prompt_cache contract (generate and
    beam_search must not drift): validates the config/budget/
    quantization/batch constraints and returns ``(cache, cached_len)``
    with a batch-1 prefix fanned out to ``b`` rows."""
    pc_cache, cached_len = prompt_cache
    if cfg.attention_window is not None:
        raise ValueError("prompt_cache requires a full-cache config "
                         "(no attention_window)")
    if use_prefill is not None:
        raise ValueError(
            "use_prefill has no effect with prompt_cache (the suffix "
            "always runs as one chunked pass); drop the argument")
    if cached_len < 1:
        raise ValueError(
            f"cached prefix length must be >= 1, got {cached_len} "
            "(an empty prefix is just a plain call)")
    if cached_len > cfg.max_len - p - max_new_tokens:
        raise ValueError(
            f"cached prefix length {cached_len} + prompt {p} + "
            f"{max_new_tokens} new tokens must fit max_len="
            f"{cfg.max_len}")
    if ("k_scale" in pc_cache) != kv_int8:
        raise ValueError(
            "prompt_cache quantization must match kv_int8= (build "
            "the prefix cache with prefill(..., kv_int8=...))")
    pcb = pc_cache["k"].shape[1]
    if pcb == b:
        return pc_cache, cached_len
    if pcb == 1:
        # Shared prefix (e.g. a system prompt) prefilled once at
        # batch 1, fanned out per request.
        return jax.tree.map(
            lambda a: jnp.repeat(a, b, axis=1), pc_cache), cached_len
    raise ValueError(
        f"prompt_cache batch {pcb} incompatible with prompt "
        f"batch {b} (must match or be 1)")


def generate(params, prompt, cfg: TransformerConfig, max_new_tokens: int,
             temperature: float = 0.0, key=None,
             top_k: int | None = None, top_p: float | None = None,
             min_p: float | None = None,
             prompt_lengths=None, eos_token: int | None = None,
             use_prefill: bool | None = None,
             exact_top_k: bool = False, kv_int8: bool = False,
             prompt_cache=None):
    """Decode ``max_new_tokens`` past ``prompt [B, P]``; returns [B, P+N].

    Prefill/decode split: uniform-length prompts run through
    :func:`prefill` (one batched flash-attention forward fills the
    whole cache — MoE configs use decode-parity dense routing) and the
    scan covers only generation positions; ragged prompts fall back to
    teacher-forcing every prompt position through the cached step.
    ``use_prefill`` overrides the automatic choice (True raises if the
    config cannot prefill).
    temperature == 0 is greedy argmax; with temperature
    > 0, ``top_k``, ``top_p`` (nucleus) and/or ``min_p`` restrict the
    sampling support — all applied to the temperature-scaled logits in
    that order (top-k, then nucleus, then the min-p relative-
    probability floor), the standard composition.  ``top_k`` uses the
    approximate-threshold mask by default (round-3 change — see
    top_k_mask: exact lax.top_k costs more than the rest of the decode
    step at large vocab); ``exact_top_k=True`` restores the exact
    support.  ``top_p=1.0`` / ``min_p=0.0`` are the explicit "no
    filter" values (identical to None, and legal even on greedy
    calls; round-6 change) — the same contract as the serving
    engines' ``submit``, so parameters accepted by a served request
    replay solo exactly.

    ``prompt_cache=(cache, cached_len)`` reuses a prefilled prefix —
    the system-prompt pattern: ``prefill`` the shared prefix once (at
    the request batch or batch 1, which fans out), then pass each
    request's remaining prompt here.  The suffix is processed in ONE
    chunked pass against the existing cache, and emitted tokens match
    the concatenated-prompt run exactly (sampling is position-keyed,
    so even sampled streams agree).  Full-cache configs only; the
    cache's quantization must match ``kv_int8``.  Returns [B, p + N]
    (the prefix tokens are the caller's already).

    PRNG stream contract (changed in round 2): the key for position
    ``pos`` is ``jax.random.fold_in(key, pos)`` — a pure function of
    (key, position) — NOT the earlier sequential ``jax.random.split``
    chain.  This makes the prefill and all-sequential paths sample
    identically (the prefill scan skips prompt positions), at the cost
    that a given ``key`` emits different tokens than the pre-fold_in
    release; seed-pinned downstream tests should re-pin.

    ``eos_token`` makes completion sticky: once a row emits it, every
    later generated slot in that row is ``eos_token`` (static shapes —
    the scan always runs ``max_new_tokens`` positions; trim on the
    host).  Ragged batches: pass right-padded prompts plus
    ``prompt_lengths [B]`` (1 <= L_i <= P).  Rows are internally left-aligned at their
    ends (per-row roll), pad slots are masked out of attention and
    position ids count from each row's true start, so every row decodes
    exactly as it would alone; the result returns in the input layout —
    row i carries its L_i prompt tokens, then its N generated tokens,
    then the original padding.

    MoE caveat: decode-time routing is dense top-k *without* expert
    capacity, so logits diverge from the TRAINING forward
    (``transformer.apply`` default routing) for any token the training
    router would capacity-drop.  The matching batched semantics is
    ``apply(..., moe_dense_routing=True)`` / ``lm_nll(...,
    moe_dense_routing=True)`` — exact decode parity at any capacity
    factor (tested at 1.25); the measured capacity-vs-dense NLL gap on
    a trained model is bounded in
    tests/test_generate.py::test_moe_capacity_vs_dense_divergence_bounded.
    """
    params = _device_tree(params)
    b, p = prompt.shape
    total = _check_decode_budget(p, max_new_tokens, cfg, eos_token,
                                 rolling_ok=prompt_lengths is None)
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs an explicit PRNG key")
    # The explicit no-op values — top_p=1.0 / min_p=0.0, the serving
    # engines' "no filter" spellings — stay legal on greedy calls too,
    # so replaying a served request's parameters solo never rejects
    # what submit() accepted (round-6 parity contract).
    if ((top_k is not None
         or (top_p is not None and top_p < 1.0)
         or (min_p is not None and min_p > 0.0))
            and temperature <= 0):
        raise ValueError(
            "top_k/top_p/min_p filter a sampling distribution; they "
            "need temperature > 0 (greedy decoding always takes the "
            "single best token, so filtering would be a no-op)")
    if top_k is not None and not 1 <= top_k <= cfg.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={cfg.vocab_size}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if min_p is not None and not 0.0 <= min_p <= 1.0:
        # 0.0 is the explicit "no min-p filter" value (like submit()).
        raise ValueError(f"min_p must be in [0, 1], got {min_p}")
    cached_len = 0
    if prompt_cache is not None:
        if prompt_lengths is not None:
            raise ValueError(
                "prompt_cache requires uniform prompts "
                "(no prompt_lengths)")
        cache, cached_len = _resolve_prompt_cache(
            prompt_cache, cfg, b, p, max_new_tokens, kv_int8,
            use_prefill)
    key = key if key is not None else jax.random.key(0)

    pad_lens = None
    if prompt_lengths is not None:
        host_lens = np.asarray(prompt_lengths)
        if host_lens.shape != (b,):
            raise ValueError(
                f"prompt_lengths must be [batch={b}], got {host_lens.shape}")
        if host_lens.min() < 1 or host_lens.max() > p:
            raise ValueError(
                f"prompt_lengths must lie in [1, {p}] (the padded prompt "
                f"width), got range [{host_lens.min()}, {host_lens.max()}]")
        lens = jnp.asarray(host_lens, jnp.int32)
        pad_lens = p - lens  # left-pad sizes after end-alignment
        # Right-align each row: [tok..., pad...] -> [pad..., tok...].
        prompt = jax.vmap(jnp.roll)(prompt, pad_lens)

    # prompt_cache takes its own suffix-chunk path: prefill
    # eligibility is moot there (and its >= 2-token / full-precision
    # preconditions do not apply to _decode_chunk; the helper already
    # rejected an explicit use_prefill).
    if prompt_cache is None:
        use_prefill = _resolve_prefill(params, cfg, p, use_prefill,
                                       ragged=pad_lens is not None)

    # Buffer of emitted tokens; absolute positions — the prompt
    # occupies [cached_len, cached_len + p).
    total = cached_len + total
    buf = jnp.zeros((b, total), jnp.int32
                    ).at[:, cached_len:cached_len + p].set(prompt)
    if prompt_cache is not None:
        # Suffix prefill against the existing prefix cache: ONE chunked
        # pass writes the prompt's K/V at [cached_len, cached_len + p)
        # and attends prefix + in-chunk-causal prompt (the same
        # _decode_chunk speculative decoding trusts).  The scan then
        # starts at the last prompt position, recomputing it in place —
        # the same convention as the prefill path below.
        _, cache = _decode_chunk(params, cache, prompt,
                                 jnp.full((b,), cached_len, jnp.int32),
                                 cfg, uniform_pos=True)
        start = cached_len + p - 1
    elif use_prefill:
        # Cache holds K/V for [0, p); the scan starts at the last
        # prompt position (its step recomputes identical K/V in place
        # and yields the logits that sample token p).
        cache, _ = prefill(params, prompt, cfg, last_logits=False,
                           kv_int8=kv_int8)
        start = p - 1
    else:
        cache = init_cache(cfg, b, kv_int8=kv_int8)
        start = 0
    done = jnp.zeros((b,), bool)

    def body(carry, pos):
        buf, cache, done = carry
        tok = jax.lax.dynamic_index_in_dim(buf, pos, axis=1, keepdims=False)
        logits, cache = _decode_step(params, cache, tok, pos, cfg, pad_lens)
        # Position-keyed stream (not a split chain): the sampled tokens
        # are a function of (key, position) alone, so the prefill path
        # — whose scan skips the prompt positions — samples identically
        # to the all-sequential path.
        sub = jax.random.fold_in(key, pos)
        if temperature > 0:
            scaled = logits / temperature
            if top_k is not None:
                scaled = top_k_mask(scaled, top_k, exact=exact_top_k)
            # top_p >= 1.0 is "no nucleus filter", matching the serving
            # engines (round-6 parity fix): the sorted cumsum can
            # float-overshoot 1.0 and drop an underflowed tail token
            # that an unfiltered draw could sample, so 1.0 must mean
            # bypass everywhere or solo and served runs diverge.
            if top_p is not None and top_p < 1.0:
                scaled = top_p_mask(scaled, top_p)
            if min_p is not None and min_p > 0.0:
                scaled = min_p_mask(scaled, min_p)
            nxt = jax.random.categorical(sub, scaled, axis=-1)
        else:
            nxt = logits.argmax(axis=-1)
        nxt = nxt.astype(jnp.int32)
        # Only write past the prompt (prompt positions are forced).
        write_pos = jnp.minimum(pos + 1, total - 1)
        gen = write_pos >= cached_len + p
        if eos_token is not None:
            nxt = jnp.where(done & gen, eos_token, nxt)  # sticky fill
            done = done | (gen & (nxt == eos_token))
        keep = jax.lax.dynamic_index_in_dim(buf, write_pos, axis=1,
                                            keepdims=False)
        nxt = jnp.where(gen, nxt, keep)
        buf = jax.lax.dynamic_update_index_in_dim(buf, nxt, write_pos, axis=1)
        return (buf, cache, done), None

    (buf, _, _), _ = jax.lax.scan(body, (buf, cache, done),
                                  jnp.arange(start, total - 1))
    if pad_lens is not None:
        # Back to the input layout: prompt, generation, then padding.
        buf = jax.vmap(jnp.roll)(buf, -pad_lens)
    # prompt_cache callers get [B, p + new] — the prefix tokens are
    # theirs already; positions stay absolute internally.
    return buf[:, cached_len:] if cached_len else buf


# Ancestry attention materializes per-layer score tensors of
# B x W^2 x n_heads x S f32 (x2: scores + the post-softmax select) —
# quadratic in beam width.  Above this ceiling the physical
# parent-gather, though slower per step, is the path that fits.
ANCESTRY_SCORE_LIMIT_BYTES = 1 << 28  # 256 MiB per layer


def _ancestry_score_bytes(b: int, w: int, cfg: TransformerConfig) -> int:
    """Estimated per-layer peak of the ancestry attention intermediates:
    the [B, W, kv_heads, groups, W, S] f32 score tensor (``b`` is the
    UNtiled batch; both beam-width dims appear — quadratic in W) and
    its post-softmax one-hot select (same shape) — see _decode_chunk."""
    return 2 * b * w * w * cfg.n_heads * cfg.max_len * 4


def beam_search(params, prompt, cfg: TransformerConfig,
                max_new_tokens: int, beam_width: int = 4,
                eos_token: int | None = None,
                use_prefill: bool | None = None,
                length_penalty: float = 0.0,
                kv_int8: bool = False, prompt_cache=None,
                beam_impl: str = "auto",
                _force_physical: bool = False):
    """Beam search decode: ``prompt [B, P]`` -> ``(sequences, scores)``
    with ``sequences [B, W, P+N]`` and ``scores [B, W]`` (sum of token
    log-probabilities of the generated part), best beam first.

    ``length_penalty`` > 0 re-ranks the RETURNED beams by the GNMT
    normalization ``score / ((5 + n) / 6) ** alpha`` over each beam's
    generated length n (frozen beams stop counting at their eos), so
    short finished hypotheses compete fairly with long ones; the search
    itself still prunes on raw scores (the standard construction), and
    the returned ``scores`` are the normalized values.  0 = raw
    log-probability ordering.

    XLA-shaped like :func:`generate`: static beam width, one compiled
    ``lax.scan`` over positions, the KV cache tiled to ``B*W`` rows and
    reordered each step by a parent gather.  The first expansion runs
    on the un-tiled batch (top-W first tokens), so beams start distinct
    instead of W copies of the greedy token.  ``eos_token`` freezes a
    finished beam: its only continuation is another ``eos_token`` at
    unchanged score, so finished and live beams compete in the same
    top-W.  Uniform-length prompts only (use :func:`generate` for
    ragged batches); quantized trees decode like everywhere else, but
    force the sequential prompt path.

    ``prompt_cache=(cache, cached_len)``: reuse a prefilled shared
    prefix exactly as in :func:`generate` — the suffix runs as one
    chunked pass, hypotheses match beaming the concatenated prompt,
    and the returned sequences cover [prompt, generation] only.

    ``beam_impl`` selects how beams read their divergent histories:

    - ``"auto"`` (default): ancestry attention — unless its per-layer
      score intermediate (quadratic in beam width; see
      :data:`ANCESTRY_SCORE_LIMIT_BYTES`) would exceed the limit, in
      which case it falls back to the physical parent-gather with a
      warning.  Windowed (``attention_window``) configs take ancestry
      too — the ancestor map indexes ring SLOTS, so it stays exact
      both within ``max_len`` and on ROLLING decodes past it (rope +
      window configs, same eligibility as ``generate``; a reused
      slot's ancestry is retired in the step that overwrites its K/V).
      Round-4: previously the windowed path always paid the physical
      gather and rolling beam decode did not exist.
    - ``"ancestry"``: force ancestry attention; raises above the
      intermediate-size limit instead of silently changing cost class.
    - ``"physical"``: force the parent-gather cache reorder (the
      pre-round-3 construction; exact same hypotheses, more HBM
      traffic per step at moderate beam widths).
    """
    params = _device_tree(params)
    b, p = prompt.shape
    w = beam_width
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if not 1 <= w <= cfg.vocab_size:
        raise ValueError(
            f"beam_width must be in [1, vocab_size={cfg.vocab_size}], "
            f"got {w}")
    if length_penalty < 0:
        raise ValueError(
            f"length_penalty must be >= 0, got {length_penalty}")
    # ``_force_physical`` is the deprecated private spelling of
    # beam_impl="physical" (kept for back-compat).  Resolved HERE, with
    # the other argument checks: an invalid beam_impl or an over-limit
    # ancestry config must raise before any prompt-pass device work
    # (the checks need only b, w, cfg).
    if beam_impl not in ("auto", "ancestry", "physical"):
        raise ValueError(
            f"beam_impl must be 'auto', 'ancestry', or 'physical', "
            f"got {beam_impl!r}")
    if _force_physical:
        beam_impl = "physical"
    use_anc = beam_impl != "physical"
    if use_anc:
        est = _ancestry_score_bytes(b, w, cfg)
        if est > ANCESTRY_SCORE_LIMIT_BYTES:
            msg = (
                f"ancestry attention's per-layer score intermediate "
                f"would be ~{est / 2**20:.0f} MiB "
                f"(batch {b} x width {w}^2 x {cfg.n_heads} heads x "
                f"max_len {cfg.max_len}, f32 x2) — over the "
                f"{ANCESTRY_SCORE_LIMIT_BYTES / 2**20:.0f} MiB limit")
            if beam_impl == "ancestry":
                raise ValueError(
                    msg + "; use beam_impl='physical' (exact same "
                    "hypotheses via cache reorder) or shrink "
                    "batch/beam_width/max_len")
            warnings.warn(msg + "; falling back to the physical "
                          "parent-gather (same hypotheses, more HBM "
                          "traffic per step)", stacklevel=2)
            use_anc = False
    # Rolling decode past max_len mirrors generate()'s eligibility
    # (rope + window <= max_len ring; checked inside the budget):
    # slots wrap, and the slot-indexed ancestry update below stays
    # exact (prompt_cache is full-cache-only, hence the guard).
    total = _check_decode_budget(p, max_new_tokens, cfg, eos_token,
                                 rolling_ok=prompt_cache is None)
    prompt = jnp.asarray(prompt, jnp.int32)
    off = 0
    if prompt_cache is not None:
        # Shared-prefix reuse, same contract as generate()'s: the
        # suffix runs as ONE chunked pass against the prefix cache, the
        # search continues at absolute positions, and the returned
        # sequences cover [prompt, generation] only.
        cache, off = _resolve_prompt_cache(
            prompt_cache, cfg, b, p, max_new_tokens, kv_int8,
            use_prefill)
        _, cache = _decode_chunk(params, cache, prompt,
                                 jnp.full((b,), off, jnp.int32), cfg,
                                 uniform_pos=True)
    else:
        use_prefill = _resolve_prefill(params, cfg, p, use_prefill,
                                       ragged=False)

    # ---- prompt pass on the un-tiled [B] batch -----------------------
    if prompt_cache is not None:
        pass  # suffix chunk above already filled [off, off + p)
    elif use_prefill:
        cache, _ = prefill(params, prompt, cfg, last_logits=False,
                           kv_int8=kv_int8)
    elif p > 1:
        # One compiled scan, like generate()'s sequential path — an
        # unrolled eager loop would pay per-op dispatch for every
        # prompt position (quantized params always land here).
        def warm(cache, q):
            tok = jax.lax.dynamic_index_in_dim(prompt, q, axis=1,
                                               keepdims=False)
            _, cache = _decode_step(params, cache, tok, q, cfg)
            return cache, None

        cache, _ = jax.lax.scan(warm, init_cache(cfg, b, kv_int8=kv_int8),
                                jnp.arange(p - 1))
    else:
        cache = init_cache(cfg, b, kv_int8=kv_int8)
    # Logits for the first generated position (recomputes the last
    # prompt position in place, same as generate()'s prefill path).
    logits, cache = _decode_step(params, cache, prompt[:, p - 1],
                                 off + p - 1, cfg)
    logp0 = jax.nn.log_softmax(logits, axis=-1)  # [B, V]

    # ---- first expansion: top-W distinct first tokens ----------------
    scores, first = jax.lax.top_k(logp0, w)          # [B, W] each
    first = first.astype(jnp.int32)
    done = ((first == eos_token) if eos_token is not None
            else jnp.zeros((b, w), bool))
    lengths = jnp.ones((b, w), jnp.int32)  # generated tokens per beam

    # Tile prompt/cache per beam: row b's beams are b*W .. b*W+W-1.
    # Positions are absolute (prefix offset ``off``); the prefix region
    # of buf stays zero and is never read — the scan starts past it.
    total = off + total
    buf = jnp.zeros((b, w, total), jnp.int32)
    buf = buf.at[:, :, off:off + p].set(prompt[:, None, :])
    buf = buf.at[:, :, off + p].set(first)
    cache = jax.tree.map(
        lambda a: jnp.repeat(a, w, axis=1), cache)  # [L, B*W, S, ...]

    neg_inf = jnp.float32(-1e30)
    # Ancestry mode (full-cache configs): the tiled cache is never
    # reordered — each lane writes itself in place, and attention
    # resolves lane w's history through ``anc[b, w, s]`` = the lane
    # that wrote position s of beam w's hypothesis (see _decode_chunk's
    # beam_anc).  The physical parent-gather it replaces rewrote the
    # whole [L, B*W, S, kv, hd] cache every step and cost more than the
    # attention itself (docs/perf_serving.md finding 4).  Windowed
    # configs use it too, rolling decodes included: the ancestor map
    # is SLOT-indexed — identical to positions until the ring wraps,
    # and the scan body retires a reused slot's entry in the same step
    # that overwrites its K/V (_ancestry_attend under the band mask).
    # (use_anc resolved with the other argument checks at the top —
    # beam_impl errors must fire before any prompt-pass device work.)
    anc0 = jnp.broadcast_to(
        jnp.arange(w, dtype=jnp.int32)[None, :, None],
        (b, w, cfg.max_len))  # prompt + first token: every lane is its
    #                           own ancestor (the tiled copies agree)

    def body(carry, q):
        buf, cache, anc, scores, done, lengths = carry
        tok = jax.lax.dynamic_index_in_dim(
            buf.reshape(b * w, total), q, axis=1, keepdims=False)
        logits, cache = _decode_step(
            params, cache, tok, q, cfg,
            beam_anc=(anc, w) if use_anc else None)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(b, w, -1)
        v = logp.shape[-1]
        cand = scores[:, :, None] + logp           # [B, W, V]
        if eos_token is not None:
            # A finished beam's only continuation is eos at unchanged
            # score; everything else is pruned.
            frozen = jnp.full((v,), neg_inf).at[eos_token].set(0.0)
            cand = jnp.where(done[:, :, None],
                             scores[:, :, None] + frozen[None, None, :],
                             cand)
        scores, idx = jax.lax.top_k(cand.reshape(b, w * v), w)
        parent = (idx // v).astype(jnp.int32)      # [B, W]
        token = (idx % v).astype(jnp.int32)
        # Reorder beams by parent: buf rows, done flags — and either
        # the ancestry map (cheap) or the cache rows (physical impl).
        buf = jnp.take_along_axis(buf, parent[:, :, None], axis=1)
        buf = buf.at[:, :, q + 1].set(token)
        done = jnp.take_along_axis(done, parent, axis=1)
        lengths = jnp.take_along_axis(lengths, parent, axis=1)
        lengths = jnp.where(done, lengths, lengths + 1)
        if eos_token is not None:
            done = done | (token == eos_token)
        if use_anc:
            # Kept beam w inherits parent's ancestry for s <= q (the
            # parent's lane wrote position q this step); next step's
            # write SLOT is its own lane.  Slot-indexed (pos % C): the
            # identity while total <= max_len, and under ROLLING decode
            # it retires the reused slot's stale ancestry in the same
            # step that overwrites its K/V — the attention for step q
            # runs before this update, so no read ever sees the reset
            # early, and the band mask never reaches the evicted
            # position afterwards.
            anc = jnp.take_along_axis(anc, parent[:, :, None], axis=1)
            anc = anc.at[:, :, (q + 1) % cfg.max_len].set(
                jnp.arange(w, dtype=jnp.int32)[None, :])
        else:
            flat_parent = (parent
                           + jnp.arange(b, dtype=jnp.int32)[:, None] * w
                           ).reshape(b * w)
            cache = jax.tree.map(lambda a: a[:, flat_parent], cache)
        return (buf, cache, anc, scores, done, lengths), None

    if max_new_tokens > 1:
        (buf, _, _, scores, _, lengths), _ = jax.lax.scan(
            body, (buf, cache, anc0, scores, done, lengths),
            jnp.arange(off + p, total - 1))
    if length_penalty > 0:
        norm = scores / jnp.power((5.0 + lengths) / 6.0, length_penalty)
        order = jnp.argsort(-norm, axis=1)
        buf = jnp.take_along_axis(buf, order[:, :, None], axis=1)
        scores = jnp.take_along_axis(norm, order, axis=1)
    return (buf[:, :, off:] if off else buf), scores
