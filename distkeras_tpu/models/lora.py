"""LoRA fine-tuning: low-rank adapters over a frozen transformer base.

Full fine-tuning updates (and keeps optimizer moments for) every
parameter; LoRA trains a rank-r delta ``W + (alpha/r) * A @ B`` on the
chosen projections only — the adapter tree is ~1000x smaller than the
base at typical ranks, so optimizer state shrinks accordingly and the
finished artifact is a small delta that merges back into the base for
serving (``lora_merge`` -> every decode path in models/generate and
models/speculative works unchanged).

TPU-first design choice: the adapters merge into the base INSIDE the
jitted step (one fused add per target weight, O(params) elementwise —
noise next to the matmuls) instead of patching each matmul with a
second low-rank contraction.  The forward therefore stays byte-for-byte
the standard :func:`~distkeras_tpu.models.transformer.apply`, which
means LoRA composes with every mesh axis, attention path (ring,
window, pipeline), remat policy, chunked CE, and packed segments with
zero new parallelism code — GSPMD shards the merge like any other
elementwise op.  Gradients flow only into A/B (the base is
stop_gradient'ed; its zero cotangents fold away in XLA).

The reference has no fine-tuning story (it trains Keras models from
scratch, reference: distkeras/trainers.py); this module is TPU-first
surplus on the train-then-adapt axis.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from distkeras_tpu.models import transformer as tfm


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """``rank`` r and scale ``alpha`` (delta = alpha/r * A@B);
    ``targets`` name the adapted weights: attention projections
    ("wq", "wk", "wv", "wo") and/or the dense-FFN mats ("w1", "w2")."""

    rank: int = 8
    alpha: float = 16.0
    targets: tuple = ("wq", "wv")


# target -> (group, a-shape fn, b-shape fn, merge einsum).  Shapes get
# (cfg, r); the leading L axis stacks layers like every other param.
_ATTN = {
    "wq": (lambda c, r: (c.n_layers, c.d_model, r),
           lambda c, r: (c.n_layers, r, c.n_heads, c.head_dim),
           "ldr,lrhk->ldhk"),
    "wk": (lambda c, r: (c.n_layers, c.d_model, r),
           lambda c, r: (c.n_layers, r, c.kv_heads, c.head_dim),
           "ldr,lrhk->ldhk"),
    "wv": (lambda c, r: (c.n_layers, c.d_model, r),
           lambda c, r: (c.n_layers, r, c.kv_heads, c.head_dim),
           "ldr,lrhk->ldhk"),
    "wo": (lambda c, r: (c.n_layers, c.n_heads, c.head_dim, r),
           lambda c, r: (c.n_layers, r, c.d_model),
           "lhkr,lrd->lhkd"),
}
_FFN = {
    "w1": (lambda c, r: (c.n_layers, c.d_model, r),
           lambda c, r: (c.n_layers, r, c.d_ff),
           "ldr,lrf->ldf"),
    "w2": (lambda c, r: (c.n_layers, c.d_ff, r),
           lambda c, r: (c.n_layers, r, c.d_model),
           "lfr,lrd->lfd"),
}


def _validate(cfg: tfm.TransformerConfig, lcfg: LoRAConfig):
    known = set(_ATTN) | set(_FFN)
    bad = set(lcfg.targets) - known
    if bad:
        raise ValueError(f"unknown LoRA targets {sorted(bad)}; "
                         f"known: {sorted(known)}")
    if not lcfg.targets:
        raise ValueError("LoRAConfig.targets is empty — nothing to train")
    if len(set(lcfg.targets)) != len(lcfg.targets):
        raise ValueError(
            f"duplicate LoRA targets in {lcfg.targets} — likely a typo "
            "for a different projection; a duplicate would silently "
            "collapse into one adapter")
    if lcfg.rank < 1:
        raise ValueError(f"rank must be >= 1, got {lcfg.rank}")
    if cfg.num_experts and set(lcfg.targets) & set(_FFN):
        raise ValueError(
            "LoRA FFN targets (w1/w2) need a dense-FFN config; this MoE "
            "config's expert mats are not adapted (attention targets "
            "work fine on MoE configs)")


def lora_init(rng, cfg: tfm.TransformerConfig, lcfg: LoRAConfig):
    """Adapter tree {"attn": {name: {"a", "b"}}, "ffn": {...}}.

    Standard LoRA init: A ~ N(0, 1/sqrt(d_in)), B = 0 — the delta
    starts at exactly zero, so step 0 reproduces the base model.
    """
    _validate(cfg, lcfg)
    tree = {}
    keys = jax.random.split(rng, len(lcfg.targets))
    for key, name in zip(keys, sorted(lcfg.targets)):
        group, specs = (("attn", _ATTN) if name in _ATTN
                        else ("ffn", _FFN))
        a_shape = specs[name][0](cfg, lcfg.rank)
        b_shape = specs[name][1](cfg, lcfg.rank)
        fan_in = math.prod(a_shape[1:-1])
        tree.setdefault(group, {})[name] = {
            "a": (jax.random.normal(key, a_shape, jnp.float32)
                  / math.sqrt(fan_in)),
            "b": jnp.zeros(b_shape, jnp.float32),
        }
    return tree


def lora_merge(params, adapters, cfg: tfm.TransformerConfig,
               lcfg: LoRAConfig):
    """Base params + scaled low-rank deltas -> a servable params tree
    (same structure as ``tfm.init_params``; feed to apply/generate/
    quantize_params/save_lm unchanged)."""
    _validate(cfg, lcfg)
    scale = lcfg.alpha / lcfg.rank
    params = dict(params)
    layers = dict(params["layers"])
    for group, specs in (("attn", _ATTN), ("ffn", _FFN)):
        if group not in adapters:
            continue
        sub = dict(layers[group])
        for name, ab in adapters[group].items():
            eq = specs[name][2]
            delta = jnp.einsum(eq, ab["a"], ab["b"]) * scale
            sub[name] = sub[name] + delta.astype(sub[name].dtype)
        layers[group] = sub
    params["layers"] = layers
    return params


def make_lora_loss(cfg: tfm.TransformerConfig, lcfg: LoRAConfig):
    """An ``lm_loss``-signature callable over the packed
    ``(adapters, base)`` tree: merges (base frozen via stop_gradient)
    then defers to :func:`~distkeras_tpu.models.transformer.lm_loss` —
    plug into ``make_train_step(..., loss_fn=...)``."""

    def loss(packed, tokens, cfg_, attention_fn=None, apply_fn=None,
             dropout_rng=None, hidden_fn=None, segment_ids=None):
        adapters, base = packed
        merged = lora_merge(jax.lax.stop_gradient(base), adapters,
                            cfg_, lcfg)
        return tfm.lm_loss(merged, tokens, cfg_, attention_fn, apply_fn,
                           dropout_rng, hidden_fn, segment_ids)

    del cfg
    return loss


def lora_mask(packed):
    """Trainability mask over the packed ``(adapters, base)`` tree for
    ``optax.masked``: True on adapter leaves, False on the base — the
    optimizer allocates moments for the adapters ONLY (the memory win
    that makes LoRA LoRA)."""
    adapters, base = packed
    return (jax.tree.map(lambda _: True, adapters),
            jax.tree.map(lambda _: False, base))
