"""Speculative decoding: draft-model-assisted generation.

The sequential decode loop is HBM-bandwidth-bound — every generated
token re-reads the full weight set (models/quant's motivation).
Speculative decoding attacks the *step count* instead of the bytes: a
small draft model proposes ``n_draft`` tokens sequentially (cheap
weight reads), and the target model scores all of them in ONE chunked
forward (:func:`~distkeras_tpu.models.generate._decode_chunk` — the
weight reads amortize over n_draft+1 positions exactly like prefill).
Accepted prefixes advance several positions per target pass; mismatches
cost one target pass for one corrective token — never worse than
plain decoding in target-pass count, and the output is EXACT:

- greedy (``temperature=0``): every emitted token is the target's
  argmax given its prefix (acceptance = argmax agreement; the
  corrective token is the target argmax), so the sequence equals
  ``generate``'s greedy rollout up to float ties — the chunked and
  per-step programs reduce in different orders (~1e-6 relative), and
  only a near-exact tie between two vocab entries can flip an argmax
  between them.
- sampled (``temperature>0``): the Leviathan/Chen speculative-sampling
  rule — accept draft token x with probability min(1, p(x)/q(x)), on
  first rejection sample from norm(max(p - q, 0)) — makes every output
  token an exact sample from the target distribution (the classic
  coupling argument), regardless of draft quality.  Draft quality only
  moves the acceptance rate, i.e. the speed.

TPU-shaped: one ``lax.while_loop`` whose body is k static draft steps
+ one static [B, k+1] target chunk; per-row accept divergence is
handled by per-row cache offsets, so the whole batch shares one
compiled program.  The reference has no serving story at all
(reference: distkeras/predictors.py runs the training forward) — this
module is TPU-first surplus on the rebuild's serving axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distkeras_tpu.models.generate import (
    _decode_chunk,
    init_cache,
    prefill,
    rolling_eligible,
)
from distkeras_tpu.models.quant import is_quantized
from distkeras_tpu.models.transformer import TransformerConfig


def _validate(params, draft_params, cfg, draft_cfg, p, max_new_tokens,
              n_draft, temperature, key, eos_token=None):
    from distkeras_tpu.models.generate import _check_eos

    _check_eos(eos_token, cfg)
    if draft_cfg.vocab_size != cfg.vocab_size:
        raise ValueError(
            f"draft vocab_size {draft_cfg.vocab_size} != target "
            f"{cfg.vocab_size} — the models must share a tokenizer")
    if n_draft < 1:
        raise ValueError(f"n_draft must be >= 1, got {n_draft}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if p < 1:
        raise ValueError("prompt must contain at least one token")
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs an explicit PRNG key")
    total = p + max_new_tokens
    # Full-cache configs: the verify chunk reaches position cur +
    # n_draft <= total - 1 + n_draft, so the cache needs n_draft slots
    # of slack past the generated length (no silent clamping — see
    # _decode_chunk).  Windowed configs (round-5): the ring absorbs
    # any total, but (a) rolling past max_len needs rope + a fitting
    # window (rolling_eligible — same bound as generate), (b) the
    # prompt warm pass writes [0, p) without wrapping, and (c) the
    # write-ahead window must satisfy window + n_draft + 1 <= max_len
    # so a rejected tail's ring slots alias OUTSIDE every live query's
    # band until real decoding overwrites them (the _decode_chunk
    # chunk-fits-ring bound with T = n_draft + 1).
    for name, c in (("cfg", cfg), ("draft_cfg", draft_cfg)):
        if c.attention_window is None:
            if total + n_draft > c.max_len:
                raise ValueError(
                    f"speculative decoding needs cache slack: "
                    f"{name}.max_len={c.max_len} < prompt ({p}) + "
                    f"max_new_tokens ({max_new_tokens}) + n_draft "
                    f"({n_draft})")
            continue
        if c.attention_window + n_draft + 1 > c.max_len:
            raise ValueError(
                f"speculative decoding on a ring cache needs "
                f"{name}.attention_window ({c.attention_window}) + "
                f"n_draft + 1 ({n_draft + 1}) <= max_len "
                f"({c.max_len}): the verify chunk's rejected tail "
                "must alias outside every live query's band")
        if p > c.max_len:
            raise ValueError(
                f"prompt ({p}) exceeds {name}.max_len={c.max_len} "
                "(the prompt warm pass cannot wrap the ring)")
        if total + n_draft > c.max_len and not rolling_eligible(c):
            raise ValueError(
                f"speculative decoding past {name}.max_len={c.max_len} "
                "rolls the ring cache, which needs rope=True and "
                f"attention_window <= max_len (got rope={c.rope})")
    return total


def speculative_accept(p_logp, q_logp, d, u):
    """The Leviathan/Chen acceptance + residual math, shared by the
    solo loop and :class:`~distkeras_tpu.serving.SpeculativeBatcher`
    (their draw KEYS differ — shared key per batch vs per-lane
    iteration-keyed — but this math must stay bit-identical or the
    engine's exact-parity contract silently breaks).

    ``p_logp [B, k+1, V]`` target log-probs, ``q_logp [B, k, V]``
    draft log-probs, ``d [B, k]`` draft tokens, ``u [B, k]`` uniform
    draws.  Returns ``(n [B], corrective_logits [B, V])``: accepted
    prefix lengths and the log-residual ``log(norm(max(p - q, 0)))``
    at the first rejected position (past-the-end the residual reduces
    to p itself — q padded with zeros; rs == 0 iff p == q, where
    rejection has probability 0, but the normalizer is guarded)."""
    k = q_logp.shape[1]
    p_d = jnp.take_along_axis(p_logp[:, :k], d[..., None],
                              axis=-1)[..., 0]
    q_d = jnp.take_along_axis(q_logp, d[..., None], axis=-1)[..., 0]
    accept = u < jnp.exp(jnp.minimum(p_d - q_d, 0.0))      # [B, k]
    n = jnp.cumprod(accept, axis=1).sum(axis=1)            # [B]
    p_n = jnp.take_along_axis(jnp.exp(p_logp), n[:, None, None],
                              axis=1)[:, 0]                # [B, V]
    q_pad = jnp.concatenate(
        [jnp.exp(q_logp), jnp.zeros_like(q_logp[:, :1])], axis=1)
    q_n = jnp.take_along_axis(q_pad, n[:, None, None], axis=1)[:, 0]
    r = jnp.maximum(p_n - q_n, 0.0)
    rs = r.sum(axis=-1, keepdims=True)
    r = jnp.where(rs > 0, r / jnp.maximum(rs, 1e-30), p_n)
    return n, jnp.log(r + 1e-30)


def _warm_cache(model_params, model_cfg, buf, p, kv_int8=False):
    """Fill a cache for prompt positions 0..p-2 (position p-1 is
    re-processed by the first verify/draft chunk, like generate()'s
    prefill path).  Prefill when eligible; otherwise (quantized tree or
    1-token prompt) CHUNKED teacher-forcing — the weight reads amortize
    over up to 128 positions per pass (sequential T=1 warming would
    re-read the full weight set p-1 times, the exact cost this module
    exists to avoid); 128 bounds the [B, T, heads, S] attention
    buffer."""
    b = buf.shape[0]
    if p > 1 and not is_quantized(model_params):
        cache, _ = prefill(model_params, buf[:, :p], model_cfg,
                           last_logits=False, kv_int8=kv_int8)
        return cache
    cache = init_cache(model_cfg, b, kv_int8=kv_int8)
    start = 0
    while start < p - 1:  # static python loop: p is a trace constant
        width = min(128, p - 1 - start)
        _, cache = _decode_chunk(model_params, cache,
                                 buf[:, start:start + width],
                                 jnp.full((b,), start, jnp.int32),
                                 model_cfg, uniform_pos=True)
        start += width
    return cache


def speculative_generate(params, draft_params, prompt, cfg: TransformerConfig,
                         draft_cfg: TransformerConfig, max_new_tokens: int,
                         n_draft: int = 4, temperature: float = 0.0,
                         key=None, eos_token: int | None = None,
                         kv_int8: bool = False):
    """Decode ``max_new_tokens`` past ``prompt [B, P]`` with draft
    assistance; returns ``(tokens [B, P+N], stats)``.

    ``stats`` (device scalars): ``iterations`` — target passes run;
    ``acceptance_rate`` — accepted draft tokens / draft tokens proposed
    by unfinished rows (the serving speedup knob: each target pass
    advances 1 + acceptance_rate * n_draft positions on average).

    ``eos_token`` is sticky like :func:`generate`'s: once a row's
    ACCEPTED stream emits it, the row's remaining generated slots fill
    with ``eos_token`` and the row stops consuming target passes
    (static shapes; trim on the host).

    Uniform-length prompts; no top-k/top-p composition in this entry
    (use :func:`~distkeras_tpu.models.generate.generate` when filtered
    sampling matters more than latency).  Quantized (int8) target or
    draft trees work — the chunk path dequantizes per read, and the
    prompt falls back to sequential warm for a quantized tree.
    ``kv_int8=True`` stores BOTH models' caches int8 (generate's
    cache-byte lever; the per-row accept-divergence writes carry the
    scale leaves through the same row-update path).

    Windowed configs compose (round-5): either model may run a
    rope + ``attention_window`` ring cache — including ROLLING past
    ``max_len`` — under ``window + n_draft + 1 <= max_len`` (verify
    chunks write through _decode_chunk's modular ring scatter; the
    bound keeps a rejected tail's slots outside every live query's
    band).  Output parity with windowed ``generate`` is exact, wraps
    included.
    """
    from distkeras_tpu.models.generate import _device_tree

    params = _device_tree(params)
    draft_params = _device_tree(draft_params)
    b, p = prompt.shape
    total = _validate(params, draft_params, cfg, draft_cfg, p,
                      max_new_tokens, n_draft, temperature, key,
                      eos_token)
    key = key if key is not None else jax.random.key(0)
    k = n_draft
    prompt = jnp.asarray(prompt, jnp.int32)
    # k+1 scratch columns past `total`: every iteration writes its full
    # [k+1] window at cur+1 unconditionally — rejected-tail garbage
    # lands beyond the row's final position and is either rewritten by
    # the next window (it starts exactly where the accepted prefix
    # ended) or falls in the scratch region; finalized positions are
    # never touched again.  No clamping, no read-modify-write.  The
    # width matters: a DONE row (cur = total-1) still writes its window
    # at start total, so the scratch must hold all k+1 columns —
    # one column less and dynamic_update_slice clamps the start back
    # onto the row's final token and corrupts it (caught by
    # test_nonuniform_acceptance_rows_finish_cleanly).
    buf = jnp.zeros((b, total + k + 1), jnp.int32).at[:, :p].set(prompt)
    tcache = _warm_cache(params, cfg, buf, p, kv_int8=kv_int8)
    dcache = _warm_cache(draft_params, draft_cfg, buf, p, kv_int8=kv_int8)

    cur0 = jnp.full((b,), p - 1, jnp.int32)  # last FINAL position per row
    idx = jnp.arange(k + 1)

    def body(state):
        buf, tcache, dcache, cur, it, acc, props = state
        kit = jax.random.fold_in(key, it)

        # ---- k sequential draft proposals, per-row positions.
        # The FIRST step is a T=2 chunk over [buf[cur-1], buf[cur]]:
        # the draft proposes d_k but never processes it, so after a
        # full-acceptance iteration slot cur-1 (== old cur + k) is
        # unwritten in the draft cache — attending its zero row would
        # silently skew every later proposal.  Rewriting cur-1
        # alongside cur closes the gap (the target cache has no gap:
        # its verify chunk writes all k+1 slots).  At cur == 0 there
        # is no previous slot; the clamped chunk covers positions
        # [0, 1] and slot 1's garbage is overwritten by the j == 0
        # proposal step before anything reads it.
        pos0 = jnp.maximum(cur - 1, 0)
        first = jax.vmap(lambda row, s: jax.lax.dynamic_slice(
            row, (s,), (2,)))(buf, pos0)
        lg2, dcache = _decode_chunk(draft_params, dcache, first, pos0,
                                    draft_cfg)
        lg = jnp.take_along_axis(
            lg2, (cur - pos0)[:, None, None], axis=1)[:, 0]   # [B, V]
        d_toks, q_logps = [], []
        for j in range(k):
            if temperature > 0:
                logp = jax.nn.log_softmax(lg / temperature, axis=-1)
                nxt = jax.random.categorical(
                    jax.random.fold_in(kit, j), logp, axis=-1)
                q_logps.append(logp)
            else:
                nxt = lg.argmax(axis=-1)
            nxt = nxt.astype(jnp.int32)
            d_toks.append(nxt)
            if j < k - 1:
                lgj, dcache = _decode_chunk(draft_params, dcache,
                                            nxt[:, None], cur + 1 + j,
                                            draft_cfg)
                lg = lgj[:, 0]
        d = jnp.stack(d_toks, axis=1)                        # [B, k]

        # ---- one target pass over [token@cur, d_1..d_k]
        chunk = jnp.concatenate(
            [jnp.take_along_axis(buf, cur[:, None], axis=1), d], axis=1)
        tlog, tcache = _decode_chunk(params, tcache, chunk, cur, cfg)

        if temperature > 0:
            p_logp = jax.nn.log_softmax(tlog / temperature, -1)  # [B,k+1,V]
            q_logp = jnp.stack(q_logps, axis=1)                  # [B,k,V]
            u = jax.random.uniform(jax.random.fold_in(kit, k + 1), (b, k))
            # Acceptance + residual: the ONE definition, shared with
            # the serving engine (speculative_accept docstring).
            n, corr_logits = speculative_accept(p_logp, q_logp, d, u)
            corrective = jax.random.categorical(
                jax.random.fold_in(kit, k + 2),
                corr_logits, axis=-1).astype(jnp.int32)
        else:
            t_pred = tlog.argmax(axis=-1).astype(jnp.int32)      # [B, k+1]
            match = d == t_pred[:, :k]
            n = jnp.cumprod(match, axis=1).sum(axis=1)           # [B]
            corrective = jnp.take_along_axis(t_pred, n[:, None],
                                             axis=1)[:, 0]

        # ---- write [d_1..d_n, corrective, <garbage>] at cur+1 per row
        done = cur >= (total - 1)
        advance = jnp.where(done, 0,
                            jnp.minimum(n + 1, total - 1 - cur)
                            ).astype(jnp.int32)
        d_ext = jnp.concatenate([d, d[:, -1:]], axis=1)          # [B, k+1]
        win = jnp.where(idx[None, :] < n[:, None], d_ext,
                        corrective[:, None]).astype(jnp.int32)
        if eos_token is not None:
            # Sticky EOS: truncate the row's advance at its first
            # accepted eos; the tail fill below pads the rest and the
            # cur jump stops the row from consuming further passes.
            is_eos = (win == eos_token) & (idx[None, :] < advance[:, None])
            hit = is_eos.any(axis=1)
            first = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
            advance = jnp.where(hit, first + 1, advance)
        buf = jax.vmap(lambda row, w, s: jax.lax.dynamic_update_slice(
            row, w, (s,)))(buf, win, cur + 1)
        if eos_token is not None:
            span = jnp.arange(buf.shape[1])
            fill = (hit[:, None]
                    & (span[None, :] > (cur + advance)[:, None])
                    & (span[None, :] < total))
            buf = jnp.where(fill, eos_token, buf)
            cur_next = jnp.where(hit, total - 1, cur + advance)
        else:
            cur_next = cur + advance

        live = (~done).astype(jnp.int32)
        acc = acc + (n * live).sum()
        props = props + k * live.sum()
        return (buf, tcache, dcache, cur_next, it + 1, acc, props)

    def cond(state):
        cur = state[3]
        return jnp.any(cur < total - 1)

    state = (buf, tcache, dcache, cur0, jnp.int32(0), jnp.int32(0),
             jnp.int32(0))
    buf, _, _, _, it, acc, props = jax.lax.while_loop(cond, body, state)
    stats = {"iterations": it,
             "acceptance_rate": acc / jnp.maximum(props, 1)}
    return buf[:, :total], stats
