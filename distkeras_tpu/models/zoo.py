"""Model zoo: the reference's example workloads as Keras 3 builders.

One builder per BASELINE.json config (the five benchmark workloads):
MNIST MLP, CIFAR-10 CNN, ATLAS-Higgs tabular MLP, IMDB LSTM, and the
ResNet-50 stretch config.  The reference defines these ad hoc inside
example notebooks (reference: examples/mnist notebook, workflow.ipynb);
here they are library functions so benchmarks and tests share one
definition.

All models end in *logits* (no softmax): pair them with the
``*_crossentropy`` losses, which fold log-softmax into the loss — the
numerically stable and XLA-fusion-friendly layout.
"""

from __future__ import annotations


def mnist_mlp(hidden=(500, 300), num_classes: int = 10, input_dim: int = 784,
              seed: int | None = None):
    """3-layer MLP, the reference's canonical MNIST architecture
    (reference: examples mnist notebook — Dense 500/300/10)."""
    import keras

    if seed is not None:
        keras.utils.set_random_seed(seed)
    layers = [keras.Input((input_dim,))]
    for h in hidden:
        layers.append(keras.layers.Dense(h, activation="relu"))
    layers.append(keras.layers.Dense(num_classes))
    return keras.Sequential(layers, name="mnist_mlp")


def cifar_cnn(num_classes: int = 10, input_shape=(32, 32, 3),
              seed: int | None = None):
    """Small CNN for CIFAR-10 (BASELINE.json config #2)."""
    import keras

    if seed is not None:
        keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.Input(input_shape),
        keras.layers.Conv2D(32, 3, padding="same", activation="relu"),
        keras.layers.Conv2D(32, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Conv2D(64, 3, padding="same", activation="relu"),
        keras.layers.Conv2D(64, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(512, activation="relu"),
        keras.layers.Dense(num_classes),
    ], name="cifar_cnn")


def higgs_mlp(input_dim: int = 28, num_classes: int = 2,
              hidden=(600, 600, 600), seed: int | None = None):
    """Tabular MLP for the ATLAS Higgs task (reference: workflow.ipynb
    trains a dense net on ~28 engineered physics features)."""
    import keras

    if seed is not None:
        keras.utils.set_random_seed(seed)
    layers = [keras.Input((input_dim,))]
    for h in hidden:
        layers.append(keras.layers.Dense(h, activation="relu"))
    layers.append(keras.layers.Dense(num_classes))
    return keras.Sequential(layers, name="higgs_mlp")


def imdb_lstm(vocab_size: int = 20000, embed_dim: int = 128,
              lstm_units: int = 128, maxlen: int = 128,
              seed: int | None = None, fused: bool = True):
    """LSTM sentiment classifier (BASELINE.json config #4).

    Binary logits output; use ``binary_crossentropy``.  ``fused=True``
    (default) uses :class:`~distkeras_tpu.models.rnn.FusedLSTM` — the
    weight-compatible TPU restructuring of ``keras.layers.LSTM`` that
    hoists the input projection out of the recurrence; ``fused=False``
    keeps the stock Keras layer (the ablation baseline).
    """
    import keras

    from distkeras_tpu.models.rnn import FusedLSTM

    if seed is not None:
        keras.utils.set_random_seed(seed)
    lstm = (FusedLSTM(lstm_units) if fused
            else keras.layers.LSTM(lstm_units))
    return keras.Sequential([
        keras.Input((maxlen,), dtype="int32"),
        keras.layers.Embedding(vocab_size, embed_dim),
        lstm,
        keras.layers.Dense(1),
    ], name="imdb_lstm")


def resnet50(num_classes: int = 1000, input_shape=(224, 224, 3),
             seed: int | None = None):
    """ResNet-50 (BASELINE.json stretch config), random init, logits out."""
    import keras

    if seed is not None:
        keras.utils.set_random_seed(seed)
    return keras.applications.ResNet50(
        weights=None, input_shape=input_shape, classes=num_classes,
        classifier_activation=None)


ZOO = {
    "mnist_mlp": mnist_mlp,
    "cifar_cnn": cifar_cnn,
    "higgs_mlp": higgs_mlp,
    "imdb_lstm": imdb_lstm,
    "resnet50": resnet50,
}
