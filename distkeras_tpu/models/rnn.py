"""TPU-first recurrent layers.

The reference's deepest sequence model is a Keras LSTM trained with
``model.train_on_batch`` (reference: examples IMDB config, via
distkeras/workers.py) — the kernels were whatever the 2017 Keras
backend emitted.  On TPU the generic per-timestep LSTM is the worst
case: two small matmuls per step inside a length-T sequential loop,
~0.1% MFU measured (BASELINE.md, IMDB-LSTM line).

:class:`FusedLSTM` is a drop-in, weight-compatible replacement for
``keras.layers.LSTM`` restructured for the MXU:

- The input projection for *all* timesteps is hoisted out of the
  recurrence into one ``[B*T, E] @ [E, 4H]`` matmul — large, batched,
  MXU-shaped, and it amortizes the weight read of ``kernel`` from T
  HBM touches to one.
- The ``lax.scan`` body keeps only what is truly sequential: one
  ``[B, H] @ [H, 4H]`` recurrent matmul plus fused elementwise gates.
- Identical parameterization to Keras (``kernel [E, 4H]``,
  ``recurrent_kernel [H, 4H]``, ``bias [4H]``, gate order i|f|g|o,
  ``unit_forget_bias``): ``get_weights``/``set_weights`` interchange
  with ``keras.layers.LSTM`` and outputs match to f32 tolerance.

JAX-backend only (the package forces ``KERAS_BACKEND=jax``); masking
and the exotic LSTM knobs (``recurrent_dropout``, non-default
activations) are intentionally out of scope — pair it with the
standard config the reference workload uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import keras
import numpy as np


@keras.saving.register_keras_serializable(package="distkeras_tpu")
class FusedLSTM(keras.layers.Layer):
    """LSTM with the input projection hoisted out of the recurrence.

    Args:
      units: hidden size H.
      return_sequences: return ``[B, T, H]`` instead of the final
        ``[B, H]``.
    """

    def __init__(self, units: int, return_sequences: bool = False, **kw):
        super().__init__(**kw)
        if units < 1:
            raise ValueError(f"units must be >= 1, got {units}")
        self.units = units
        self.return_sequences = return_sequences

    def build(self, input_shape):
        if len(input_shape) != 3:
            raise ValueError(
                f"FusedLSTM expects [batch, time, features], got "
                f"{input_shape}")
        e = int(input_shape[-1])
        u = self.units

        def unit_forget_bias(shape, dtype=None):
            b = np.zeros(shape, dtype="float32")
            b[u:2 * u] = 1.0  # forget gate opens at init (Keras default)
            return b

        self.kernel = self.add_weight(
            shape=(e, 4 * u), initializer="glorot_uniform", name="kernel")
        self.recurrent_kernel = self.add_weight(
            shape=(u, 4 * u), initializer="orthogonal",
            name="recurrent_kernel")
        self.bias = self.add_weight(
            shape=(4 * u,), initializer=unit_forget_bias, name="bias")

    def call(self, x):
        u = self.units
        # One big projection for every timestep (the MXU hot path);
        # bias folds in here so the scan body is add-free.
        xp = jnp.einsum("bte,ef->btf", x, self.kernel) + self.bias
        rk = jnp.asarray(self.recurrent_kernel)

        def step(carry, xt):
            h, c = carry
            z = xt + h @ rk
            i = jax.nn.sigmoid(z[:, :u])
            f = jax.nn.sigmoid(z[:, u:2 * u])
            g = jnp.tanh(z[:, 2 * u:3 * u])
            o = jax.nn.sigmoid(z[:, 3 * u:])
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h if self.return_sequences else None

        b = xp.shape[0]
        h0 = jnp.zeros((b, u), xp.dtype)
        (h, _), ys = jax.lax.scan(step, (h0, h0), jnp.swapaxes(xp, 0, 1))
        return jnp.swapaxes(ys, 0, 1) if self.return_sequences else h

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (*input_shape[:2], self.units)
        return (input_shape[0], self.units)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(units=self.units,
                   return_sequences=self.return_sequences)
        return cfg
