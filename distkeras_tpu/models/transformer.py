"""Functional transformer LM: the framework's flagship composite model.

The reference's deepest model is a one-layer LSTM (reference: examples,
IMDB config); this module is where the TPU rebuild goes past it — a
decoder-only transformer written as pure functions over a dict pytree,
designed so every parallelism axis of the device mesh applies:

- **data**: batch sharded via the batch PartitionSpec,
- **model** (TP): Megatron layout — QKV/FFN-in column-sharded,
  attn-out/FFN-out row-sharded (XLA inserts the psum/reduce-scatter),
- **seq** (SP): ring attention (distkeras_tpu.parallel.ring) when
  ``attention_fn`` is a ring wrapper; activations sharded [data, seq],
- **expert** (EP): Switch-style top-1 MoE FFN with capacity dropping;
  expert weights sharded over ``expert`` (XLA inserts the all-to-alls
  around the dispatch/combine einsums),
- **pipeline** (PP): the per-layer params are stacked [L, ...] so a
  contiguous slice of layers forms a stage
  (distkeras_tpu.parallel.pipeline consumes ``block_apply``).

No flax: parameters are plain nested dicts so sharding rules regex over
key-paths (parallel.sharding.ShardingPlan.tree_shardings) and the
driver's dry-run can jit the full train step with explicit
NamedShardings.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distkeras_tpu.ops.attention import flash_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 128
    # MoE: 0 experts = dense FFN.  With E > 0 every layer's FFN is a
    # top-k MoE with `capacity_factor` slack per expert: moe_top_k=1 is
    # Switch routing (combine weight = the raw top-1 probability),
    # moe_top_k=2 is GShard/Mixtral-style top-2 (combine weights =
    # top-k probabilities renormalized over the selected experts;
    # first choices take capacity priority over second choices).
    # Expert capacity scales with k: cap = capacity_factor * k * N / E
    # (capacity_factor stays "slack per assignment" at any k).
    num_experts: int = 0
    moe_top_k: int = 1
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    dtype: str = "float32"  # activation/compute dtype (bfloat16 on TPU)
    # Rotary position embeddings (half-split rotation on q/k) instead of
    # the learned pos_emb table: position information becomes relative
    # inside attention, the standard long-context choice (no trained
    # table capping usable length at max_len — max_len still bounds the
    # decode KV cache).  Requires an even head_dim.
    rope: bool = False
    rope_theta: float = 10000.0
    # Residual dropout rate (embedding, attention output, FFN/MoE
    # output).  Active only when a dropout_rng is supplied (training);
    # inference and eval are always deterministic.  Not supported under
    # pipeline parallelism (the compiled tick schedule has no
    # per-microbatch rng stream) — LMTrainer rejects the combination.
    dropout: float = 0.0
    # Grouped-query attention: fewer K/V heads than Q heads (None =
    # n_heads = vanilla MHA; 1 = multi-query).  Shrinks the decode KV
    # cache and its HBM traffic by n_heads/n_kv_heads; K/V are repeated
    # to full heads for the attention kernels (training compute
    # unchanged, the cache is the win).
    n_kv_heads: int | None = None
    # Rematerialize each block in the backward pass (jax.checkpoint):
    # activation memory drops from O(layers) to O(1) blocks at ~1/3 more
    # FLOPs — the standard long-context/deep-model trade on TPU, where
    # HBM, not MXU, is the usual ceiling.
    remat: bool = False
    # Selective remat (only with remat=True): which intermediates the
    # backward may keep instead of recomputing.  None = recompute
    # everything (max memory saving); "dots" saves matmul outputs
    # (recompute only the cheap elementwise work — most of the no-remat
    # speed at a fraction of its memory); "dots_no_batch" saves only
    # matmuls without batch dims (weight-stationary contractions).
    remat_policy: str | None = None
    # Vocab-head cross-entropy chunking (training/eval loss only).
    # With ce_chunks > 1 the loss computes the [tokens, vocab] logits in
    # ce_chunks sequential slices, each rematerialized in the backward,
    # so the full [B, S, V] f32 logits never materialize in HBM — at
    # vocab 32k, seq 1k, batch 8 that is ~1 GB of f32 written + re-read
    # several times per step on the unchunked path.  Pure optimization:
    # loss and gradients are exact (per-slice logsumexp), sampling and
    # predict paths are untouched (they need one position's logits
    # only).  0/1 = off.
    ce_chunks: int = 0
    # Sliding-window (local) attention: each position attends its last
    # `attention_window` positions (self included) instead of the full
    # causal past — compute per token drops from O(L) to O(window) in
    # the flash kernels (dead blocks skipped), the standard local-
    # attention long-context trade.  None = full causal attention.
    # Composes with rope/GQA/remat/ce_chunks, the KV-cached decode, and
    # ring attention (global-position masking per hop).
    attention_window: int | None = None
    # z-loss (ST-MoE eq. 6): z_loss_coef * mean(logsumexp(logits)^2)
    # added to the TRAINING loss only.  Keeps the softmax normalizer
    # near 0 so bf16 logits stay in range over long runs — the standard
    # stability regularizer for large-vocab LMs.  Excluded from lm_nll
    # (eval perplexity stays a pure model-quality number).  Typical:
    # 1e-4.  Works on every head path, including chunked CE.
    z_loss_coef: float = 0.0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads if self.n_kv_heads is not None else self.n_heads
        if not 1 <= kv <= self.n_heads or self.n_heads % kv:
            raise ValueError(
                f"n_kv_heads={kv} must divide n_heads={self.n_heads}")
        return kv


_REMAT_POLICIES = {
    None: None,
    "dots": "checkpoint_dots",
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
}


def _validate_remat_policy(cfg: "TransformerConfig",
                           require_remat: bool = True) -> None:
    """Single enforcement point for the remat knobs.

    ``require_remat=True`` (init_params) also rejects a policy with
    remat=False — a config *built* that way is a mistake.  Wrap time
    passes False: ``dataclasses.replace(cfg, remat=False)`` on a
    training config is the natural way to run eval/inference, and the
    leftover policy is simply inert there.
    """
    if cfg.remat_policy is None:
        return
    if cfg.remat_policy not in _REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r}; "
            f"known: {sorted(k for k in _REMAT_POLICIES if k)} or None")
    if require_remat and not cfg.remat:
        raise ValueError(
            "remat_policy is set but remat=False — the policy only "
            "selects what a rematerialized backward may save; enable "
            "remat=True (or drop the policy)")


def _remat_block(cfg: "TransformerConfig", moe_dense_routing: bool = False):
    """``block_apply`` wrapped per cfg.remat / cfg.remat_policy.

    ``moe_dense_routing`` is bound OUTSIDE the checkpoint wrapper (a
    plain-Python partial, not a traced argument): a bool passed through
    ``jax.checkpoint`` would become a tracer and break the block's
    Python-level routing branch.
    """
    # Unknown names are rejected even with remat=False (typos must not
    # pass silently); only the remat-required pairing check is relaxed
    # (an inert leftover policy is fine at eval time).
    _validate_remat_policy(cfg, require_remat=False)
    fn = (functools.partial(block_apply, moe_dense_routing=True)
          if moe_dense_routing else block_apply)
    if not cfg.remat:
        return fn
    name = _REMAT_POLICIES[cfg.remat_policy]
    policy = getattr(jax.checkpoint_policies, name) if name else None
    return jax.checkpoint(fn, static_argnums=(2, 3), policy=policy)


def _dense_init(rng, shape, fan_in):
    return jax.random.normal(rng, shape, jnp.float32) / math.sqrt(fan_in)


def init_params(rng, cfg: TransformerConfig):
    """Build the parameter pytree.  Per-layer params are stacked on a
    leading [n_layers] axis (scan/pipeline-friendly: one tree, L-major).
    """
    if not 0.0 <= cfg.dropout < 1.0:
        raise ValueError(f"dropout must be in [0, 1), got {cfg.dropout}")
    if cfg.ce_chunks < 0:
        raise ValueError(f"ce_chunks must be >= 0, got {cfg.ce_chunks}")
    if cfg.z_loss_coef < 0:
        raise ValueError(
            f"z_loss_coef must be >= 0, got {cfg.z_loss_coef} (a negative "
            "coefficient would silently disable the regularizer)")
    if cfg.attention_window is not None and cfg.attention_window < 1:
        raise ValueError(
            f"attention_window must be >= 1, got {cfg.attention_window}")
    if cfg.num_experts and not 1 <= cfg.moe_top_k <= cfg.num_experts:
        raise ValueError(
            f"moe_top_k={cfg.moe_top_k} must be in [1, num_experts="
            f"{cfg.num_experts}]")
    _validate_remat_policy(cfg)
    keys = jax.random.split(rng, 12)
    d, f, h, hd = cfg.d_model, cfg.d_ff, cfg.n_heads, cfg.head_dim
    kv = cfg.kv_heads
    L = cfg.n_layers

    def stack(key, shape, fan_in):
        return _dense_init(key, (L, *shape), fan_in)

    layers = {
        "ln1_scale": jnp.ones((L, d)),
        "ln2_scale": jnp.ones((L, d)),
        "attn": {
            "wq": stack(keys[0], (d, h, hd), d),
            "wk": stack(keys[1], (d, kv, hd), d),
            "wv": stack(keys[2], (d, kv, hd), d),
            "wo": stack(keys[3], (h, hd, d), d),
        },
    }
    if cfg.num_experts:
        layers["moe"] = {
            "wg": stack(keys[4], (d, cfg.num_experts), d),
            "w1": stack(keys[5], (cfg.num_experts, d, f), d),
            "w2": stack(keys[6], (cfg.num_experts, f, d), f),
        }
    else:
        layers["ffn"] = {
            "w1": stack(keys[7], (d, f), d),
            "w2": stack(keys[8], (f, d), f),
        }
    params = {
        # Tied embedding/unembedding: std 1/sqrt(d) keeps initial logits
        # O(1) so the initial LM loss sits at ~ln(vocab).
        "tok_emb": _dense_init(keys[9], (cfg.vocab_size, d), d),
        "ln_f_scale": jnp.ones((d,)),
        "layers": layers,
    }
    if cfg.rope:
        if hd % 2:
            raise ValueError(
                f"rope needs an even head_dim, got {hd} "
                f"(d_model={d}, n_heads={h})")
    else:
        params["pos_emb"] = _dense_init(keys[10], (cfg.max_len, d), 1.0) * 0.02
    return params


def tp_rules():
    """Megatron-layout PartitionSpecs over the ``model`` axis.

    Keyed on tree_shardings key-paths (leading [L] stack axis first for
    per-layer params).  Column-parallel in, row-parallel out: the only
    collective per block is one psum pair, inserted by XLA.
    """
    return [
        (r"attn/w[qkv]$", P(None, None, "model", None)),
        (r"attn/wo$", P(None, "model", None, None)),
        (r"ffn/w1$", P(None, None, "model")),
        (r"ffn/w2$", P(None, "model", None)),
        # MoE: experts over 'expert', their matmuls over 'model'.
        (r"moe/wg$", P()),
        (r"moe/w1$", P(None, "expert", None, "model")),
        (r"moe/w2$", P(None, "expert", "model", None)),
        (r"tok_emb$", P(None, "model")),
        (r"pos_emb$", P(None, "model")),
    ]


def _resolve_attention_fn(cfg: "TransformerConfig", attention_fn,
                          segment_ids=None):
    """ONE guard for the window/attention_fn pairing (apply_hidden and
    apply_pipelined share it).

    No fn: build the default windowed flash lambda (closing over
    ``segment_ids`` for packed sequences).  Custom fn: its
    ``handles_window`` attribute (set by make_ring_attention; set it
    yourself on hand-rolled fns) must equal ``cfg.attention_window`` in
    BOTH directions — a band applied on one side only would silently
    diverge training from the KV-cached decode, which follows cfg.
    """
    if attention_fn is None:
        return lambda q, k, v: flash_attention(
            q, k, v, True, window=cfg.attention_window,
            segment_ids=segment_ids)
    if segment_ids is not None:
        if getattr(attention_fn, "handles_segments", False):
            # make_ring_attention sets the attribute: the fn takes the
            # per-call segments itself (rotating the KV-side shard).
            base_fn = attention_fn
            attention_fn = lambda q, k, v: base_fn(
                q, k, v, segment_ids=segment_ids)
            attention_fn.handles_window = getattr(base_fn,
                                                  "handles_window", None)
        else:
            raise ValueError(
                "segment_ids with this custom attention_fn is not "
                "supported: the packed-document mask must be applied "
                "inside the attention implementation (set "
                "fn.handles_segments = True and accept a segment_ids "
                "kwarg, as make_ring_attention does) — or drop the "
                "custom fn / unpack the batch")
    fn_window = getattr(attention_fn, "handles_window", None)
    if fn_window != cfg.attention_window:
        raise ValueError(
            f"attention window mismatch: cfg.attention_window="
            f"{cfg.attention_window} but the supplied attention_fn "
            f"implements window={fn_window} (fn.handles_window). Build "
            "the fn with the same window (make_ring_attention(..., "
            "window=...) sets the attribute; set it yourself on custom "
            "fns) or align the config — a one-sided band silently "
            "diverges training from the KV-cached decode")
    return attention_fn


def _check_len(s: int, cfg: TransformerConfig) -> None:
    # RoPE has no trained position table: any training length is valid
    # (max_len only sizes the decode KV cache, models/generate.py).
    if not cfg.rope and s > cfg.max_len:
        raise ValueError(
            f"sequence length {s} exceeds max_len={cfg.max_len} (note "
            "lm_loss feeds tokens[:, :-1], so token arrays may carry "
            "max_len + 1 positions)")


def _dropout(x, rate: float, key):
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


def _rms_norm(x, scale, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def rope_angles(positions, head_dim: int, theta: float):
    """Rotation angles ``[..., head_dim/2]`` for integer positions."""
    half = head_dim // 2
    inv = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    return positions.astype(jnp.float32)[..., None] * inv


def rope_rotate(x, ang):
    """Half-split rotary rotation of the last dim of ``x`` by ``ang``
    (broadcastable to ``x[..., :half]``); f32 math, input dtype out."""
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    c, s = jnp.cos(ang), jnp.sin(ang)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c],
                           axis=-1).astype(x.dtype)


def _attention_block(lp, x, attention_fn, rope_ang=None, kv_groups=1,
                     return_kv=False):
    q = jnp.einsum("bsd,dhk->bshk", x, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", x, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", x, lp["wv"])
    if rope_ang is not None:
        q, k = rope_rotate(q, rope_ang), rope_rotate(k, rope_ang)
    kv = (k, v)  # post-rope, pre-GQA-expansion: the decode cache layout
    if kv_groups > 1:  # GQA: expand shared K/V heads for the kernel
        k = jnp.repeat(k, kv_groups, axis=2)
        v = jnp.repeat(v, kv_groups, axis=2)
    out = attention_fn(q, k, v)
    out = jnp.einsum("bshk,hkd->bsd", out, lp["wo"])
    return (out, kv) if return_kv else out


def _moe_gates(probs, cfg: TransformerConfig):
    """Top-k expert choice shared by every routing path.

    Returns ``(gates [..., k], expert [..., k])``: k=1 keeps the raw
    top-1 probability as the combine weight (Switch semantics — the
    router gradient flows through the gate magnitude); k>1 renormalizes
    the top-k probabilities over the selected experts (GShard/Mixtral).
    ONE definition so capacity, dense, and decode routing cannot drift.
    """
    gates, expert = jax.lax.top_k(probs, cfg.moe_top_k)
    if cfg.moe_top_k > 1:
        gates = gates / gates.sum(axis=-1, keepdims=True)
    return gates, expert


def _moe_block(lp, x, cfg: TransformerConfig):
    """Top-k MoE with capacity dropping (Switch at k=1, GShard at k=2).

    Tokens flatten to [N, D]; the dispatch/combine einsums carry the
    expert axis, which the EP sharding rules place on the mesh
    ``expert`` axis — XLA emits the all-to-alls.  Dropped assignments
    contribute 0 (the residual connection keeps the token's stream;
    with k>1 a token's other choice may still land).  First choices
    take capacity priority over second choices (choice-major cumsum) —
    GShard's sequential assignment.  Returns (out, aux_loss).
    """
    b, s, d = x.shape
    n = b * s
    e = cfg.num_experts
    k_sel = cfg.moe_top_k
    # Capacity per expert scales with k so capacity_factor keeps
    # meaning "slack per assignment" (t5x convention).
    cap = max(1, int(cfg.capacity_factor * k_sel * n / e))
    flat = x.reshape(n, d)

    router = jnp.einsum("nd,de->ne", flat.astype(jnp.float32), lp["wg"])
    probs = jax.nn.softmax(router, axis=-1)
    gates, expert = _moe_gates(probs, cfg)          # [N, k] each
    one_hot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [N, k, E]

    # Load-balancing aux loss (Switch Transformer eq. 4) on FIRST
    # choices — reduces exactly to Switch at k=1, and first-choice
    # density is the balance that matters at any k.
    density = one_hot[:, 0].mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = jnp.sum(density * density_proxy) * e * cfg.aux_loss_coef

    # Choice-major flattening: all first choices claim slots before any
    # second choice competes.
    oh_cm = one_hot.transpose(1, 0, 2).reshape(k_sel * n, e)
    pos = jnp.cumsum(oh_cm, axis=0) * oh_cm  # 1-based slot, [kN, E]
    keep = (pos <= cap).astype(jnp.float32) * oh_cm
    slot_oh = jax.nn.one_hot((pos - 1.0).astype(jnp.int32), cap,
                             dtype=jnp.float32) * keep[..., None]  # [kN,E,C]
    slot_k = slot_oh.reshape(k_sel, n, e, cap)

    # Dispatch sums over choices: a token picked by both its choices
    # (different experts — top_k indices are distinct) lands in both.
    xe = jnp.einsum("knec,nd->ecd", slot_k, flat.astype(jnp.float32))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe, lp["w1"]))
    ye = jnp.einsum("ecf,efd->ecd", h, lp["w2"])
    # Combine weights ride the slot one-hots: choice k of token n
    # contributes gates[n, k] iff its assignment survived capacity.
    comb = slot_k * gates.T.reshape(k_sel, n)[:, :, None, None]
    out = jnp.einsum("ecd,knec->nd", ye, comb)
    return out.astype(x.dtype).reshape(b, s, d), aux


def _moe_dense_block(lp, x, cfg: TransformerConfig):
    """Capacity-FREE top-k MoE over [B, S, D] — the batched twin of
    _decode_step's per-token branch (models/generate.py): every expert
    runs on every token (E x compute) and the router's picks are
    gathered.  Used by generate.prefill so prefilled and sequential
    prompt processing match exactly; training keeps :func:`_moe_block`
    (capacity dispatch).  Unselected experts are zero-masked BEFORE the
    combine so a non-finite value in an unpicked expert cannot poison
    the token (0 * inf is NaN; where() is not).
    """
    dtype = x.dtype
    router = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), lp["wg"])
    probs = jax.nn.softmax(router, axis=-1)
    gates, expert = _moe_gates(probs, cfg)               # [B, S, k]
    # Per-expert combined weight: top_k indices are distinct, so this
    # sums each selected expert's gate into its slot.
    sel = jnp.einsum("bske,bsk->bse",
                     jax.nn.one_hot(expert, cfg.num_experts,
                                    dtype=jnp.float32), gates)
    h1 = jax.nn.gelu(jnp.einsum("bsd,edf->bsef", x,
                                lp["w1"].astype(dtype)))
    y_all = jnp.einsum("bsef,efd->bsed", h1, lp["w2"].astype(dtype))
    y_all = jnp.where(sel[..., None] > 0, y_all, 0.0)
    return jnp.einsum("bsed,bse->bsd", y_all, sel.astype(y_all.dtype)
                      ).astype(dtype)


def block_apply(layer_params, x, cfg: TransformerConfig,
                attention_fn: Callable, rope_ang=None, drop_key=None,
                return_kv=False, moe_dense_routing=False):
    """One transformer block (pre-norm).  Returns (x, aux_loss), or
    (x, aux_loss, (k, v)) with ``return_kv`` (post-rope, kv-heads-only —
    the decode-cache layout; generate.prefill consumes it so there is
    exactly ONE definition of the block body to keep in sync).
    ``moe_dense_routing`` swaps the MoE FFN for the capacity-free
    decode-parity :func:`_moe_dense_block` (prefill's inference
    semantics); aux comes back 0 on that path.

    ``rope_ang`` and ``drop_key`` are *traced array* arguments (not
    closures) so the remat wrapper's static_argnums stay (2, 3) — a
    callable closing over traced values would leak tracers through
    jax.checkpoint.  ``drop_key`` non-None enables residual dropout.
    """
    h = _rms_norm(x, layer_params["ln1_scale"])
    a = _attention_block(layer_params["attn"], h, attention_fn, rope_ang,
                         kv_groups=cfg.n_heads // cfg.kv_heads,
                         return_kv=return_kv)
    kv = None
    if return_kv:
        a, kv = a
    if drop_key is not None:
        a = _dropout(a, cfg.dropout, jax.random.fold_in(drop_key, 0))
    x = x + a
    h = _rms_norm(x, layer_params["ln2_scale"])
    if cfg.num_experts and moe_dense_routing:
        y = _moe_dense_block(layer_params["moe"], h, cfg)
        aux = jnp.zeros((), jnp.float32)
    elif cfg.num_experts:
        y, aux = _moe_block(layer_params["moe"], h, cfg)
    else:
        y = jnp.einsum(
            "bsf,fd->bsd",
            jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, layer_params["ffn"]["w1"])),
            layer_params["ffn"]["w2"])
        aux = jnp.zeros((), jnp.float32)
    if drop_key is not None:
        y = _dropout(y, cfg.dropout, jax.random.fold_in(drop_key, 1))
    out = x + y
    return (out, aux, kv) if return_kv else (out, aux)


def apply_hidden(params, tokens, cfg: TransformerConfig,
                 attention_fn: Callable | None = None, dropout_rng=None,
                 moe_dense_routing: bool = False, segment_ids=None):
    """Trunk forward: tokens [B, S] int32 -> final-norm hidden [B, S, D].

    Everything in :func:`apply` except the unembedding matmul; the
    chunked cross-entropy path consumes the hidden states directly so
    the full-vocab logits never materialize.  Returns (hidden, aux).

    ``moe_dense_routing=True`` scores MoE configs with the capacity-FREE
    dense routing that :func:`~distkeras_tpu.models.generate.generate`
    and ``prefill`` use — the *inference semantics* (aux comes back 0).
    Evaluating a trained MoE this way agrees exactly with the KV-cached
    decode at ANY capacity factor; the default (training capacity
    dispatch) diverges for every token the router would capacity-drop.
    No-op for dense configs.

    ``segment_ids [B, S]`` int32 (packed sequences, data/packing.py):
    attention is masked to within-segment pairs; 0 marks padding.
    With ``rope=True`` the packed forward is EXACT vs running each
    document alone — rotary scores depend only on within-document
    relative distance, which a uniform position shift preserves.  With
    a learned position table, packed documents see shifted rows
    (standard packing behavior; prefer rope for packed training).
    """
    attention_fn = _resolve_attention_fn(cfg, attention_fn, segment_ids)
    dtype = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    _check_len(s, cfg)
    x = params["tok_emb"][tokens].astype(dtype)
    rope_ang = None
    if cfg.rope:
        rope_ang = rope_angles(jnp.arange(s), cfg.head_dim,
                               cfg.rope_theta)[None, :, None, :]
    else:
        x = x + params["pos_emb"][:s][None].astype(dtype)
    dropping = cfg.dropout > 0 and dropout_rng is not None
    if dropping:
        # fold_in index n_layers: disjoint from the per-layer keys 0..L-1.
        x = _dropout(x, cfg.dropout,
                     jax.random.fold_in(dropout_rng, cfg.n_layers))

    aux_total = jnp.zeros((), jnp.float32)

    block = _remat_block(cfg, moe_dense_routing=moe_dense_routing)

    # Python loop (not scan): attention_fn may close over shard_map /
    # pallas calls whose tracing under scan complicates sharding; layer
    # counts at this framework's scale compile fine unrolled.
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        drop_key = (jax.random.fold_in(dropout_rng, i) if dropping
                    else None)
        x, aux = block(lp, x, cfg, attention_fn, rope_ang, drop_key)
        aux_total = aux_total + aux

    return _rms_norm(x, params["ln_f_scale"]), aux_total


def _unembed(hidden, params, cfg: TransformerConfig):
    """Tied unembedding head: hidden [B, S, D] -> f32 logits [B, S, V].

    The single definition of the head — apply, apply_pipelined and the
    materialized loss branch all call it, so the 'chunked CE matches
    materialized logits' invariant has one site to stay in sync with.
    """
    dtype = jnp.dtype(cfg.dtype)
    logits = jnp.einsum("bsd,vd->bsv", hidden,
                        params["tok_emb"].astype(dtype))
    return logits.astype(jnp.float32)


def apply(params, tokens, cfg: TransformerConfig,
          attention_fn: Callable | None = None, dropout_rng=None,
          moe_dense_routing: bool = False, segment_ids=None):
    """Forward pass: tokens [B, S] int32 -> logits [B, S, V].

    ``attention_fn(q, k, v) -> out`` defaults to causal flash attention
    (Pallas on TPU); pass a ``make_ring_attention(...)`` wrapper for
    sequence parallelism.  ``dropout_rng`` non-None (with cfg.dropout
    > 0) enables training dropout; omit it for deterministic
    inference/eval.  ``moe_dense_routing=True`` selects the decode-
    parity capacity-free MoE routing; ``segment_ids`` masks packed
    sequences (see :func:`apply_hidden`).
    Returns (logits, aux_loss).
    """
    x, aux_total = apply_hidden(params, tokens, cfg, attention_fn,
                                dropout_rng, moe_dense_routing,
                                segment_ids)
    return _unembed(x, params, cfg), aux_total


def chunked_softmax_xent(hidden, emb, targets, n_chunks: int):
    """Mean softmax cross-entropy without materializing full logits.
    Returns ``(mean_nll, mean_lse_sq)`` — the second term is the z-loss
    statistic ``mean(logsumexp^2)`` (free here: the per-row logsumexp
    is already computed), consumed by ``lm_loss`` when
    ``cfg.z_loss_coef`` is set.

    ``hidden`` [B, S, D] (compute dtype), ``emb`` [V, D], ``targets``
    [B, S] int — target -1 marks an EXCLUDED position (loss masking:
    packed-sequence boundaries/padding, plus the internal chunk-pad
    rows) and the mean divides by the VALID count only.  A ``lax.scan``
    over the chunks computes each [N/n_chunks, V] logits
    slice, reduces it to its per-row ``logsumexp - target_logit``, and
    discards it.  ``jax.checkpoint`` on the body re-derives the slice in
    the backward, so peak HBM for the head is one slice fwd + bwd
    instead of the full [N, V] f32 logits (plus XLA's saved
    intermediates).  Exact — not an approximation: same per-row math as
    ``log_softmax`` + gather, chunking only reorders the reduction.
    """
    n_tok = targets.size
    d = hidden.shape[-1]
    h = hidden.reshape(n_tok, d)
    t = targets.reshape(n_tok).astype(jnp.int32)
    pad = (-n_tok) % n_chunks
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t = jnp.concatenate([t, jnp.full((pad,), -1, jnp.int32)])
    h = h.reshape(n_chunks, -1, d)
    t = t.reshape(n_chunks, -1)
    emb_c = emb.astype(hidden.dtype)

    def body(carry, sl):
        nll_total, z_total, n_valid = carry
        hc, tc = sl
        logits = jnp.einsum("cd,vd->cv", hc, emb_c).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, jnp.maximum(tc, 0)[:, None], axis=-1)[:, 0]
        valid = tc >= 0
        nll = jnp.where(valid, lse - tgt, 0.0)
        z = jnp.where(valid, jnp.square(lse), 0.0)
        return (nll_total + nll.sum(), z_total + z.sum(),
                n_valid + valid.sum()), None

    (total, z_total, n_valid), _ = jax.lax.scan(
        jax.checkpoint(body),
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
         jnp.zeros((), jnp.int32)), (h, t))
    denom = jnp.maximum(n_valid, 1).astype(jnp.float32)
    return total / denom, z_total / denom


def apply_pipelined(params, tokens, cfg: TransformerConfig, mesh,
                    microbatches: int, attention_fn: Callable | None = None,
                    axis_name: str = "pipeline", seq_axis: str | None = None,
                    return_hidden: bool = False, segment_ids=None):
    """Forward pass with the layer trunk pipelined over ``axis_name``.

    Embedding and the head run outside the pipeline (they change shape);
    the residual trunk — whose stacked [L, ...] params slice naturally
    into ``n_stages`` contiguous stages — runs under
    parallel.pipeline.make_pipeline.  MoE aux loss flows through the
    pipeline (stage outputs carry ``(activation, aux)``), averaged over
    microbatches so it sits on the same scale as :func:`apply` — note
    expert capacity applies per *microbatch* under PP, so routing can
    drop slightly differently than the un-pipelined forward.

    For PP x SP, pass ``seq_axis="seq"``: the pipeline's shard_map goes
    manual over {pipeline, seq} and each stage runs the raw
    :func:`~distkeras_tpu.parallel.ring.ring_attention` body on its
    sequence shard — one composed shard_map, which (unlike a nested
    shard_map) transposes cleanly under AD.  MoE routing/capacity then
    applies per sequence shard.

    ``segment_ids [B, S]`` (packed sequences): every stage masks
    attention to within-document pairs — the per-microbatch segment
    slice rides the pipeline as make_pipeline ``extras`` (each stage
    indexes the microbatch it is processing), sharded over ``seq_axis``
    under PP x SP so the ring body receives its local shard.  Only the
    default-flash and seq_axis attention paths carry segments (a custom
    attention_fn raises, as in :func:`apply_hidden`).

    Returns (logits, aux).
    """
    import functools

    from distkeras_tpu.parallel.pipeline import make_pipeline

    segmented = segment_ids is not None
    if segmented and attention_fn is not None:
        raise ValueError(
            "segment_ids with a custom attention_fn is not supported "
            "under the pipeline — use the default flash path or "
            "seq_axis (see apply_hidden's guard)")
    x_spec = P()
    extras_spec = P() if segmented else None
    ring_seq = seq_axis is not None and int(mesh.shape[seq_axis]) > 1
    if ring_seq:
        if attention_fn is not None:
            raise ValueError(
                "pass either attention_fn or seq_axis, not both: under "
                "seq_axis the pipeline installs the ring attention body "
                "itself")
        from distkeras_tpu.parallel.ring import ring_attention

        attention_fn = functools.partial(ring_attention, axis_name=seq_axis,
                                         causal=True,
                                         window=cfg.attention_window)
        x_spec = P(None, seq_axis)
        if segmented:
            extras_spec = P(None, None, seq_axis)
    elif not segmented:
        attention_fn = _resolve_attention_fn(cfg, attention_fn)
    n_stages = int(mesh.shape[axis_name])
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible into {n_stages} stages")
    per_stage = cfg.n_layers // n_stages

    dtype = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    _check_len(s, cfg)
    x = params["tok_emb"][tokens].astype(dtype)
    if not cfg.rope:
        x = x + params["pos_emb"][:s][None].astype(dtype)

    stage_params = jax.tree.map(
        lambda a: a.reshape(n_stages, per_stage, *a.shape[1:]),
        params["layers"])

    block = _remat_block(cfg)

    seq_sharded = x_spec != P()

    def stage_fn(lp, u, seg=None):
        rope_ang = None
        if cfg.rope:
            # Positions must be *global*: under PP x SP this body runs
            # on a sequence shard, so offset by the shard's ring index.
            l_loc = u.shape[1]
            start = (jax.lax.axis_index(seq_axis) * l_loc
                     if seq_sharded else 0)
            rope_ang = rope_angles(start + jnp.arange(l_loc), cfg.head_dim,
                                   cfg.rope_theta)[None, :, None, :]
        if seg is None:
            att = attention_fn
        elif ring_seq:
            # The ring body with this microbatch's LOCAL segment shard.
            att = functools.partial(attention_fn, segment_ids=seg)
        else:
            # ONE definition of the default segmented flash path —
            # shared with apply_hidden via the resolver.
            att = _resolve_attention_fn(cfg, None, seg)
        aux_stage = jnp.zeros((), jnp.float32)
        for i in range(per_stage):
            li = jax.tree.map(lambda a: a[i], lp)
            u, aux = block(li, u, cfg, att, rope_ang)
            aux_stage = aux_stage + aux
        return u, aux_stage

    pipe = make_pipeline(stage_fn, mesh, microbatches, axis_name,
                         x_spec=x_spec, extras_spec=extras_spec)
    if segmented:
        if segment_ids.shape != tokens.shape:
            raise ValueError(
                f"segment_ids must align with tokens {tokens.shape}, "
                f"got {segment_ids.shape}")
        seg_mb = jnp.asarray(segment_ids, jnp.int32).reshape(
            microbatches, b // microbatches, s)
        x, aux_total = pipe(stage_params, x, seg_mb)
    else:
        x, aux_total = pipe(stage_params, x)
    x = _rms_norm(x, params["ln_f_scale"])
    if return_hidden:
        # The head runs outside the pipeline, so the chunked-CE loss can
        # consume the hidden states directly (lm_loss hidden_fn).
        return x, aux_total
    return _unembed(x, params, cfg), aux_total


def _forward_nll(params, tokens, cfg: TransformerConfig,
                 attention_fn: Callable | None,
                 apply_fn: Callable | None, dropout_rng=None,
                 hidden_fn: Callable | None = None,
                 moe_dense_routing: bool = False,
                 segment_ids=None):
    """(mean next-token NLL, aux) — shared by train loss and eval.

    Three forward routes:

    - ``apply_fn(params, inputs) -> (logits, aux)``: caller-materialized
      logits (legacy custom-forward hook); full log_softmax head.
    - ``hidden_fn(params, inputs) -> (hidden, aux)``: caller supplies
      final-norm hidden states (e.g. ``apply_pipelined`` with
      ``return_hidden=True``); the head honors ``cfg.ce_chunks``.
    - neither: the default :func:`apply_hidden` trunk; the head honors
      ``cfg.ce_chunks``.

    ``segment_ids [B, S+1]`` (aligned with ``tokens``, packed
    sequences): attention is segment-masked on the default trunk, and
    the loss EXCLUDES targets that cross a document boundary or sit in
    padding (segment 0) — the mean divides by the valid count.  A
    custom apply_fn/hidden_fn with ``handles_segments = True`` is
    called as ``fn(params, inputs, seg)`` so its forward can mask
    attention too (LMTrainer's pipelined fwd does); without the
    attribute it gets only the loss masking.
    """
    if apply_fn is not None and hidden_fn is not None:
        raise ValueError("pass apply_fn or hidden_fn, not both")
    targets = tokens[:, 1:]
    valid = None
    seg_in = None
    if segment_ids is not None:
        if segment_ids.shape != tokens.shape:
            raise ValueError(
                f"segment_ids must align with tokens {tokens.shape}, "
                f"got {segment_ids.shape}")
        seg_in = segment_ids[:, :-1]
        # A target is trainable iff it continues its input's document
        # (same nonzero segment) — boundary and pad targets are dead.
        valid = ((segment_ids[:, 1:] == seg_in) & (seg_in != 0))
        targets = jnp.where(valid, targets, -1)
    zc = cfg.z_loss_coef

    def full_head(logits, aux):
        # z-loss rides in aux (training-only, like the MoE penalty —
        # lm_nll drops aux, so eval perplexity stays pure).
        logp = jax.nn.log_softmax(logits, axis=-1)
        per_tok = -jnp.take_along_axis(
            logp, jnp.maximum(targets, 0)[..., None], axis=-1)[..., 0]
        if valid is None:
            nll = per_tok.mean()
        else:
            denom = jnp.maximum(valid.sum(), 1)
            nll = jnp.where(valid, per_tok, 0.0).sum() / denom
        if zc > 0:
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            if valid is None:
                aux = aux + zc * jnp.square(lse).mean()
            else:
                denom = jnp.maximum(valid.sum(), 1)
                aux = aux + zc * (jnp.where(valid, jnp.square(lse), 0.0)
                                  .sum() / denom)
        return nll, aux

    def call_custom(fn, *args):
        if seg_in is not None and getattr(fn, "handles_segments", False):
            return fn(*args, seg_in)
        return fn(*args)

    if apply_fn is not None:
        logits, aux = call_custom(apply_fn, params, tokens[:, :-1])
        return full_head(logits, aux)
    if hidden_fn is None:
        hidden_fn = lambda p, t: apply_hidden(p, t, cfg, attention_fn,
                                              dropout_rng,
                                              moe_dense_routing,
                                              seg_in)
    hidden, aux = call_custom(hidden_fn, params, tokens[:, :-1])
    if cfg.ce_chunks > 1:
        nll, z_mean = chunked_softmax_xent(hidden, params["tok_emb"],
                                           targets, cfg.ce_chunks)
        if zc > 0:
            aux = aux + zc * z_mean
        return nll, aux
    return full_head(_unembed(hidden, params, cfg), aux)


def lm_loss(params, tokens, cfg: TransformerConfig,
            attention_fn: Callable | None = None,
            apply_fn: Callable | None = None, dropout_rng=None,
            hidden_fn: Callable | None = None, segment_ids=None):
    """Next-token cross-entropy (+ MoE aux), mean over the trainable
    targets (all B*(S-1) positions, or the within-document subset when
    ``segment_ids`` marks packed sequences — see :func:`_forward_nll`).

    ``apply_fn(params, inputs) -> (logits, aux)`` defaults to
    :func:`apply`; pass ``hidden_fn`` (e.g. a closure over
    :func:`apply_pipelined` with ``return_hidden=True``) to train a
    custom trunk under the ``cfg.ce_chunks`` head.
    """
    if dropout_rng is not None and (apply_fn is not None
                                    or hidden_fn is not None):
        raise ValueError(
            "dropout_rng only threads through the default apply(); "
            "a custom apply_fn/hidden_fn (e.g. the pipelined trunk) "
            "must take its own rng — pipeline parallelism does not "
            "support dropout (see TransformerConfig.dropout)")
    nll, aux = _forward_nll(params, tokens, cfg, attention_fn, apply_fn,
                            dropout_rng, hidden_fn,
                            segment_ids=segment_ids)
    return nll + aux


def lm_nll(params, tokens, cfg: TransformerConfig,
           attention_fn: Callable | None = None,
           apply_fn: Callable | None = None,
           hidden_fn: Callable | None = None,
           moe_dense_routing: bool = False, segment_ids=None):
    """Mean next-token NLL *without* the MoE aux regularizer — the
    evaluation quantity (``exp`` of it is perplexity; the router load
    penalty is a training device, not model quality).

    ``moe_dense_routing=True`` evaluates MoE configs with the decode-
    parity capacity-free routing (see :func:`apply_hidden`) — the right
    lens for "what perplexity will the served model show": identical to
    the KV-cached decode at any capacity factor.  Only affects the
    default trunk (a custom apply_fn/hidden_fn controls its own
    routing)."""
    return _forward_nll(params, tokens, cfg, attention_fn, apply_fn,
                        hidden_fn=hidden_fn,
                        moe_dense_routing=moe_dense_routing,
                        segment_ids=segment_ids)[0]


def make_train_step(cfg: TransformerConfig, optimizer,
                    attention_fn: Callable | None = None,
                    apply_fn: Callable | None = None,
                    grad_accum: int = 1,
                    hidden_fn: Callable | None = None,
                    loss_fn: Callable | None = None,
                    value_and_grad: Callable | None = None,
                    probe: bool = False):
    """``step((params, opt_state), tokens) -> ((params', opt_state'), loss)``.

    Pure; callers jit it with NamedShardings (see __graft_entry__ and
    the trainers).  With ``grad_accum > 1``, ``tokens`` is
    ``[grad_accum, B, S+1]``: gradients accumulate over the microbatches
    and one optimizer update applies their mean — the memory lever for
    batch sizes whose activations do not fit HBM (the LM analogue of
    the Keras family's ``communication_window``, SURVEY.md §7.4).  The
    microbatch loop is unrolled, not scanned: attention_fn may close
    over shard_map/pallas calls whose tracing under scan complicates
    sharding (same reason apply() unrolls its layer loop).

    ``loss_fn`` (default :func:`lm_loss`) must share lm_loss's
    signature; a custom hook reinterprets the differentiated "params"
    tree (e.g. models/lora's (adapters, base) packing, which merges
    before calling lm_loss).

    ``value_and_grad`` (default ``jax.value_and_grad``) is the
    gradient-construction hook: it receives the loss fn and must
    return a callable with ``jax.value_and_grad``'s calling
    convention.  LMTrainer's replicated-DP configuration passes a
    shard_map-local construction here that sums the tied embedding's
    two gradient contributions *before* the cross-replica exchange
    (trainers/lm.py ``_dp_local_value_and_grad``) — XLA's CPU
    partitioner otherwise all-reduces them separately.

    ``probe=True``: the step returns ``(carry, (loss, aux))`` with
    ``aux = {"grad_norm": ...}`` computed in-graph — LMTrainer's
    opt-in diagnostics probe (same program count either way; under the
    stacked-local-gradient exchange the norm is over the stacked
    per-replica tree).
    """
    dropping = cfg.dropout > 0
    if value_and_grad is None:
        value_and_grad = jax.value_and_grad

    def step(carry, tokens, dropout_rng=None, segment_ids=None):
        params, opt_state = carry
        grad_fn = value_and_grad(loss_fn if loss_fn is not None
                                 else lm_loss)
        if dropping and dropout_rng is None:
            raise ValueError(
                f"cfg.dropout={cfg.dropout} but the train step got no "
                "dropout_rng: pass step(carry, tokens, rng) or training "
                "silently runs unregularized (LMTrainer threads the rng "
                "automatically)")
        rng = dropout_rng if dropping else None
        if grad_accum == 1:
            loss, grads = grad_fn(params, tokens, cfg, attention_fn,
                                  apply_fn, rng, hidden_fn, segment_ids)
        else:
            # NOTE: a stacked-local value_and_grad returns [n, *leaf]
            # gradients; the zeros_like(params) accumulator broadcasts
            # against them on the first add, so accumulation works for
            # both layouts.
            grads = jax.tree.map(jnp.zeros_like, params)
            loss = jnp.zeros((), jnp.float32)
            for i in range(grad_accum):
                ri = jax.random.fold_in(rng, i) if rng is not None else None
                li, gi = grad_fn(params, tokens[i], cfg, attention_fn,
                                 apply_fn, ri, hidden_fn,
                                 None if segment_ids is None
                                 else segment_ids[i])
                grads = jax.tree.map(jnp.add, grads, gi)
                loss = loss + li
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
            loss = loss / grad_accum
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        if probe:
            import optax

            return (params, opt_state), (
                loss, {"grad_norm": optax.global_norm(grads)})
        return (params, opt_state), loss

    return step
