"""Checkpoint / resume (orbax-backed) — a capability the reference lacks.

SURVEY.md §5: the reference has **no** mid-training checkpointing; a
model survives only by being serialized back to the Spark driver after
training completes, and the parameter server is a single point of
failure.  The TPU rebuild's failure story is checkpoint/restart: the
whole training state (parameters, optimizer state, step counter — any
pytree) is written asynchronously by orbax while the next step runs,
and restored sharding-aware onto the mesh.

Kept deliberately kwargs-first (no config system — SURVEY.md §5):
trainers grow ``checkpoint_dir`` / ``checkpoint_every`` / ``resume``
constructor knobs and everything else is defaulted.
"""

from __future__ import annotations

import os
from typing import Any

import jax


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Saves arbitrary pytrees (TrainState, stacked replica states, ...)
    under integer step numbers.  Restores take a *template* pytree —
    the live, correctly-sharded state — so restored arrays land with
    the template's shardings (device-resident, mesh-aware).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ),
        )

    # ------------------------------------------------------------------ ops

    def save(self, state: Any, step: int, force: bool = False) -> bool:
        """Persist ``state`` under ``step``.  Async: returns immediately.

        Respects ``save_interval_steps`` unless ``force``.  Returns
        whether a save was actually started.
        """
        return self._mngr.save(
            step, args=self._ocp.args.StandardSave(state), force=force)

    def restore(self, template: Any, step: int | None = None) -> Any:
        """Restore the checkpoint at ``step`` (default: latest).

        ``template`` supplies structure, dtypes and shardings; restored
        arrays are placed accordingly (sharded loads go straight to the
        right devices — no host-side full-model materialization).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        abstract = jax.tree.map(_abstractify, template)
        return self._mngr.restore(
            step, args=self._ocp.args.StandardRestore(abstract))

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def wait_until_finished(self) -> None:
        """Block until outstanding async saves hit disk."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _abstractify(x):
    """Template leaf -> ShapeDtypeStruct carrying the leaf's sharding."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return x
