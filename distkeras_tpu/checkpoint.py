"""Checkpoint / resume — a capability the reference lacks.

SURVEY.md §5: the reference has **no** mid-training checkpointing; a
model survives only by being serialized back to the Spark driver after
training completes, and the parameter server is a single point of
failure.  The TPU rebuild's failure story is checkpoint/restart: the
whole training state (parameters, optimizer state, step counter — any
pytree) is written by a pluggable backend and restored sharding-aware
onto the mesh.

Two backends behind one :class:`CheckpointManager` surface:

- ``"orbax"`` — the production path: async, multi-host, sharded saves
  via ``orbax.checkpoint`` (a ZeRO-1/FSDP-scattered optimizer state is
  written shard-native — no host-side reassembly — and restored
  straight onto the template's devices).
- ``"pickle"`` — a pure-stdlib single-host fallback: synchronous
  atomic writes (tmp dir + ``os.replace``), the same integer-step
  directory layout and refuse-to-overwrite semantics.  Scattered
  leaves *gather on save* into one host array and re-scatter on
  restore via the template's shardings.  Exists so the resilience
  machinery (and its tests) runs on any box, orbax installed or not.

``backend="auto"`` (the default) picks orbax when importable and falls
back to pickle otherwise; asking for ``"orbax"`` explicitly without the
package raises a clear ImportError instead of the bare lazy-import
traceback.

Kept deliberately kwargs-first (no config system — SURVEY.md §5):
trainers grow ``checkpoint_dir`` / ``checkpoint_every`` / ``resume``
constructor knobs and everything else is defaulted.

Every save passes through the ``"checkpoint.save"`` chaos probe
(resilience/chaos.py), so fault-injection plans can make persistence
fail exactly like a flaky filesystem would.
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any

import jax

from distkeras_tpu.resilience import chaos

BACKENDS = ("auto", "orbax", "pickle")


class CheckpointManager:
    """Save/restore arbitrary pytrees (TrainState, stacked replica
    states, ...) under integer step numbers.

    Restores take a *template* pytree — the live, correctly-sharded
    state — so restored arrays land with the template's shardings
    (device-resident, mesh-aware).

    ``backend``: ``"auto"`` / ``"orbax"`` / ``"pickle"`` (see module
    docstring); the resolved choice is readable as ``self.backend``.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True,
                 backend: str = "auto"):
        if backend not in BACKENDS:
            raise ValueError(
                f"unknown checkpoint backend {backend!r}; known: {BACKENDS}")
        self.directory = os.path.abspath(directory)
        ocp = None
        if backend in ("auto", "orbax"):
            try:
                import orbax.checkpoint as ocp
            except ImportError as e:
                if backend == "orbax":
                    raise ImportError(
                        "checkpoint backend 'orbax' needs the "
                        "orbax-checkpoint package (pip install "
                        "orbax-checkpoint); for single-host runs "
                        "without it, pass backend='pickle' (or leave "
                        "backend='auto' to fall back automatically)"
                    ) from e
        if ocp is not None:
            self._impl = _OrbaxBackend(
                ocp, self.directory, max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                async_save=async_save)
            self.backend = "orbax"
        else:
            self._impl = _PickleBackend(
                self.directory, max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps)
            self.backend = "pickle"

    # ------------------------------------------------------------------ ops

    def save(self, state: Any, step: int, force: bool = False) -> bool:
        """Persist ``state`` under ``step``.  Async (orbax): returns
        immediately.  Respects ``save_interval_steps`` unless ``force``.
        Returns whether a save was actually started.
        """
        chaos.probe("checkpoint.save", step=step)
        return self._impl.save(state, step, force)

    def restore(self, template: Any, step: int | None = None) -> Any:
        """Restore the checkpoint at ``step`` (default: latest).

        ``template`` supplies structure, dtypes and shardings; restored
        arrays are placed accordingly (sharded loads go straight to the
        right devices — no host-side full-model materialization on the
        orbax path).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        return self._impl.restore(template, step)

    def latest_step(self) -> int | None:
        return self._impl.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._impl.all_steps())

    def valid_steps(self) -> list[int]:
        """Committed steps that pass the cheap integrity check
        (:func:`distkeras_tpu.resilience.cluster.step_is_valid`): a
        host that died mid-save on a filesystem without atomic rename
        can leave a torn step directory that lists as committed but
        cannot be restored.  The cluster-consistent resume rule and the
        trainers' restore validation both select from THIS set, not
        ``all_steps``.  Delegates to the cluster-resilience scan so
        per-host resume and cluster-consistent selection share ONE
        validity rule."""
        from distkeras_tpu.resilience.cluster import valid_steps

        return valid_steps(self.directory)

    def latest_valid_step(self) -> int | None:
        """Newest valid step — scans newest-first and stops at the
        first step that passes, so the common case (intact latest)
        validates one payload instead of the whole history."""
        from distkeras_tpu.resilience.cluster import latest_valid_step

        return latest_valid_step(self.directory)

    def wait_until_finished(self) -> None:
        """Block until outstanding async saves hit disk."""
        self._impl.wait_until_finished()

    def close(self) -> None:
        self._impl.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _OrbaxBackend:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, ocp, directory, *, max_to_keep, save_interval_steps,
                 async_save):
        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, state, step, force):
        return self._mngr.save(
            step, args=self._ocp.args.StandardSave(state), force=force)

    def restore(self, template, step):
        abstract = jax.tree.map(_abstractify, template)
        return self._mngr.restore(
            step, args=self._ocp.args.StandardRestore(abstract))

    def latest_step(self):
        return self._mngr.latest_step()

    def all_steps(self):
        return self._mngr.all_steps()

    def wait_until_finished(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()


class _PickleBackend:
    """Pure-stdlib single-host checkpointing.

    Same on-disk contract as orbax where the rest of the stack can see
    it: integer-named step directories committed atomically (write to a
    hidden tmp dir, then ``os.replace`` — a crash mid-write leaves no
    integer-named dir, so a partial save is never restored), saves
    refuse to overwrite an existing step, and ``max_to_keep`` garbage-
    collects the oldest steps.  Saves are synchronous:
    ``wait_until_finished`` is a no-op because ``save`` only returns
    once the rename committed.

    Single-host only: leaves are materialized via ``np.asarray``, which
    would gather a multi-host sharded array; the manager's restore
    re-places each leaf with the template leaf's sharding.
    """

    def __init__(self, directory, *, max_to_keep, save_interval_steps):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps
        if jax.process_count() > 1:
            raise ValueError(
                "the pickle checkpoint backend is single-host only "
                "(leaves are materialized on this host); multi-host "
                "runs need backend='orbax'")
        os.makedirs(directory, exist_ok=True)

    def save(self, state, step, force):
        import numpy as np

        if not force and self.save_interval_steps > 1 \
                and step % self.save_interval_steps:
            return False
        final = os.path.join(self.directory, str(step))
        if os.path.isdir(final):
            raise ValueError(
                f"checkpoint step {step} already exists under "
                f"{self.directory} (steps are immutable once committed)")

        def to_host(x):
            # Gather-on-save: a ZeRO-1/FSDP-scattered leaf reassembles
            # into one host array (every shard is addressable on this
            # single host — the multi-process guard in __init__ holds);
            # restore re-scatters it via the template leaf's sharding.
            if isinstance(x, jax.Array) and len(x.sharding.device_set) > 1:
                if not x.is_fully_addressable:  # pragma: no cover
                    raise ValueError(
                        "pickle checkpoint backend cannot gather a leaf "
                        "spanning non-addressable devices; use "
                        "backend='orbax' for multi-host sharded state")
                return np.asarray(jax.device_get(x))
            return np.asarray(x) if hasattr(x, "shape") else x

        host = jax.tree.map(to_host, state)
        tmp = os.path.join(self.directory, f".tmp.{step}.{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        try:
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)  # the commit point
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        self._gc()
        return True

    def _gc(self):
        steps = sorted(self.all_steps())
        for s in steps[:max(0, len(steps) - self.max_to_keep)]:
            shutil.rmtree(os.path.join(self.directory, str(s)),
                          ignore_errors=True)

    def restore(self, template, step):
        path = os.path.join(self.directory, str(step), "state.pkl")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {self.directory}")
        with open(path, "rb") as f:
            loaded = pickle.load(f)

        def place(t, v):
            if hasattr(v, "shape") and hasattr(t, "shape"):
                return jax.device_put(v, getattr(t, "sharding", None))
            return v

        return jax.tree.map(place, template, loaded)

    def latest_step(self):
        steps = self.all_steps()
        return max(steps) if steps else None

    def all_steps(self):
        if not os.path.isdir(self.directory):
            return []
        return [int(e) for e in os.listdir(self.directory) if e.isdigit()]

    def wait_until_finished(self):
        pass  # saves are synchronous

    def close(self):
        pass


def _abstractify(x):
    """Template leaf -> ShapeDtypeStruct carrying the leaf's sharding."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return x
