"""Resilience subsystem: fault injection, supervised training, cluster
coordination, and serving admission control.

Five layers (docs/resilience.md has the failure model):

- :mod:`~distkeras_tpu.resilience.chaos` — deterministic, seedable
  fault injection over named probe sites in the production code paths
  (checkpoint saves, training rounds, serving steps, the speculative
  draft, cluster heartbeats).
- :mod:`~distkeras_tpu.resilience.supervisor` — retry + backoff +
  verified auto-resume around any trainer's ``train``, with a SIGTERM
  preemption handler that forces a final synchronous checkpoint.
- :mod:`~distkeras_tpu.resilience.health` — per-host heartbeats over a
  shared directory plus the read-side staleness monitor.
- :mod:`~distkeras_tpu.resilience.cluster` — cluster epochs, the
  collective watchdog, per-host restart drivers, and cluster-consistent
  checkpoint selection (coordinated multi-host restart).
- :mod:`~distkeras_tpu.resilience.admission` — request deadlines,
  bounded-queue backpressure, and structured results for the serving
  engines (wired into :mod:`distkeras_tpu.serving`).
"""

from distkeras_tpu.resilience import chaos, cluster, health
from distkeras_tpu.resilience.admission import (EngineClosed, QueueFull,
                                                 RequestResult)
from distkeras_tpu.resilience.chaos import (FaultInjected, FaultPlan,
                                             Preempted)
from distkeras_tpu.resilience.cluster import (ClusterMember,
                                               ClusterSupervisor,
                                               cluster_consistent_step)
from distkeras_tpu.resilience.health import HealthMonitor, HeartbeatWriter
from distkeras_tpu.resilience.supervisor import Attempt, Supervisor

__all__ = [
    "chaos",
    "cluster",
    "health",
    "FaultPlan",
    "FaultInjected",
    "Preempted",
    "Supervisor",
    "Attempt",
    "RequestResult",
    "QueueFull",
    "EngineClosed",
    "ClusterMember",
    "ClusterSupervisor",
    "cluster_consistent_step",
    "HealthMonitor",
    "HeartbeatWriter",
]
