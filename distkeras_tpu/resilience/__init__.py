"""Resilience subsystem: fault injection, supervised training, and
serving admission control.

Three layers (docs/resilience.md has the failure model):

- :mod:`~distkeras_tpu.resilience.chaos` — deterministic, seedable
  fault injection over named probe sites in the production code paths
  (checkpoint saves, training rounds, serving steps, the speculative
  draft).
- :mod:`~distkeras_tpu.resilience.supervisor` — retry + backoff +
  verified auto-resume around any trainer's ``train``, with a SIGTERM
  preemption handler that forces a final synchronous checkpoint.
- :mod:`~distkeras_tpu.resilience.admission` — request deadlines,
  bounded-queue backpressure, and structured results for the serving
  engines (wired into :mod:`distkeras_tpu.serving`).
"""

from distkeras_tpu.resilience import chaos
from distkeras_tpu.resilience.admission import (EngineClosed, QueueFull,
                                                 RequestResult)
from distkeras_tpu.resilience.chaos import (FaultInjected, FaultPlan,
                                             Preempted)
from distkeras_tpu.resilience.supervisor import Attempt, Supervisor

__all__ = [
    "chaos",
    "FaultPlan",
    "FaultInjected",
    "Preempted",
    "Supervisor",
    "Attempt",
    "RequestResult",
    "QueueFull",
    "EngineClosed",
]
