"""Admission-control types for the serving engines.

The serving engines (:mod:`distkeras_tpu.serving`) gain three
production behaviors from this layer — all host-side bookkeeping, so
the compiled decode programs (and the exact-parity contract they are
pinned to) are untouched:

- **deadlines**: every request may carry a TTL; an expired request is
  evicted from its lane (or dropped from the queue before it ever
  occupies one) and reported as a structured ``timeout`` result.
- **bounded admission queue**: ``enqueue`` buffers requests when all
  lanes are busy, up to ``max_queue``; past that it raises
  :class:`QueueFull` — backpressure the caller can act on (shed load,
  retry elsewhere) instead of an unbounded hidden buffer.
- **drain-then-shutdown**: ``begin_shutdown`` stops admission,
  ``shutdown`` runs the decode loop until every in-flight request
  finishes (or times out) and returns the collected results.
"""

from __future__ import annotations

import dataclasses

import numpy as np


class QueueFull(RuntimeError):
    """Admission rejected: every lane is busy and the bounded queue is
    at capacity.  The backpressure signal — callers shed or retry."""


class EngineClosed(RuntimeError):
    """Admission rejected: the engine is shutting down (drain phase)."""


@dataclasses.dataclass
class RequestResult:
    """Terminal record for one request.

    ``status``: ``"ok"`` (finished by eos/budget), ``"timeout"``
    (deadline expired — ``tokens`` holds the prompt plus whatever was
    generated before eviction; a request that expired before ever
    occupying a lane holds just the prompt), ``"cancelled"`` (dropped
    by shutdown before completing), or ``"error"`` (a queued request
    failed engine-specific admission validation when its lane freed;
    ``error`` carries the message).

    The live-transcript snapshot (``partial()``, the round-17
    streaming read) reuses this record with two NON-terminal
    statuses: ``"queued"`` (``tokens`` is just the prompt) and
    ``"decoding"`` (``tokens`` is the prompt plus everything emitted
    so far) — same prompt-inclusive transcript shape, so cursor
    arithmetic never branches on terminality.
    """

    request_id: int
    tokens: np.ndarray
    status: str
    prompt_len: int
    error: str | None = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    @property
    def timed_out(self) -> bool:
        return self.status == "timeout"

    @property
    def generated(self) -> np.ndarray:
        """The emitted tokens (prompt stripped)."""
        return self.tokens[self.prompt_len:]


@dataclasses.dataclass
class _Pending:
    """A queued request waiting for a free lane."""

    request_id: int
    prompt: np.ndarray
    max_new: int
    deadline: float | None
    submit_kw: dict
    born: float | None = None  # engine clock() at enqueue (obs latency)
