"""Cluster health: per-host heartbeats over a shared directory.

The multi-host failure mode PR 1 could not cover: synchronous
data-parallel training blocks in a collective every step, so when one
host of the job dies the survivors do not crash — they HANG, forever,
inside the next all-reduce (the classic sync-SGD stall: one dead
participant freezes the whole step DAG, arXiv:1805.03812).  Nothing
host-local can notice that, because the hung host is perfectly healthy;
what died is a *peer*.  This module is the peer-visibility layer the
cluster supervisor (:mod:`distkeras_tpu.resilience.cluster`) builds its
bounded-window detection on:

- :class:`HeartbeatWriter` — a daemon thread on every host that
  appends a fresh beat (atomic file replace) every ``interval``
  seconds.  The write goes through the ``cluster.heartbeat`` chaos
  probe, so fault plans can stall it (partition: the host is alive but
  its beats stop arriving) or kill the host outright.
- :class:`HealthMonitor` — reads every host's beat file and reports
  which peers are **stale** (no beat within ``window`` seconds).  Pure
  read-side; safe to poll from a watchdog thread while the main thread
  is wedged in a collective.

Deliberately file-based (any shared filesystem — NFS, GCS-fuse, or a
plain tmpdir in the multiprocess tests) and stdlib-only: the driver
process that supervises restarts must be able to import this without
initializing jax.  Clocks: beats carry ``time.time()`` wall time; on a
single machine (the test harness) that is one clock, and on a real
cluster NTP skew just widens the effective window — choose ``window``
>> ``interval`` + worst-case skew.
"""

from __future__ import annotations

import json
import os
import threading
import time


def _beat_path(directory: str, host: int) -> str:
    return os.path.join(directory, f"host{host}.hb")


def write_beat(directory: str, host: int, epoch: int, n: int,
               clock=time.time, done: bool = False) -> None:
    """Atomically publish one beat (tmp + ``os.replace`` — a reader
    never sees a torn beat).  ``done=True`` is the terminal beat: this
    host finished its work cleanly and will stop beating; monitors
    must not read the ensuing silence as a death."""
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".hb.{host}.{os.getpid()}.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"host": host, "epoch": epoch, "n": n,
                   "t": clock(), "pid": os.getpid(), "done": done}, f)
    os.replace(tmp, _beat_path(directory, host))


def read_beat(directory: str, host: int) -> dict | None:
    """The host's latest beat, or None if it has never beaten (or the
    file is unreadable mid-replace on a non-atomic filesystem)."""
    try:
        with open(_beat_path(directory, host), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def beat_age(directory: str, host: int,
             clock=time.time) -> tuple[float, bool] | None:
    """``(age_seconds, done)`` of the host's latest beat, or None when
    it never beat.  The freshness primitive the live telemetry plane's
    ``/healthz`` endpoint answers from (obs/live.py): fresh within the
    window -> 200, stale -> 503, ``done`` -> clean completion, always
    healthy."""
    beat = read_beat(directory, host)
    if beat is None:
        return None
    return clock() - beat.get("t", 0.0), bool(beat.get("done"))


class HeartbeatWriter:
    """Daemon thread: publish a beat every ``interval`` seconds.

    Each beat passes the ``cluster.heartbeat`` chaos probe first, so a
    :class:`~distkeras_tpu.resilience.chaos.FaultPlan` can ``delay``
    (stalled host), ``drop`` (partition: alive but invisible), or
    ``kill`` (hard host loss) the heartbeat stream deterministically.
    """

    def __init__(self, directory: str, host: int, epoch: int = 0,
                 interval: float = 0.5, clock=time.time):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.directory = directory
        self.host = host
        self.epoch = epoch
        self.interval = interval
        self._clock = clock
        self._stop = threading.Event()
        self._thread = None
        self.beats = 0

    def beat_once(self) -> None:
        """One beat, chaos-probed (the writer thread's body; also
        callable directly from a round loop for progress-coupled
        beats)."""
        from distkeras_tpu.resilience import chaos

        try:
            chaos.probe("cluster.heartbeat", step=self.beats + 1)
        except chaos.BeatDropped:
            return  # partition: stay alive, publish nothing
        self.beats += 1
        write_beat(self.directory, self.host, self.epoch, self.beats,
                   clock=self._clock)

    def start(self) -> "HeartbeatWriter":
        if self._thread is not None:
            raise RuntimeError("heartbeat writer already started")
        self.beat_once()  # first beat lands before start() returns

        def run():
            while not self._stop.wait(self.interval):
                self.beat_once()

        self._thread = threading.Thread(
            target=run, name=f"dkt-heartbeat-host{self.host}", daemon=True)
        self._thread.start()
        return self

    def mark_done(self) -> None:
        """Publish the terminal beat (``done=True``) and stop the
        thread: clean completion, not death.  The done beat passes the
        ``cluster.heartbeat`` chaos probe like every other beat — a
        partition that swallows a host's heartbeats must swallow its
        completion announcement too, or a partitioned host could fake
        clean completion to its peers."""
        from distkeras_tpu.resilience import chaos

        self.stop()
        try:
            chaos.probe("cluster.heartbeat", step=self.beats + 1)
        except chaos.BeatDropped:
            return  # partitioned: the done beat never arrives either
        self.beats += 1
        write_beat(self.directory, self.host, self.epoch, self.beats,
                   clock=self._clock, done=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class HealthMonitor:
    """Read-side peer health over the shared beat directory.

    A peer is **stale** when its last beat is older than ``window``
    seconds (or it never beat at all once ``grace`` has elapsed since
    the monitor started — covers a host that died before its first
    beat).  ``stale_peers`` is what the collective watchdog polls; it
    never blocks and never touches jax.
    """

    def __init__(self, directory: str, host: int, num_hosts: int,
                 window: float = 3.0, grace: float | None = None,
                 clock=time.time):
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        if window <= 0:
            raise ValueError(f"window must be > 0, got {window}")
        self.directory = directory
        self.host = host
        self.num_hosts = num_hosts
        self.window = window
        self.grace = window if grace is None else grace
        self._clock = clock
        self._born = clock()

    def peer_ids(self) -> list[int]:
        return [h for h in range(self.num_hosts) if h != self.host]

    def stale_peers(self, epoch: int | None = None) -> list[int]:
        """Hosts whose beats are missing or stale.  ``epoch``: ignore
        beats from older epochs (a relaunched host's stale pre-restart
        file must not count as liveness in the new generation)."""
        now = self._clock()
        stale = []
        for h in self.peer_ids():
            beat = read_beat(self.directory, h)
            if beat is not None and epoch is not None \
                    and beat.get("epoch", 0) < epoch:
                beat = None
            if beat is None:
                if now - self._born >= self.grace:
                    stale.append(h)
                continue
            if beat.get("done"):
                continue  # clean completion: silence is not death
            if now - beat.get("t", 0.0) > self.window:
                stale.append(h)
        return stale

    def alive(self, epoch: int | None = None) -> bool:
        return not self.stale_peers(epoch=epoch)


__all__ = ["HeartbeatWriter", "HealthMonitor", "write_beat",
           "read_beat", "beat_age"]
