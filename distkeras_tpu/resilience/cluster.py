"""Coordinated multi-host restart: cluster epochs, watchdogs, drivers.

PR 1's :class:`~distkeras_tpu.resilience.supervisor.Supervisor` made
each *process* durable; this module makes the *job* durable.  The gap
it closes: synchronous data-parallel training steps through
collectives, so when one host dies the survivors neither crash nor
retry — they block forever inside the next all-reduce (SURVEY.md §5's
"the job dies", upgraded to "the job hangs").  A hung host cannot save
itself from the inside: the main thread is wedged in XLA.  Recovery
therefore has three cooperating layers, all coordinated through one
shared **cluster directory** (any shared filesystem; stdlib-only so
the driver never imports jax):

- **Epoch store** (:class:`EpochStore`) — a monotone generation
  counter published as marker files.  Every jax.distributed runtime
  the job ever forms is stamped with the epoch it belongs to; a
  restart is "everyone moves to epoch N+1", and the per-epoch
  coordinator port (``base_port + epoch``) means a stale epoch's
  half-dead runtime can never be rejoined by accident.
- **Member** (:class:`ClusterMember`) — runs *inside* each training
  process: a heartbeat writer (health.py) plus the **collective
  watchdog** thread.  The watchdog polls peer heartbeats; when a peer
  goes stale (died, stalled, partitioned) or the epoch moves on, it
  requests the next epoch and aborts THIS process (``os._exit`` with
  :data:`EXIT_RESTART`) — the only reliable way out of a blocked
  collective, and exactly what a preemption looks like to the rest of
  the stack, so the per-host Supervisor/checkpoint machinery needs no
  new cases.
- **Driver** (:class:`ClusterSupervisor`) — runs *outside* (one per
  host, no jax): launches the training process for the current epoch,
  watches peers and the epoch store itself (covering the case where
  the training process died before its watchdog could act), kills and
  relaunches under the next epoch, and — on restart — trims every
  host's checkpoint store to the latest **cluster-consistent** step
  (:func:`cluster_consistent_step`: the highest step committed AND
  valid on every host) so all hosts resume from the same state and the
  resumed run replays the uninterrupted trajectory bit-for-bit.

The same fault matrix that PR 1 injects per-process drives this layer
end to end: ``FaultPlan.kill`` (host-kill), ``delay`` on
``cluster.heartbeat`` (stall) and ``drop`` (partition) — see
``scripts/chaos_suite.py --cluster`` and tests/test_cluster.py.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from distkeras_tpu.resilience.health import HealthMonitor, HeartbeatWriter

# The exit code a member uses to say "I aborted for a cluster restart,
# relaunch me under the next epoch" (EX_TEMPFAIL).  Any OTHER nonzero
# exit also triggers a restart — this one just names the reason.
EXIT_RESTART = 75

# orbax's atomic-rename tmp suffix: a step directory carrying it (or
# containing entries that do) was never committed.
_ORBAX_TMP = ".orbax-checkpoint-tmp"


class ClusterGivenUp(RuntimeError):
    """The driver exhausted ``max_restarts`` coordinated restarts."""


# --------------------------------------------------------------- epochs


class EpochStore:
    """Monotone cluster generation counter over marker files.

    ``request(e)`` creates ``<dir>/epochs/<e>`` (atomic, idempotent —
    any number of hosts may request the same epoch concurrently);
    ``current()`` is the highest requested epoch, 0 before any
    request.  Epochs only ever move forward: there is no delete."""

    def __init__(self, directory: str):
        self.directory = os.path.join(directory, "epochs")

    def request(self, epoch: int) -> None:
        if epoch < 0:
            raise ValueError(f"epoch must be >= 0, got {epoch}")
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, str(int(epoch)))
        with open(path, "a", encoding="utf-8"):
            pass

    def current(self) -> int:
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return 0
        steps = [int(e) for e in entries if e.isdigit()]
        return max(steps, default=0)


# ----------------------------------- cluster-consistent checkpoint state


def step_is_valid(step_dir: str) -> bool:
    """Cheap integrity check for one committed checkpoint step.

    Pickle layout (``state.pkl``): the opcode stream must parse
    through to ``STOP`` — a host that died mid-
    ``CheckpointManager.save`` on a filesystem without atomic rename
    leaves a torn file that truncates mid-stream.  The scan
    (``pickletools.genops``) reads the file but never materializes the
    payload, so validating a multi-GB training state costs I/O, not
    allocation.  Orbax layout: the directory must be committed by name
    (no orbax tmp suffix), non-empty, and free of uncommitted tmp
    entries inside.  Anything else non-empty is trusted (unknown
    backends fail at restore time, loudly)."""
    if not os.path.isdir(step_dir):
        return False
    if _ORBAX_TMP in os.path.basename(step_dir):
        return False
    pkl = os.path.join(step_dir, "state.pkl")
    if os.path.exists(pkl):
        import pickletools

        try:
            with open(pkl, "rb") as f:
                last = None
                for op, _arg, _pos in pickletools.genops(f):
                    last = op.name
            return last == "STOP"
        except Exception:  # noqa: BLE001 — torn/corrupt == invalid
            return False
    entries = os.listdir(step_dir)
    if not entries:
        return False
    return not any(_ORBAX_TMP in e for e in entries)


def valid_steps(checkpoint_dir: str) -> list[int]:
    """The committed AND valid integer steps under one host's
    checkpoint directory (sorted)."""
    if not os.path.isdir(checkpoint_dir):
        return []
    return sorted(
        int(e) for e in os.listdir(checkpoint_dir)
        if e.isdigit() and step_is_valid(os.path.join(checkpoint_dir, e)))


def latest_valid_step(checkpoint_dir: str) -> int | None:
    """Newest committed step that passes :func:`step_is_valid`.

    Scans newest-first and stops at the first valid step, so the
    common case (intact latest) validates exactly one payload —
    :func:`valid_steps` would unpickle every retained checkpoint,
    which at multi-GB training state is real I/O.  Use this for
    resume-point selection; ``valid_steps`` only where the full set is
    needed (cluster consistency)."""
    if not os.path.isdir(checkpoint_dir):
        return None
    for s in sorted((int(e) for e in os.listdir(checkpoint_dir)
                     if e.isdigit()), reverse=True):
        if step_is_valid(os.path.join(checkpoint_dir, str(s))):
            return s
    return None


def cluster_consistent_step(checkpoint_dirs) -> int | None:
    """The highest checkpoint step present and valid on EVERY host.

    This is the cluster resume rule: a step that only some hosts
    committed (the fault landed mid-cadence), or that any host holds
    torn (died mid-save), must not be resumed from — the survivors
    would restore state the dead host never reached and the replicas
    would diverge on round one.  Duplicate paths (hosts sharing one
    store, e.g. multi-host orbax) collapse to one."""
    dirs = {os.path.realpath(d) for d in checkpoint_dirs}
    if not dirs:
        return None
    common = None
    for d in dirs:
        steps = set(valid_steps(d))
        common = steps if common is None else common & steps
    return max(common) if common else None


def trim_to_consistent(checkpoint_dirs) -> int | None:
    """Delete every step beyond (or torn at) the cluster-consistent
    step, on every host, so each host's own ``latest_step()``-driven
    auto-resume lands on the SAME state.  Returns the consistent step
    (None = nothing usable anywhere: resume from scratch)."""
    import shutil

    keep = cluster_consistent_step(checkpoint_dirs)
    for d in {os.path.realpath(p) for p in checkpoint_dirs}:
        if not os.path.isdir(d):
            continue
        for e in os.listdir(d):
            if not e.isdigit():
                continue
            step = int(e)
            path = os.path.join(d, e)
            if keep is None or step > keep or not step_is_valid(path):
                shutil.rmtree(path, ignore_errors=True)
    return keep


# --------------------------------------------------------------- member


class ClusterMember:
    """The in-process half: heartbeats out, collective watchdog in.

    Start this FIRST in a cluster job script — before
    ``initialize_jax`` — so peers see liveness while the distributed
    runtime forms, and the watchdog can already abort a join that will
    never complete because a peer is gone:

    .. code-block:: python

        member = cluster.member_from_env()
        member.start()
        member.initialize_jax()          # epoch-stamped coordinator
        try:
            Supervisor(trainer).run(ds)  # per-host retry still applies
            member.complete()
        finally:
            member.stop()

    The watchdog polls every ``poll`` seconds; a peer with no beat for
    ``window`` seconds (or a cluster epoch newer than ours) trips it:
    it requests the next epoch, emits a ``cluster.fault`` obs event,
    and calls ``abort`` — by default ``os._exit(EXIT_RESTART)``,
    because a survivor blocked inside a dead collective cannot be
    unwound politely (``abort=`` is injectable for tests).  Detection
    latency is bounded by ``window + poll``.
    """

    def __init__(self, coord_dir: str, host: int, num_hosts: int,
                 epoch: int = 0, *, base_port: int = 8476,
                 heartbeat_interval: float = 0.5, window: float = 3.0,
                 poll: float = 0.25, grace: float = 30.0,
                 abort=None, clock=time.time):
        self.coord_dir = coord_dir
        self.host = host
        self.num_hosts = num_hosts
        self.epoch = epoch
        self.base_port = base_port
        self.epochs = EpochStore(coord_dir)
        self.writer = HeartbeatWriter(
            os.path.join(coord_dir, "hb"), host, epoch=epoch,
            interval=heartbeat_interval, clock=clock)
        self.monitor = HealthMonitor(
            os.path.join(coord_dir, "hb"), host, num_hosts,
            window=window, grace=grace, clock=clock)
        self.poll = poll
        self._abort = abort if abort is not None else self._exit_abort
        self._stop = threading.Event()
        self._thread = None
        self.fault_reason: str | None = None

    @property
    def coordinator_address(self) -> str:
        """Epoch-stamped coordinator: a new generation forms on a new
        port, so survivors of epoch N can never half-join N+1."""
        return f"localhost:{self.base_port + self.epoch}"

    def initialize_jax(self) -> None:
        """Join the epoch's jax.distributed runtime (no-op when
        single-host).  NOTE: jax requires this before the FIRST
        computation — and importing the framework (keras backend init)
        already computes — so cluster job scripts usually inline this
        call on a bare ``import jax`` before importing distkeras_tpu
        (see the child template in scripts/chaos_suite.py); until the
        member starts beating, liveness during the join is covered by
        the drivers' launch grace."""
        if self.num_hosts <= 1:
            return
        import jax

        from distkeras_tpu.parallel.mesh import enable_cpu_collectives

        enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_hosts, process_id=self.host)

    # ---------------------------------------------------------- threads

    def start(self) -> "ClusterMember":
        if self._thread is not None:
            raise RuntimeError("cluster member already started")
        self.writer.start()
        self._thread = threading.Thread(
            target=self._watch, name=f"dkt-watchdog-host{self.host}",
            daemon=True)
        self._thread.start()
        return self

    def _watch(self) -> None:
        while not self._stop.wait(self.poll):
            stale = self.monitor.stale_peers(epoch=self.epoch)
            if stale:
                self.trip(f"peer heartbeat(s) stale: hosts {stale}")
                return
            current = self.epochs.current()
            if current > self.epoch:
                self.trip(f"cluster moved to epoch {current} "
                          f"(we are epoch {self.epoch})")
                return

    def trip(self, reason: str) -> None:
        """The watchdog fired: request the next epoch, record the
        fault, abort this process (see class docstring)."""
        from distkeras_tpu import obs

        self.fault_reason = reason
        self.epochs.request(self.epoch + 1)
        obs.event("cluster.fault", host=self.host, epoch=self.epoch,
                  reason=reason)
        obs.count("cluster.faults")
        self._abort(reason)

    def _exit_abort(self, reason: str) -> None:
        from distkeras_tpu import obs

        try:
            # Best-effort flush: close the obs session so the trace
            # gets its final metrics record before the hard exit.
            obs.disable()
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        print(f"[dkt-cluster host {self.host}] watchdog abort: {reason}",
              file=sys.stderr, flush=True)
        os._exit(EXIT_RESTART)

    def complete(self) -> None:
        """Training finished on this host: publish the terminal
        ``done`` beat (so stragglers never read our exit as a death)
        and stop the watchdog."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.writer.mark_done()

    def stop(self) -> None:
        self._stop.set()
        self.writer.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def member_from_env() -> ClusterMember:
    """Build the :class:`ClusterMember` a :class:`ClusterSupervisor`
    driver described through the ``DKT_CLUSTER_*`` env vars."""
    env = os.environ
    return ClusterMember(
        coord_dir=env["DKT_CLUSTER_DIR"],
        host=int(env["DKT_CLUSTER_HOST"]),
        num_hosts=int(env["DKT_CLUSTER_NHOSTS"]),
        epoch=int(env.get("DKT_CLUSTER_EPOCH", "0")),
        base_port=int(env.get("DKT_CLUSTER_BASE_PORT", "8476")),
        heartbeat_interval=float(env.get("DKT_CLUSTER_INTERVAL", "0.5")),
        window=float(env.get("DKT_CLUSTER_WINDOW", "3.0")),
        grace=float(env.get("DKT_CLUSTER_GRACE", "30.0")),
    )


# --------------------------------------------------------------- driver


class ClusterSupervisor:
    """Per-host relauncher: the process-level half of coordinated
    restart.  Wraps the training process (which runs the per-host
    :class:`~distkeras_tpu.resilience.supervisor.Supervisor` inside)
    the way that Supervisor wraps ``trainer.train``:

    - launch ``command`` for the current epoch with the
      ``DKT_CLUSTER_*`` env contract (:func:`member_from_env` reads
      it);
    - while it runs, watch peer heartbeats and the epoch store from
      the OUTSIDE — if a peer goes stale or the epoch advances, kill
      the child (this host may be wedged in a collective with a dead
      peer; its own watchdog usually fires first, this is the
      belt-and-braces layer) and move on;
    - on any child death, request the next epoch, wait at the epoch
      **barrier** (every host's driver must acknowledge the new epoch
      before anyone launches, so the new coordinator and its clients
      form one runtime), trim checkpoints to the cluster-consistent
      step (host 0 only, before releasing its barrier marker), and
      relaunch;
    - give up after ``max_restarts`` coordinated restarts
      (:class:`ClusterGivenUp`).

    **Flap dampening** (``healthy_uptime``): a link that partitions
    every few minutes would burn the whole restart budget in a day
    even though each epoch between flaps made real progress.  When an
    attempt runs at least ``healthy_uptime`` seconds before dying, the
    restart budget is REFUNDED (the counter resets to zero before the
    failure is charged): ``max_restarts`` then bounds *consecutive
    rapid* failures — the crash-loop it exists to stop — instead of
    lifetime flap count.  Attempts killed for exceeding
    ``attempt_timeout`` never refund (a hung child always outlives any
    uptime bar).  ``None`` disables the refund (the pre-PR-6
    behavior).

    Stdlib-only on purpose: drivers survive anything the training
    stack does, including jax refusing to import.
    """

    def __init__(self, coord_dir: str, host: int, num_hosts: int,
                 command, *, env: dict | None = None,
                 base_port: int = 8476, window: float = 3.0,
                 poll: float = 0.25, grace: float = 30.0,
                 heartbeat_interval: float = 0.5,
                 checkpoint_dirs=None, max_restarts: int = 4,
                 barrier_timeout: float = 120.0,
                 attempt_timeout: float | None = None,
                 healthy_uptime: float | None = None):
        self.coord_dir = coord_dir
        self.host = host
        self.num_hosts = num_hosts
        self.command = list(command)
        self.env = dict(env or {})
        self.base_port = base_port
        self.window = window
        self.poll = poll
        self.grace = grace
        self.heartbeat_interval = heartbeat_interval
        self.checkpoint_dirs = list(checkpoint_dirs or [])
        self.max_restarts = max_restarts
        self.barrier_timeout = barrier_timeout
        self.attempt_timeout = attempt_timeout
        if healthy_uptime is not None and healthy_uptime <= 0:
            raise ValueError(
                f"healthy_uptime must be positive (seconds) or None, "
                f"got {healthy_uptime}")
        self.healthy_uptime = healthy_uptime
        self.epochs = EpochStore(coord_dir)
        self.history: list[dict] = []   # one record per attempt

    # ------------------------------------------------------------ barrier

    def _barrier_dir(self, epoch: int) -> str:
        return os.path.join(self.coord_dir, "ready", str(epoch))

    def _enter_barrier(self, epoch: int) -> None:
        """Host 0 trims checkpoints BEFORE publishing its marker, so
        every other host's launch happens-after the trim."""
        if self.host == 0 and epoch > 0 and self.checkpoint_dirs:
            kept = trim_to_consistent(self.checkpoint_dirs)
            self.history.append({"epoch": epoch, "event": "trim",
                                 "consistent_step": kept})
        d = self._barrier_dir(epoch)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, str(self.host)), "a",
                  encoding="utf-8"):
            pass
        deadline = time.monotonic() + self.barrier_timeout
        while True:
            try:
                present = {int(e) for e in os.listdir(d) if e.isdigit()}
            except OSError:
                present = set()
            if present >= set(range(self.num_hosts)):
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"epoch {epoch} barrier: hosts "
                    f"{sorted(set(range(self.num_hosts)) - present)} "
                    f"never arrived within {self.barrier_timeout}s")
            time.sleep(self.poll)

    # -------------------------------------------------------------- run

    def _child_env(self, epoch: int) -> dict:
        env = {**os.environ, **self.env}
        env.update({
            "DKT_CLUSTER_DIR": self.coord_dir,
            "DKT_CLUSTER_HOST": str(self.host),
            "DKT_CLUSTER_NHOSTS": str(self.num_hosts),
            "DKT_CLUSTER_EPOCH": str(epoch),
            "DKT_CLUSTER_BASE_PORT": str(self.base_port),
            "DKT_CLUSTER_WINDOW": str(self.window),
            "DKT_CLUSTER_INTERVAL": str(self.heartbeat_interval),
            "DKT_CLUSTER_GRACE": str(self.grace),
        })
        return env

    def run(self) -> dict:
        """Drive attempts until one epoch's child exits 0 with the
        epoch still current.  Returns a summary dict (``epochs`` used,
        ``restarts``, per-attempt ``history``)."""
        restarts = 0
        while True:
            epoch = self.epochs.current()
            self._enter_barrier(epoch)
            monitor = HealthMonitor(
                os.path.join(self.coord_dir, "hb"), self.host,
                self.num_hosts, window=self.window, grace=self.grace)
            t0 = time.monotonic()
            child = subprocess.Popen(self.command,
                                     env=self._child_env(epoch))
            reason = None
            try:
                while child.poll() is None:
                    if self.attempt_timeout is not None and \
                            time.monotonic() - t0 > self.attempt_timeout:
                        reason = "attempt timeout"
                    elif self.epochs.current() > epoch:
                        reason = "epoch advanced"
                    else:
                        stale = monitor.stale_peers(epoch=epoch)
                        if stale:
                            reason = f"stale peers {stale}"
                            self.epochs.request(epoch + 1)
                    if reason is not None:
                        child.kill()
                        child.wait(timeout=30)
                        break
                    time.sleep(self.poll)
            finally:
                if child.poll() is None:
                    child.kill()
                    child.wait(timeout=30)
            rc = child.returncode
            duration = time.monotonic() - t0
            self.history.append({
                "epoch": epoch, "event": "attempt", "rc": rc,
                "reason": reason,
                "duration": duration})
            if rc == 0 and self.epochs.current() == epoch:
                return {"host": self.host, "epochs": epoch + 1,
                        "restarts": restarts, "history": self.history}
            if (self.healthy_uptime is not None and restarts
                    and duration >= self.healthy_uptime
                    and reason != "attempt timeout"):
                # Flap dampening: the attempt was healthy long enough
                # that this failure is a fresh fault, not the next
                # rung of a crash loop — refund the budget before
                # charging it.  A kill for exceeding attempt_timeout
                # is excluded: a deterministically hung child always
                # "survives" past healthy_uptime, and refunding it
                # would make ClusterGivenUp unreachable.
                self.history.append({"epoch": epoch, "event": "refund",
                                     "restarts_forgiven": restarts})
                restarts = 0
            self.epochs.request(epoch + 1)
            restarts += 1
            if restarts > self.max_restarts:
                raise ClusterGivenUp(
                    f"host {self.host}: {restarts} coordinated "
                    f"restarts exhausted (last rc={rc}, "
                    f"reason={reason})")


def run_cluster_local(command, num_hosts: int, coord_dir: str, *,
                      per_host_env=None, base_port: int = 8476,
                      checkpoint_dirs=None, **driver_kw) -> list[dict]:
    """Dev/test harness: run one :class:`ClusterSupervisor` per host
    in threads of THIS process (each drives its own training
    subprocesses).  ``per_host_env``: ``{host: {ENV: VAL}}`` extras —
    how chaos schedules are delivered to a single host.  Returns every
    driver's summary; any driver failure re-raises after all join."""
    per_host_env = per_host_env or {}
    results: list = [None] * num_hosts
    errors: list = [None] * num_hosts

    def drive(h):
        try:
            sup = ClusterSupervisor(
                coord_dir, h, num_hosts, command,
                env=per_host_env.get(h), base_port=base_port,
                checkpoint_dirs=checkpoint_dirs, **driver_kw)
            results[h] = sup.run()
        except BaseException as e:  # noqa: BLE001 — reported below
            errors[h] = e

    threads = [threading.Thread(target=drive, args=(h,),
                                name=f"dkt-driver-host{h}", daemon=True)
               for h in range(num_hosts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for h, e in enumerate(errors):
        if e is not None:
            raise RuntimeError(f"cluster driver for host {h} failed") from e
    return results


__all__ = ["EXIT_RESTART", "ClusterGivenUp", "EpochStore",
           "ClusterMember", "ClusterSupervisor", "member_from_env",
           "run_cluster_local", "step_is_valid", "valid_steps",
           "latest_valid_step", "cluster_consistent_step",
           "trim_to_consistent"]
