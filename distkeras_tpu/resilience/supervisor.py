"""Preemption-safe training supervision: retry, backoff, auto-resume.

The reference framework's failure story is "the job dies" (SURVEY.md
§5: single-point-of-failure parameter server, no worker retry).  The
TPU rebuild already persists training state
(:class:`~distkeras_tpu.checkpoint.CheckpointManager`); this module
adds the loop that *uses* it: a :class:`Supervisor` wraps any trainer's
``train`` with

- **retry + exponential backoff with jitter** on faults (IO errors,
  injected chaos, flaky infrastructure), resuming from the latest
  checkpoint instead of restarting from scratch;
- a **preemption signal handler**: on SIGTERM the trainer's next round
  boundary forces a final *synchronous* checkpoint and raises
  :class:`~distkeras_tpu.resilience.chaos.Preempted`, so an evicted VM
  loses at most one round of work;
- **verified auto-resume**: the latest checkpoint step must never move
  backward across attempts, and the trainers' own restore validation
  (step-counter vs round arithmetic, round-keyed dropout RNG streams)
  guarantees a resumed run replays the uninterrupted trajectory
  bit-for-bit on CPU (pinned by tests/test_resilience.py).

Works with every trainer in the family — anything built on
``CheckpointingBase`` (``SingleTrainer``, the distributed/elastic
trainers, ``LMTrainer``/``LoRATrainer``) — because the preemption hook
and the chaos probe live in the shared ``_checkpoint`` round
bookkeeping.
"""

from __future__ import annotations

import dataclasses
import random
import signal
import threading
import time

from distkeras_tpu import obs
from distkeras_tpu.resilience.chaos import Preempted


@dataclasses.dataclass
class Attempt:
    """One ``trainer.train`` invocation under the supervisor."""

    index: int
    outcome: str               # "ok" | "fault" | "preempted"
    error: str | None
    resumed_from: int | None   # checkpoint step the attempt started at
    duration: float


class Supervisor:
    """Run ``trainer.train`` to completion across faults and preemptions.

    ``trainer`` must checkpoint periodically (``checkpoint_dir`` +
    ``checkpoint_every``) — without durable mid-run state there is
    nothing to resume and a retry would silently retrain from scratch.

    ``max_retries``: fault retries (beyond the first attempt) before
    giving up and re-raising.  ``max_preemptions`` bounds SIGTERM/
    ``Preempted`` resumptions separately — preemptions are expected
    lifecycle events, not faults, and consume no backoff.

    Backoff for attempt k (1-based) sleeps
    ``min(backoff * backoff_factor**(k-1), max_backoff)`` scaled by
    ``1 + jitter * U[0, 1)`` — the jitter decorrelates a fleet of
    restarting workers (seeded: deterministic in tests).

    ``handle_sigterm``: install a SIGTERM handler for the duration of
    :meth:`run` (restored afterward) that requests a graceful
    preemption; only the main thread can own signal handlers, so pass
    ``False`` when supervising from a worker thread and deliver the
    preemption by setting ``supervisor.preempt_event`` yourself.
    """

    def __init__(self, trainer, max_retries: int = 3,
                 max_preemptions: int = 8, backoff: float = 0.5,
                 backoff_factor: float = 2.0, max_backoff: float = 30.0,
                 jitter: float = 0.5, seed: int = 0,
                 handle_sigterm: bool = True,
                 retryable: tuple = (Exception,),
                 sleep=None):
        if not getattr(trainer, "checkpoint_dir", None):
            raise ValueError(
                "Supervisor needs a trainer with checkpoint_dir set — "
                "retry without durable state would restart from scratch")
        if not getattr(trainer, "checkpoint_every", 0):
            raise ValueError(
                "Supervisor needs checkpoint_every >= 1: a fault must "
                "cost at most checkpoint_every rounds of recompute, not "
                "the whole run")
        if getattr(trainer, "shuffle", False) and trainer.seed is None:
            raise ValueError(
                "supervised training with shuffle=True needs a fixed "
                "seed: auto-resume skips the first N rounds of the "
                "stream, which only lands on the right data if the "
                "permutation is reproducible")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if backoff < 0 or max_backoff < backoff:
            raise ValueError(
                f"need 0 <= backoff <= max_backoff, got {backoff}, "
                f"{max_backoff}")
        self.trainer = trainer
        self.max_retries = max_retries
        self.max_preemptions = max_preemptions
        self.backoff = backoff
        self.backoff_factor = backoff_factor
        self.max_backoff = max_backoff
        self.jitter = jitter
        self.retryable = retryable
        self.handle_sigterm = handle_sigterm
        self._sleep = sleep
        self._rng = random.Random(seed)
        self.preempt_event = threading.Event()
        self.attempts: list[Attempt] = []

    # ------------------------------------------------------------ state

    def latest_step(self) -> int | None:
        """Latest committed AND valid checkpoint step, backend-agnostic
        (both the orbax and pickle backends commit a step by renaming
        an integer-named directory into place; a torn step — died
        mid-save, no atomic rename — does not count as progress, and
        the trainer's restore validation trims it on resume)."""
        from distkeras_tpu.resilience.cluster import latest_valid_step

        d = self.trainer.checkpoint_dir
        return latest_valid_step(d) if d else None

    def backoff_for(self, retry: int) -> float:
        """Sleep before fault retry ``retry`` (1-based)."""
        base = min(self.backoff * self.backoff_factor ** (retry - 1),
                   self.max_backoff)
        return base * (1.0 + self.jitter * self._rng.random())

    def _backoff_sleep(self, wait: float) -> None:
        """Interruptible backoff: a SIGTERM during the window must not
        ride it out — ``preempt_event.wait`` returns the instant the
        preemption arrives, and the next attempt's first round boundary
        then runs the normal forced-sync-checkpoint path (a preemption
        outranks politeness toward a flaky disk).  An injected
        ``sleep=`` (tests) bypasses the event and keeps full control of
        timing."""
        if self._sleep is not None:
            self._sleep(wait)
        else:
            self.preempt_event.wait(wait)

    # -------------------------------------------------------------- run

    def run(self, *args, **kw):
        """``trainer.train(*args, **kw)`` to completion; returns its
        result.  Exhausted retries re-raise the last fault."""
        installed = False
        prev_handler = None
        if self.handle_sigterm:
            prev_handler = signal.signal(
                signal.SIGTERM, lambda *_: self.preempt_event.set())
            installed = True
        self.trainer.preempt_event = self.preempt_event
        orig_resume = getattr(self.trainer, "resume", False)
        retries = preemptions = 0
        try:
            while True:
                resumed_from = self.latest_step()
                if resumed_from is not None:
                    # Auto-resume: the crash-restart case (this process
                    # is the rerun after an eviction) and the retry case
                    # share one path.
                    self.trainer.resume = True
                t0 = time.perf_counter()
                try:
                    result = self.trainer.train(*args, **kw)
                except Preempted as e:
                    self._record("preempted", e, resumed_from, t0)
                    self.preempt_event.clear()
                    preemptions += 1
                    obs.count("supervisor.preemptions")
                    if preemptions > self.max_preemptions:
                        raise
                    self._verify_progress(resumed_from)
                    continue
                except self.retryable as e:
                    self._record("fault", e, resumed_from, t0)
                    retries += 1
                    obs.count("supervisor.retries")
                    if retries > self.max_retries:
                        raise
                    self._verify_progress(resumed_from)
                    wait = self.backoff_for(retries)
                    obs.event("supervisor.backoff", seconds=wait,
                              retry=retries)
                    obs.observe("supervisor.backoff_s", wait)
                    self._backoff_sleep(wait)
                    continue
                self._record("ok", None, resumed_from, t0)
                return result
        finally:
            self.trainer.preempt_event = None
            # resume=True is run()'s internal retry machinery; leaving
            # it flipped would disable the trainer's designed
            # refuse-to-overwrite guard on later direct train() calls.
            self.trainer.resume = orig_resume
            if installed:
                # A None prev_handler means SIGTERM was owned outside
                # Python (unrestorable from here); SIG_DFL at least
                # restores default termination instead of leaving our
                # event-setting lambda installed forever.
                signal.signal(signal.SIGTERM,
                              prev_handler if prev_handler is not None
                              else signal.SIG_DFL)

    # ---------------------------------------------------------- helpers

    def _record(self, outcome, error, resumed_from, t0):
        att = Attempt(
            index=len(self.attempts), outcome=outcome,
            error=None if error is None else repr(error),
            resumed_from=resumed_from,
            duration=time.perf_counter() - t0)
        self.attempts.append(att)
        # Every attempt (and restart) lands in the obs event trace:
        # the machine-readable fault/recovery timeline chaos_suite.py
        # and obs_report.py reconstruct.
        obs.event("supervisor.attempt", index=att.index,
                  outcome=outcome, resumed_from=resumed_from,
                  duration_s=att.duration, error=att.error)

    def _verify_progress(self, before: int | None):
        """Crash-consistency check between attempts: the checkpoint
        step counter must never move backward (a truncated/corrupted
        store resuming earlier than a previous attempt would silently
        replay — and with a different RNG/step alignment, diverge)."""
        after = self.latest_step()
        if before is not None and (after is None or after < before):
            raise RuntimeError(
                f"checkpoint store at {self.trainer.checkpoint_dir!r} "
                f"moved backward across attempts (step {before} -> "
                f"{after}); refusing to resume from a store that lost "
                "committed state")
