"""Deterministic, seedable fault injection (chaos hooks).

The reference stack dies whole-job on any single failure and never
*exercises* that path — the parameter server is a single point of
failure and nothing in its test suite ever kills a worker (SURVEY.md
§5).  This module is the other half of a real failure story: the code
paths that production leans on (checkpoint saves, the training round
loop, the serving decode step, the speculative draft) each carry a
**probe site**, and a :class:`FaultPlan` decides — deterministically,
from a seed — which probes fire a fault.

Usage (tests, and scripts/chaos_suite.py)::

    from distkeras_tpu.resilience import chaos

    plan = chaos.FaultPlan(seed=0)
    plan.fail("train.round", at=7)           # raise FaultInjected at round 7
    plan.preempt("train.round", at=5)        # raise Preempted (preemption)
    plan.fail("checkpoint.save")             # next save raises
    plan.delay("serving.step", seconds=0.01) # slow every decode window
    with plan:
        ...                                  # faults fire; plan.events records them

Sites are probed by the production code via :func:`probe`; when no plan
is active the probe is a module-level ``None`` check — effectively
free.  One plan is active at a time (nesting is a usage error: a chaos
schedule must be read off one plan, not two interleaved ones).

Probes are **host-side only**.  Nothing here reaches inside a jitted
program — a fault lands between device dispatches, which is exactly
where real preemptions and IO failures land.
"""

from __future__ import annotations

import dataclasses
import random
import signal as _signal
import time
from typing import Callable

from distkeras_tpu import obs

# The known probe sites, checked at rule-registration time so a typo'd
# site fails loudly instead of silently never firing.
SITES = (
    "train.round",      # trainer family: start of every round's bookkeeping
    "checkpoint.save",  # CheckpointManager.save (both backends)
    "serving.step",     # ContinuousBatcher/SpeculativeBatcher.step
    "serving.admit",    # lane admission (submit/pump)
    "serving.draft",    # SpeculativeBatcher's draft half of the step
    "cluster.heartbeat",  # HeartbeatWriter: before every beat publishes
    "cluster.push",     # AsyncPlane.push: before a host's delta publishes
    "cluster.merge",    # AsyncPlane aggregation wave: before center applies
    "autoscale.join",   # Autoscaler scale-up: between warm-pool take
                        # and the join health gate (round 19)
    "publish.commit",   # SnapshotPublisher: between the bucket writes
                        # and the atomic manifest rename — a kill here
                        # leaves a torn snapshot no reader adopts
                        # (round 20)
    "canary.promote",   # CanaryController: between the canary gate
                        # passing and the fleet-wide swap (round 20)
)


class FaultInjected(RuntimeError):
    """Default error raised by an injected fault."""


class BeatDropped(RuntimeError):
    """Internal signal of a ``drop`` rule: the heartbeat writer catches
    it and skips publishing the beat — the partition fault kind (host
    alive, beats invisible to peers).  Never escapes the writer."""


class Preempted(RuntimeError):
    """A (simulated or real) preemption: stop now, resume from the
    latest checkpoint.  Raised by the preemption machinery in
    ``CheckpointingBase._checkpoint`` after it forces a final
    synchronous checkpoint, and by ``FaultPlan.preempt`` rules; the
    :class:`~distkeras_tpu.resilience.supervisor.Supervisor` treats it
    as resumable rather than as a failure."""


@dataclasses.dataclass
class _Rule:
    site: str
    kind: str            # "fail" | "delay" | "signal" | "kill" | "drop"
    at: int | None = None          # fire when the probe's step/call == at
    times: int | None = 1          # firings remaining (None = unlimited)
    error: Callable[[str], BaseException] | None = None
    seconds: float = 0.0
    p: float = 1.0                 # firing probability (plan-seeded RNG)
    fired: int = 0


class FaultPlan:
    """A deterministic schedule of faults over the probe sites.

    ``seed`` drives the one RNG behind probabilistic rules (``p < 1``),
    so a chaos run is reproducible end to end.  ``events`` records every
    firing as ``(site, step, kind)`` for assertions.
    """

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._rules: list[_Rule] = []
        self._calls: dict[str, int] = {}
        self.events: list[tuple[str, int, str]] = []

    # ------------------------------------------------------------ rules

    def _check_site(self, site: str) -> None:
        if site not in SITES:
            raise ValueError(
                f"unknown chaos site {site!r}; known sites: {SITES}")

    def fail(self, site: str, at: int | None = None, times: int | None = 1,
             error=None, p: float = 1.0) -> "FaultPlan":
        """Raise at ``site`` (``error``: exception class or factory
        taking the message; default :class:`FaultInjected`)."""
        self._check_site(site)
        self._rules.append(_Rule(site, "fail", at=at, times=times,
                                 error=error or FaultInjected, p=p))
        return self

    def preempt(self, site: str = "train.round", at: int | None = None,
                via_signal: bool = False) -> "FaultPlan":
        """Simulate a preemption at ``site``.

        ``via_signal=False`` raises :class:`Preempted` directly from the
        probe; ``via_signal=True`` delivers a real SIGTERM to this
        process instead — the full production path: the Supervisor's
        handler marks the preemption and the trainer's next round
        boundary forces a synchronous checkpoint and raises.
        """
        self._check_site(site)
        if via_signal:
            self._rules.append(_Rule(site, "signal", at=at, times=1))
        else:
            self._rules.append(_Rule(site, "fail", at=at, times=1,
                                     error=Preempted))
        return self

    def delay(self, site: str, seconds: float, at: int | None = None,
              times: int | None = None, p: float = 1.0) -> "FaultPlan":
        """Sleep ``seconds`` at ``site`` (default: every probe).  On
        ``cluster.heartbeat`` this is the **heartbeat-stall** fault
        kind: the writer thread wedges mid-beat and peers see the host
        go stale."""
        self._check_site(site)
        if seconds < 0:
            raise ValueError(f"seconds must be >= 0, got {seconds}")
        self._rules.append(_Rule(site, "delay", at=at, times=times,
                                 seconds=seconds, p=p))
        return self

    def kill(self, site: str, at: int | None = None,
             rc: int = 137) -> "FaultPlan":
        """**Host-kill** fault kind: ``os._exit(rc)`` at ``site`` — the
        process dies instantly with no cleanup, no atexit, no final
        checkpoint, exactly like SIGKILL/hardware loss.  The default rc
        mirrors a SIGKILLed process (128 + 9).  Only meaningful in
        multiprocess chaos runs (the cluster restart harness); a
        single-process test that kills itself takes pytest with it."""
        self._check_site(site)
        self._rules.append(_Rule(site, "kill", at=at, times=1,
                                 seconds=float(rc)))
        return self

    def drop(self, site: str = "cluster.heartbeat", at: int | None = None,
             times: int | None = 1, p: float = 1.0) -> "FaultPlan":
        """**Partition** fault kind: the probe site swallows the
        operation instead of performing it.  On ``cluster.heartbeat``
        the beat is silently not published — the host keeps running
        (and keeps training) while its peers watch it go stale, which
        is what a network partition looks like from the outside."""
        self._check_site(site)
        self._rules.append(_Rule(site, "drop", at=at, times=times, p=p))
        return self

    # ------------------------------------------------------------ firing

    def probe(self, site: str, step: int | None = None) -> None:
        """Evaluate this plan at one probe point.  ``step``: the
        caller's own counter (round number, step index); rules with
        ``at`` match against it, or against the per-site call index
        (1-based) when the caller has no counter."""
        self._calls[site] = self._calls.get(site, 0) + 1
        n = self._calls[site] if step is None else step
        for rule in self._rules:
            if rule.site != site:
                continue
            if rule.times is not None and rule.fired >= rule.times:
                continue
            if rule.at is not None and n != rule.at:
                continue
            if rule.p < 1.0 and self._rng.random() >= rule.p:
                continue
            rule.fired += 1
            self.events.append((site, n, rule.kind))
            # Injected faults ride the obs event trace (when a
            # telemetry session is active), so a chaos run's
            # fault/recovery timeline is machine-readable —
            # scripts/chaos_suite.py --trace and obs_report.py
            # reconstruct it without parsing logs.
            obs.event("chaos.fault", site=site, step=n, kind=rule.kind)
            obs.count("chaos.faults", site=site, kind=rule.kind)
            if rule.kind == "delay":
                time.sleep(rule.seconds)
            elif rule.kind == "signal":
                _signal.raise_signal(_signal.SIGTERM)
            elif rule.kind == "kill":
                import os

                # Hard host loss: flush what telemetry we can (the
                # trace file is line-buffered) and die without cleanup.
                os._exit(int(rule.seconds))
            elif rule.kind == "drop":
                raise BeatDropped(f"chaos: dropped {site} (step {n})")
            else:
                raise rule.error(f"chaos: injected fault at {site} "
                                 f"(step {n})")

    # ------------------------------------------------------- activation

    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a FaultPlan is already active; chaos "
                               "plans do not nest")
        _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None


_ACTIVE: FaultPlan | None = None


def active_plan() -> FaultPlan | None:
    return _ACTIVE


def probe(site: str, step: int | None = None) -> None:
    """Production-side hook: no-op unless a :class:`FaultPlan` is
    active (one attribute load + ``is`` check on the hot path)."""
    if _ACTIVE is not None:
        _ACTIVE.probe(site, step)
