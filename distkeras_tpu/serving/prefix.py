"""Multi-prefix KV pool: N prefilled prefix segments, device-resident.

The single-prefix engines (``prompt_cache=`` — one shared system
prompt compiled into admission) cover exactly one deployment shape.
Real fleets serve MANY prefixes at once: a handful of system prompts,
per-tenant few-shot preambles, tool schemas.  :class:`PrefixPool`
holds up to ``slots`` prefilled prefix segments stacked in ONE device
slab; requests carry ``prefix_id`` at ``submit``/``enqueue`` and the
admission program GATHERS the right segment into the lane — so a
request reusing a pooled prefix runs **zero prefill work for the
prefix tokens** (only its tail's admission chunk executes), and one
compiled admission program serves every prefix.

Bookkeeping is host-side and deliberately boring:

- **refcounts**: a lane occupying a prefix pins it
  (``acquire``/``release`` are called by the engines at admission and
  lane vacation); a pinned entry is never evicted.
- **LRU eviction**: ``put`` on a full pool evicts the
  least-recently-used entry with zero references; if every entry is
  pinned, ``put`` raises instead of corrupting an in-flight lane.
- **ids are never reused**: a stale ``prefix_id`` fails loudly at
  submit instead of silently serving someone else's prefix.

Segments are what :func:`~distkeras_tpu.models.generate.prefill`
returns — a full-``max_len`` batch-1 cache with the prefix slots
filled and the rest zero, exactly the fresh-lane seed admission needs
(``kv_int8`` segments must come from ``prefill(..., kv_int8=True)``,
the same quantization-match contract as ``prompt_cache``).  For
:class:`~distkeras_tpu.serving.SpeculativeBatcher` pools
(``draft_cfg=`` given), a segment is the ``(target_cache,
draft_cache)`` pair — the same prefix prefilled through both models.

The slab write is ONE pre-compiled program (warmed at construction,
slot traced), so populating or rotating prefixes never recompiles —
pinned by ``scripts/check_compile_counts.py``'s ``serving_prefix_pool``
and ``spec_prefix`` sessions.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from distkeras_tpu.models.generate import init_cache
from distkeras_tpu.models.transformer import TransformerConfig
from distkeras_tpu.utils.locks import TracedRLock


@dataclasses.dataclass
class _Entry:
    slot: int
    length: int
    refs: int = 0
    tick: int = 0
    last_token: int | None = None


class PrefixPool:
    """Refcounted, LRU-evicting pool of prefilled prefix segments.

    ``cfg``: the serving model config (segment shape =
    ``init_cache(cfg, 1, kv_int8=kv_int8)``).  ``slots``: device
    capacity — the slab holds ``slots`` segments, ~``slots`` x one
    lane's cache bytes of HBM.  ``draft_cfg``: build a speculative
    pool instead (segments are ``(target, draft)`` cache pairs; no
    ``kv_int8`` — the speculative engines hold bf16 caches).

    Thread-safe: one lock serializes ``put``/``acquire``/``release``
    (engines call acquire/release under their own admission locks, but
    a pool may be shared across engines).

    ``mesh``/``kv_axis`` (round 14): build the pool for a pod-sharded
    engine — the slab commits with the engine's KV sharding (kv-heads
    over ``kv_axis``) so the pooled admission gather stays a sharded
    device gather with zero resharding; the engine validates the
    match at construction.
    """

    def __init__(self, cfg: TransformerConfig, slots: int = 4,
                 kv_int8: bool = False,
                 draft_cfg: TransformerConfig | None = None,
                 mesh=None, kv_axis: str | None = "model"):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if mesh is not None and draft_cfg is not None:
            raise ValueError(
                "sharded pools serve pod-sharded ContinuousBatchers; "
                "SpeculativeBatcher has no plan= mode, so a sharded "
                "speculative pool has no consumer")
        if cfg.attention_window is not None or (
                draft_cfg is not None
                and draft_cfg.attention_window is not None):
            raise ValueError(
                "prefix pools need full-cache configs (no "
                "attention_window): a ring slot has no stable notion "
                "of 'the first P positions' to seed from")
        if draft_cfg is not None and kv_int8:
            raise ValueError(
                "speculative pools hold full-precision caches "
                "(SpeculativeBatcher has no kv_int8 mode)")
        self.cfg = cfg
        self.draft_cfg = draft_cfg
        self.kv_int8 = kv_int8
        self.slots = slots
        if draft_cfg is None:
            seg = init_cache(cfg, 1, kv_int8=kv_int8)
        else:
            seg = (init_cache(cfg, 1), init_cache(draft_cfg, 1))
        self._seg_spec = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), seg)
        self.slab = jax.tree.map(
            lambda a: jnp.zeros((slots,) + a.shape, a.dtype), seg)
        # Pod-sharded placement (round 14): a pool serving a
        # ``plan=``/``mesh=`` engine commits its slab with the SAME
        # kv-heads sharding the engine's cache uses (the shared
        # kv_slab_specs rule — the slab layout just carries a leading
        # [slots] axis), so the pooled admission gather is a sharded
        # device gather with zero resharding.  The engine validates
        # the match at construction.
        self.mesh = mesh
        self.kv_axis = kv_axis if mesh is not None else None
        constrain = None
        if mesh is not None:
            from distkeras_tpu.parallel.rules import kv_slab_shardings

            if self.kv_axis is not None \
                    and cfg.kv_heads % int(mesh.shape[self.kv_axis]):
                raise ValueError(
                    f"kv_heads={cfg.kv_heads} is not divisible by "
                    f"mesh axis {self.kv_axis!r} "
                    f"(size {int(mesh.shape[self.kv_axis])})")
            slab_sh = kv_slab_shardings(mesh, self.slab, self.kv_axis)
            self.slab = jax.device_put(self.slab, slab_sh)

            def constrain(slab):
                return jax.lax.with_sharding_constraint(
                    slab, kv_slab_shardings(mesh, slab, self.kv_axis))

        def put(slab, seg, slot):
            out = jax.tree.map(
                lambda s, g: jax.lax.dynamic_update_slice_in_dim(
                    s, g.astype(s.dtype)[None], slot, axis=0), slab, seg)
            return constrain(out) if constrain is not None else out

        # Slot is traced: ONE compiled write program for the pool's
        # lifetime, warmed here so put() never compiles at serve time.
        # NOT donated: an engine admitting on another thread may hold
        # the previous slab buffer for an in-flight gather — put() is
        # rare (operator-paced), so the copy is the safe trade.
        self._put = jax.jit(put)
        self.slab = self._put(self.slab, seg, jnp.int32(0))

        self._entries: dict[int, _Entry] = {}
        self._next_id = 0
        self._tick = 0
        # Leaf lock: engines acquire it UNDER their admission lock
        # (_pin_prefix/_vacate); nothing is acquired under this one.
        self._lock = TracedRLock("serving.prefix_pool")

    # -------------------------------------------------------- mutation

    def put(self, segment, length: int, last_token: int | None = None
            ) -> int:
        """Insert a prefilled segment; returns its ``prefix_id``.

        ``segment``: the ``prefill(prefix[None], ...)`` cache (or the
        ``(target, draft)`` pair for speculative pools) — structure,
        shapes, and dtypes must match the pool's spec exactly.
        ``length``: the prefix token count the segment holds.
        ``last_token``: the prefix's final token — optional metadata a
        :class:`SpeculativeBatcher` needs to admit a **1-token** prompt
        against this prefix (its draft chunk rewrites the position
        before the prompt).

        A full pool evicts the least-recently-used entry with zero
        references; if every entry is referenced by a lane, raises
        ``RuntimeError`` (shed the put or grow ``slots``).
        """
        if length < 1:
            raise ValueError(f"prefix length must be >= 1, got {length}")
        if length >= self.cfg.max_len:
            raise ValueError(
                f"prefix length {length} must leave room under "
                f"max_len={self.cfg.max_len}")
        spec = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                           jnp.asarray(a).dtype), segment)
        if (jax.tree.structure(spec) != jax.tree.structure(self._seg_spec)
                or jax.tree.leaves(spec) != jax.tree.leaves(
                    self._seg_spec)):
            raise ValueError(
                f"segment does not match the pool's spec "
                f"{self._seg_spec} (build it with prefill() on the "
                "pool's config, kv_int8 matching)")
        with self._lock:
            used = {e.slot for e in self._entries.values()}
            free = [s for s in range(self.slots) if s not in used]
            if free:
                slot = free[0]
            else:
                victims = [(e.tick, pid) for pid, e in
                           self._entries.items() if e.refs == 0]
                if not victims:
                    raise RuntimeError(
                        f"prefix pool full: all {self.slots} slots are "
                        "referenced by live lanes; wait for requests "
                        "to finish or grow slots")
                _, victim = min(victims)
                slot = self._entries.pop(victim).slot
            self.slab = self._put(self.slab, segment, jnp.int32(slot))
            pid = self._next_id
            self._next_id += 1
            self._tick += 1
            self._entries[pid] = _Entry(slot=slot, length=int(length),
                                        tick=self._tick,
                                        last_token=last_token)
            return pid

    def acquire(self, prefix_id: int) -> _Entry:
        """Pin the entry (a lane is about to decode against it) and
        mark it recently used; returns the entry.  Engines call this
        under their admission lock; callers use ``submit(prefix_id=)``
        instead."""
        with self._lock:
            e = self._entry(prefix_id)
            e.refs += 1
            self._tick += 1
            e.tick = self._tick
            return e

    def release(self, prefix_id: int) -> None:
        """Unpin (the referencing lane was vacated)."""
        with self._lock:
            e = self._entries.get(prefix_id)
            if e is not None and e.refs > 0:
                e.refs -= 1

    # ------------------------------------------------------ inspection

    def _entry(self, prefix_id: int) -> _Entry:
        e = self._entries.get(prefix_id)
        if e is None:
            raise KeyError(
                f"unknown prefix_id {prefix_id} (evicted or never "
                "inserted; ids are never reused)")
        return e

    def length_of(self, prefix_id: int) -> int:
        return self._entry(prefix_id).length

    def slot_of(self, prefix_id: int) -> int:
        return self._entry(prefix_id).slot

    def last_token_of(self, prefix_id: int) -> int | None:
        return self._entry(prefix_id).last_token

    def refs_of(self, prefix_id: int) -> int:
        return self._entry(prefix_id).refs

    def ids(self) -> list[int]:
        return sorted(self._entries)

    def __contains__(self, prefix_id: int) -> bool:
        return prefix_id in self._entries

    def __len__(self) -> int:
        return len(self._entries)


@dataclasses.dataclass
class _Stem:
    blocks: tuple          # block ids pinned for this prefix, in order
    length: int            # prefix token count (a multiple of block)


class PinnedStems:
    """Host-side registry of PINNED block runs on a paged KV slab —
    the :class:`PrefixPool` story re-expressed in the paged engine's
    one-allocator world (round 12).

    Where the pool holds prefix segments in its OWN device slab and
    requests name them by ``prefix_id``, a pinned stem is just a run
    of ordinary cache blocks in the engine's slab whose refcounts this
    registry holds up (so the allocator can never recycle them), each
    block hash-registered like any admission-prefilled block.
    Requests need no id at all: a prompt that starts with the pinned
    tokens hash-hits the blocks through normal stem sharing — one
    mechanism serves "registered system prompt" and "two requests
    happened to share a stem" alike.

    Pure bookkeeping: the engine
    (:meth:`~distkeras_tpu.serving.paged.PagedBatcher.pin_prefix`)
    prefills the blocks and takes the references; this class only
    records which blocks each pin holds so ``unpin`` releases exactly
    them.  Engines call it under their admission lock; the leaf lock
    keeps a shared registry safe anyway (same posture as the pool).
    """

    def __init__(self):
        self._entries: dict[int, _Stem] = {}
        self._next_id = 0
        self._lock = TracedRLock("serving.pinned_stems")

    def add(self, blocks, length: int) -> int:
        with self._lock:
            pid = self._next_id
            self._next_id += 1
            self._entries[pid] = _Stem(tuple(blocks), int(length))
            return pid

    def pop(self, prefix_id: int) -> tuple:
        """Remove the pin and return its block run (the caller
        releases the references)."""
        with self._lock:
            e = self._entries.pop(prefix_id, None)
            if e is None:
                raise KeyError(
                    f"unknown pinned prefix {prefix_id} (unpinned "
                    "already or never pinned; ids are never reused)")
            return e.blocks

    def length_of(self, prefix_id: int) -> int:
        return self._entry(prefix_id).length

    def blocks_of(self, prefix_id: int) -> tuple:
        return self._entry(prefix_id).blocks

    def _entry(self, prefix_id: int) -> _Stem:
        e = self._entries.get(prefix_id)
        if e is None:
            raise KeyError(f"unknown pinned prefix {prefix_id}")
        return e

    def ids(self) -> list[int]:
        return sorted(self._entries)

    def __contains__(self, prefix_id: int) -> bool:
        return prefix_id in self._entries

    def __len__(self) -> int:
        return len(self._entries)


__all__ = ["PrefixPool", "PinnedStems"]
