"""Admission control for the serving engines (resilience subsystem).

The host-side production layer both engines inherit: per-request
``ttl``/``deadline`` with lane eviction and structured
:class:`RequestResult` reporting, the bounded ``enqueue`` FIFO with
:class:`QueueFull` backpressure, expired-on-arrival handling, the
drain-then-``shutdown()`` lifecycle, and the engine lock that makes
admission atomic against ``begin_shutdown`` (EngineClosed wins).

The exception/result TYPES live in
:mod:`distkeras_tpu.resilience.admission` (the resilience subsystem
owns the contract); this module re-exports them so
``from distkeras_tpu.serving import QueueFull`` keeps working, and
adds the engine-side mixin that implements the behavior.  All of it is
pure host bookkeeping — the compiled decode programs and their
exact-parity contract are untouched.
"""

from __future__ import annotations

import collections
import time

import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.resilience.admission import (EngineClosed, QueueFull,
                                                 RequestResult, _Pending)
from distkeras_tpu.utils.locks import TracedRLock


class _AdmissionMixin:
    """Admission-control behavior for :class:`_LaneEngine`: queueing,
    deadlines, structured results, lifecycle.  Assumes the host lane
    table (``_lane_state``, ``free_lanes``, ``running``, ``_vacate``)
    and the engine's ``submit``/``step`` exist on the composed class.
    """

    def _init_admission(self, max_queue: int, clock) -> None:
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self._clock = clock if clock is not None else time.monotonic
        self._pending = collections.deque()
        self._completed: dict[int, RequestResult] = {}
        self._closed = False
        # One lock makes the closed-check and the queue insert ATOMIC:
        # a begin_shutdown() racing an in-flight enqueue() must yield
        # exactly one of two outcomes — the request raised EngineClosed
        # (close won) or it is in the queue/lane and shutdown's drain
        # reaches it (insert won).  Without the lock, the enqueue could
        # pass the closed check, lose the race, and then raise
        # QueueFull off a queue that shutdown was already cancelling —
        # the caller would shed load from an engine that is not
        # overloaded, it is closing.  EngineClosed WINS: once
        # begin_shutdown returns, every later enqueue/submit raises it,
        # even when the queue is also full.  Reentrant because
        # enqueue -> pump -> _admit_pending nests.  Ordering contract
        # (docs/concurrency.md): this lock is acquired FIRST — pool/
        # obs locks nest inside it, never the reverse.
        self._admission_lock = TracedRLock("serving.admission")
        # Internal admission (enqueue -> pump -> submit) threads the
        # request's ENQUEUE-TIME id through to submit, so every span/
        # event the admission path emits carries the id the caller
        # holds (per-request trace propagation, round 11).  Doubles as
        # the "internal admission in progress" marker (the
        # ``_admitting_internal`` property): ONE piece of state, so
        # the id and the pump-bypasses-_closed behavior cannot drift.
        self._admit_rid: int | None = None
        # Chunked-prefill scheduler state: lanes with pending admission
        # chunks, FIFO (see engine._run_pending_chunk).
        self._admitting = collections.deque()
        # Elastic-tier bookkeeping (ContinuousBatcher(lane_tiers=...);
        # inert defaults for every other engine).
        self.lane_tiers = None
        self.tier_epoch = 0
        self.scale_up_after = 2
        self.scale_down_after = 8
        self._bp_strikes = 0
        self._idle_strikes = 0
        # The id under which the most recent bare submit() recorded (or
        # will record) its RequestResult — how drain()-style callers
        # that pass a ttl reach their structured timeout via poll/take
        # instead of the pop-everything results().
        self.last_request_id: int | None = None
        # Why the most recent submit() declined (None after a success):
        # "no_free_lane", or "kv_blocks" — the paged engine's
        # allocator-exhausted signal, which enqueue/pump treat as
        # QUEUE backpressure (blocks free as lanes drain) instead of
        # inventing a timeout.
        self._decline_reason: str | None = None

    def _deadline_of(self, ttl, deadline):
        """Resolve submit/enqueue's ``ttl`` (seconds from now) /
        ``deadline`` (absolute ``clock()`` time) pair."""
        if ttl is not None and deadline is not None:
            raise ValueError("pass ttl (relative) OR deadline "
                             "(absolute), not both")
        if ttl is not None:
            return self._clock() + ttl
        return deadline

    @property
    def _admitting_internal(self) -> bool:
        """True while ``submit`` runs as internal admission (the
        enqueue -> pump path): pump bypasses ``_closed`` and declines
        register under the caller's id, not a fresh one."""
        return self._admit_rid is not None

    def _check_open(self) -> None:
        if self._closed and not self._admitting_internal:
            obs.count("serving.rejected", reason="closed")
            raise EngineClosed(
                "engine is shutting down (begin_shutdown was called); "
                "no new requests are admitted during drain")

    def _obs_request_done(self, status: str, born,
                          rid: int | None = None) -> None:
        """Terminal-request telemetry: status counter, deadline-miss
        counter, the request latency histogram (engine clock, so
        chaos tests with an injected clock stay deterministic), and
        the ``serving.finish`` trace event closing the request's
        submit -> admit -> emit -> finish story."""
        obs.count("serving.requests", status=status)
        if status == "timeout":
            obs.count("serving.deadline_misses")
        if obs.active() is not None:
            if born is not None:
                obs.observe("serving.request_s", self._clock() - born,
                            status=status)
            if rid is not None:
                obs.event("serving.finish", request_id=rid,
                          status=status)

    def _finish(self, rid: int, tokens, status: str, prompt_len: int,
                error: str | None = None, born=None):
        self._obs_request_done(status, born, rid=rid)
        self._completed[rid] = RequestResult(
            request_id=rid, tokens=np.asarray(tokens, np.int32),
            status=status, prompt_len=prompt_len, error=error)

    def _expired_on_arrival(self, dl, prompt, p: int) -> bool:
        """The ONE expired-on-arrival protocol for both engines'
        ``submit``: an already-dead request never occupies a lane; a
        caller-facing submit records the structured timeout under a
        fresh id (exposed as ``last_request_id``), while internal
        admission (enqueue/pump) declines silently — the caller records
        under the request's own id."""
        if dl is None or dl > self._clock():
            return False
        if not self._admitting_internal:
            rid = self._next_id
            self._next_id += 1
            obs.event("serving.submit", request_id=rid, prompt_len=p,
                      expired_on_arrival=True)
            self._finish(rid, prompt, "timeout", p,
                         born=self._clock())
            self.last_request_id = rid
        return True

    def _claim_rid(self) -> int:
        """The id this admission runs under: the enqueue-assigned id
        when submit is running as internal admission (so the admit
        span/events carry the id the caller holds), else a fresh
        allocation.  No ``last_request_id`` side effect — caller-
        facing submits publish it only once the lane commits."""
        if self._admit_rid is not None:
            return self._admit_rid
        rid = self._next_id
        self._next_id += 1
        return rid

    def _decline(self, reason: str) -> None:
        """Record a submit() decline: no request was registered, so a
        stale ``last_request_id`` must not masquerade as this
        request's; enqueue/pump read ``_decline_reason`` to tell a
        storage decline (retryable backpressure) from a deadline
        expiry."""
        self._decline_reason = reason
        if not self._admitting_internal:
            obs.count("serving.rejected", reason=reason)
            self.last_request_id = None

    def _decline_full(self) -> None:
        self._decline("no_free_lane")

    def enqueue(self, prompt, max_new_tokens: int, ttl=None, deadline=None,
                **submit_kw) -> int:
        """Admission-controlled submit: returns a request id
        immediately; the terminal :class:`RequestResult` arrives via
        :meth:`poll` / :meth:`take` / :meth:`results` once the request
        finishes, times out, or is cancelled by shutdown.

        No free lane: the request waits in the bounded FIFO queue
        (capacity ``max_queue``); past capacity, raises
        :class:`QueueFull` — the backpressure signal.  An already-
        expired deadline never occupies a lane or a queue slot: the
        structured timeout result is recorded up front.

        ``submit_kw`` forwards to this engine's ``submit`` (per-request
        key / sampling overrides / eos_token / ``prefix_id``);
        engine-specific validation beyond the prompt/budget checks runs
        at admission time, which for a queued request is a later
        ``step()`` — a pooled prefix evicted while its request queues
        therefore surfaces as a structured ``"error"`` result, not a
        crash (queued requests do not pin pool entries).

        Thread safety: the closed check and the queue insert are
        atomic under one engine lock, and **EngineClosed wins** — an
        enqueue racing ``begin_shutdown`` either gets its request in
        (and shutdown's drain reaches it) or raises EngineClosed;
        QueueFull is only ever raised by an engine that is actually
        open and overloaded.  On elastic engines (``lane_tiers``),
        sustained overflow steps the lane tier up instead of raising
        (see the ContinuousBatcher docstring).
        """
        with self._admission_lock:
            self._check_open()
            prompt = self._validate_request_args(prompt, max_new_tokens)
            self._validate_budget(prompt.size, max_new_tokens,
                                  **self._budget_kw(submit_kw))
            dl = self._deadline_of(ttl, deadline)
            rid = self._next_id
            self._next_id += 1
            obs.event("serving.submit", request_id=rid,
                      prompt_len=int(prompt.size),
                      max_new=int(max_new_tokens))
            if dl is not None and dl <= self._clock():
                # born=now: a ~0s latency observation, so the request_s
                # histogram count agrees with the requests counter (the
                # deadline-miss population must not vanish from it).
                self._finish(rid, prompt, "timeout", prompt.size,
                             born=self._clock())
                return rid
            pend = _Pending(rid, prompt, int(max_new_tokens), dl,
                            submit_kw, born=self._clock())
            # FIFO: queued requests get first claim on any free lane
            # (and expired heads are dropped) before this one may jump
            # in.
            self.pump()
            if self.free_lanes() and not self._pending:
                # Immediate admission: validation errors raise to the
                # caller here, synchronously.
                if self._admit_pending(pend):
                    self._bp_strikes = 0
                    return rid
                # A lane was free, so submit declined either because
                # the deadline expired between our check and its
                # re-check, or (paged engines) because the KV-block
                # allocator is exhausted — the latter queues like any
                # other backpressure (blocks free as lanes drain).
                if self._decline_reason != "kv_blocks":
                    self._finish(rid, prompt, "timeout", prompt.size,
                                 born=pend.born)
                    return rid
            while len(self._pending) >= self.max_queue:
                if not self._try_scale_up():
                    obs.count("serving.rejected", reason="queue_full")
                    if self._decline_reason == "kv_blocks":
                        # Name the REAL bottleneck: lanes may well be
                        # free — the paged allocator is what's dry,
                        # and "raise max_queue" would tune the wrong
                        # knob.
                        raise QueueFull(
                            f"KV block allocator exhausted and the "
                            f"admission queue holds "
                            f"{len(self._pending)}/{self.max_queue} "
                            "requests; shed load, raise n_blocks, or "
                            "bound request budgets")
                    raise QueueFull(
                        f"all {self.lanes} lanes busy and the "
                        f"admission queue holds {len(self._pending)}/"
                        f"{self.max_queue} requests; shed load or "
                        "raise max_queue")
                # Fresh lanes: queued requests keep FIFO priority,
                # then this one takes a lane or the queue headroom.
                self.pump()
                if self.free_lanes() and not self._pending:
                    if self._admit_pending(pend):
                        return rid
                    if self._decline_reason != "kv_blocks":
                        self._finish(rid, prompt, "timeout",
                                     prompt.size, born=pend.born)
                        return rid
            self._bp_strikes = 0
            self._pending.append(pend)
            obs.gauge("serving.queue_depth", len(self._pending))
            return rid

    def _budget_kw(self, submit_kw) -> dict:
        """Budget-validation kwargs enqueue() resolves up front from
        the submit kwargs: the prefix offset, for pooled requests.
        Advisory only — admission re-validates under its own pin, so
        an entry evicted between enqueue and admission still surfaces
        as a structured error, never a wrong-prefix decode."""
        pid = submit_kw.get("prefix_id")
        if pid is None:
            return {}
        if self._prefix_pool is None:
            raise ValueError(
                f"prefix_id needs "
                f"{type(self).__name__}(prefix_pool=...)")
        try:
            return {"off": self._prefix_pool.length_of(pid)}
        except KeyError as e:
            raise ValueError(str(e)) from e

    def _pin_prefix(self, prefix_id):
        """Atomically PIN a pooled prefix for an admission attempt and
        resolve its parameters: returns ``(length, slot, last_token)``.
        Pinning first closes the eviction race — a pinned entry can
        never be LRU-evicted, so the slot the subsequent slab gather
        reads is guaranteed to still hold THIS prefix (a ``put``
        landing concurrently only ever rewrites unpinned slots).  The
        caller owns the pin: it becomes the admitted lane's reference
        on success and MUST be released on every other exit
        (validation failure, expired-on-arrival, engine full)."""
        if self._prefix_pool is None:
            raise ValueError(
                f"prefix_id needs "
                f"{type(self).__name__}(prefix_pool=...)")
        try:
            e = self._prefix_pool.acquire(prefix_id)
        except KeyError as err:
            raise ValueError(str(err)) from err
        return e.length, e.slot, e.last_token

    def _admit_pending(self, pend) -> bool:
        self._admit_rid = pend.request_id
        self._decline_reason = None
        try:
            lane = self.submit(pend.prompt, pend.max_new,
                               deadline=pend.deadline, **pend.submit_kw)
        finally:
            self._admit_rid = None
        if lane is None:
            return False
        st = self._lane_state[lane]
        # submit() admitted under the enqueue-assigned id (_claim_rid)
        # so its admit span/events already carry the id the caller
        # holds; the assignment is belt and braces.
        st.request_id = pend.request_id
        st.managed = True
        if pend.born is not None:
            # Request latency counts from enqueue, queue wait included.
            st.born = pend.born
            if obs.active() is not None:
                obs.observe("serving.queue_wait_s",
                            self._clock() - pend.born)
        return True

    def pump(self) -> list[int]:
        """Admit queued requests into free lanes (FIFO); queued
        requests whose deadline expired are dropped with a structured
        timeout — they never occupy a lane.  Runs automatically at the
        start of every ``step()``; returns the admitted request ids."""
        with self._admission_lock:
            return self._pump_locked()

    def _pump_locked(self) -> list[int]:
        admitted = []
        while self._pending:
            pend = self._pending[0]
            if (pend.deadline is not None
                    and pend.deadline <= self._clock()):
                self._pending.popleft()
                self._finish(pend.request_id, pend.prompt, "timeout",
                             pend.prompt.size, born=pend.born)
                continue
            if not self.free_lanes():
                break
            self._pending.popleft()
            try:
                ok = self._admit_pending(pend)
            except Exception as e:  # noqa: BLE001 — deferred validation
                # Engine-specific validation that enqueue() could not
                # run up front (e.g. the key-iff-sampling rule, or a
                # pooled prefix evicted while queued) fails at
                # admission: the request must still reach a terminal
                # structured result, not crash the decode loop.
                self._finish(pend.request_id, pend.prompt, "error",
                             pend.prompt.size, error=str(e),
                             born=pend.born)
                continue
            if ok:
                admitted.append(pend.request_id)
            elif self._decline_reason == "kv_blocks":
                # Allocator exhausted (paged engine): the request
                # stays at the queue HEAD — blocks free as running
                # lanes drain, and FIFO order must hold.
                self._pending.appendleft(pend)
                break
            else:
                # Free lane + declined admission == the deadline
                # expired between pump's check and submit's re-check.
                self._finish(pend.request_id, pend.prompt, "timeout",
                             pend.prompt.size, born=pend.born)
        # Unconditionally: expired-head drops shrink the queue without
        # admitting anything, and the gauge must not report phantom
        # backlog (no-op when telemetry is disabled).
        obs.gauge("serving.queue_depth", len(self._pending))
        return admitted

    def _reap(self) -> None:
        """Post-step bookkeeping: collect finished managed lanes and
        evict deadline-expired running lanes (structured timeout with
        the partial transcript).  Evicted/collected lanes free
        immediately — the next pump()/submit() reuses them."""
        now = None
        for lane, st in enumerate(self._lane_state):
            if st is None:
                continue
            if st.done:
                if st.managed:
                    self._finish(st.request_id, st.tokens, "ok",
                                 st.prompt_len, born=st.born)
                    self._vacate(lane)
                continue
            if st.deadline is not None:
                if now is None:
                    now = self._clock()
                if st.deadline <= now:
                    self._finish(st.request_id, st.tokens, "timeout",
                                 st.prompt_len, born=st.born)
                    self._vacate(lane)

    # ------------------------------------------------------- results

    def poll(self, request_id: int):
        """The request's :class:`RequestResult`, or None if still
        queued/decoding."""
        return self._completed.get(request_id)

    def take(self, request_id: int):
        """Pop and return the request's result; raises KeyError if it
        has not finished."""
        return self._completed.pop(request_id)

    def partial(self, request_id: int):
        """Live transcript snapshot — the streaming read (round 17).

        A terminal request returns its completed
        :class:`RequestResult` (exactly what :meth:`poll` returns); a
        request still decoding returns a ``RequestResult`` with
        status ``"decoding"`` and the transcript SO FAR (prompt +
        every token emitted to date — the same prompt-inclusive shape
        terminal transcripts carry, so a caller's cursor arithmetic
        never branches); a request still queued returns ``"queued"``
        with just the prompt.  ``None`` for unknown ids.  Taken under
        the admission lock so the snapshot never tears against a
        concurrent step's emit — the one rule the streaming relay
        (``/stream``, :meth:`Router.stream`) leans on.
        """
        with self._admission_lock:
            res = self._completed.get(request_id)
            if res is not None:
                return res
            for st in self._lane_state:
                if st is not None and st.request_id == request_id:
                    return RequestResult(
                        request_id=request_id,
                        tokens=np.asarray(st.tokens, np.int32),
                        status="decoding", prompt_len=st.prompt_len,
                        error=None)
            for pend in self._pending:
                if pend.request_id == request_id:
                    return RequestResult(
                        request_id=request_id,
                        tokens=np.asarray(pend.prompt, np.int32),
                        status="queued", prompt_len=pend.prompt.size,
                        error=None)
            return None

    def results(self) -> dict:
        """Pop every completed result: ``{request_id: RequestResult}``."""
        out = self._completed
        self._completed = {}
        return out

    @property
    def queued(self) -> int:
        return len(self._pending)

    @property
    def closed(self) -> bool:
        return self._closed

    # ------------------------------------------------------ lifecycle

    def begin_shutdown(self) -> None:
        """Stop admission (submit/enqueue raise :class:`EngineClosed`);
        in-flight lanes and the queue keep decoding via ``step()``.
        Taken under the admission lock: any enqueue that already
        passed its closed check finishes its insert first (and will be
        drained), and every enqueue after this returns raises
        EngineClosed — never QueueFull (EngineClosed wins)."""
        with self._admission_lock:
            self._closed = True

    def shutdown(self, max_steps: int | None = None) -> dict:
        """Drain-then-shutdown: stop admission, run the decode loop
        until every queued and running request reaches a terminal state
        (finish, eos, or deadline), and return the collected results.

        ``max_steps`` bounds the drain; requests still unfinished when
        it trips are cancelled (structured ``"cancelled"`` results,
        partial transcripts for lanes already decoding).  Lanes that
        were admitted with bare ``submit()`` and already finished are
        left for their caller's ``drain()`` — only live work blocks
        shutdown.
        """
        self.begin_shutdown()
        steps = 0
        while self.running() or self._pending:
            if max_steps is not None and steps >= max_steps:
                break
            if not self.running() and not self.free_lanes():
                # Queue blocked behind finished-but-undrained manual
                # lanes: stepping cannot make progress.
                break
            free_before = bool(self.free_lanes())
            backlog = len(self._pending)
            self.step()
            steps += 1
            if (free_before and not self.running() and self._pending
                    and len(self._pending) == backlog):
                # Free lanes went into the step, yet the queue head
                # still could not admit and nothing is decoding —
                # storage starvation (e.g. a paged engine whose blocks
                # are all pinned): stepping again cannot make progress
                # either, so fall through to cancellation instead of
                # spinning.  (``free_before`` matters: lanes freed by
                # THIS step's reap get their pump on the next
                # iteration, which must run.)
                break
        for pend in self._pending:
            self._finish(pend.request_id, pend.prompt, "cancelled",
                         pend.prompt.size, born=pend.born)
        self._pending.clear()
        obs.gauge("serving.queue_depth", 0)
        for lane, st in enumerate(self._lane_state):
            if st is not None and not st.done:
                self._finish(st.request_id, st.tokens, "cancelled",
                             st.prompt_len, born=st.born)
                self._vacate(lane)
        return self.results()


__all__ = ["EngineClosed", "QueueFull", "RequestResult", "_Pending",
           "_AdmissionMixin"]
