"""SpeculativeBatcher: draft-assisted continuous batching.

Every lane advances up to ``n_draft + 1`` positions per device
round-trip: ``n_draft`` cheap draft proposals, ONE target verify
chunk, per-lane acceptance.  The lane/admission machinery is shared
with :class:`~distkeras_tpu.serving.lanes.ContinuousBatcher` through
:class:`~distkeras_tpu.serving.engine._LaneEngine`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.resilience import chaos

from distkeras_tpu.models.generate import (_decode_chunk, _device_tree,
                                           init_cache, rolling_eligible)
from distkeras_tpu.models.speculative import speculative_accept
from distkeras_tpu.models.transformer import TransformerConfig
from distkeras_tpu.serving.engine import (_Lane, _LaneEngine,
                                          _make_lane_admit,
                                          _make_lane_reseed)


class SpeculativeBatcher(_LaneEngine):
    """Draft-assisted continuous batching: every lane advances up to
    ``n_draft + 1`` positions per device round-trip.

    The lane/admission machinery is :class:`ContinuousBatcher`'s; the
    step is one iteration of :func:`speculative_generate`'s body
    vectorized over lanes at divergent positions — ``n_draft`` draft
    proposals (the draft's first chunk is T=2, closing the
    full-acceptance cache gap exactly like the solo loop), ONE target
    verify chunk, per-lane acceptance, and a per-lane advance of
    ``accepted + 1`` tokens.  Rejected-tail cache writes land
    beyond each lane's frontier and are masked until overwritten
    (the _decode_chunk staleness argument), so lanes never interact.

    Contract: every request's emitted tokens are EXACTLY its solo
    ``speculative_generate`` run's (batch 1, same key).  Greedy
    (``temperature=0``) that is ``generate``'s greedy rollout;
    sampled (engine-level ``temperature > 0``, per-request keys) it
    is the Leviathan/Chen speculative-sampling rollout — each lane
    carries its own iteration counter so its accept/corrective draws
    replay the solo run's ``fold_in(key, iteration)`` stream exactly,
    whenever the lane was admitted.  Scope: no top-k/p filters (the
    solo fn has none either); unsupported combinations reject loudly.

    **Shared prefixes** (round-10 — the v1 "no shared prefix"
    exclusion is LIFTED): attach a
    ``PrefixPool(cfg, slots, draft_cfg=draft_cfg)`` whose segments are
    ``(target_cache, draft_cache)`` pairs (the same prefix prefilled
    through BOTH models) and ``submit``/``enqueue`` take
    ``prefix_id=`` — both lane caches are seeded from the pooled
    segments by a device gather, so the prefix tokens run zero prefill
    work on either model.  Greedy pooled requests keep exact parity
    with ``generate(prompt, cfg, n, prompt_cache=(target_segment, P))``
    (greedy speculative IS the greedy target rollout); sampled pooled
    requests draw on the engine's iteration-keyed stream (valid
    target-distribution samples — there is no solo
    ``speculative_generate(prompt_cache=...)`` to replay).  A 1-token
    prompt against a prefix needs the prefix's ``last_token`` recorded
    at ``PrefixPool.put`` (the draft's first chunk rewrites the
    position before the prompt).  Full-cache configs only.

    Budget (full-cache): a request needs ``prefix + prompt +
    max_new_tokens + n_draft <= max_len`` on BOTH models (the verify
    chunk writes ``n_draft + 1`` slots past the frontier; same slack
    as the solo fn).  Finished lanes keep decoding with their frontier
    clamped at the last budget-safe position — outputs discarded,
    admission reseeds.

    ROLLING lanes (round-7): when BOTH configs are windowed
    (rope + ``attention_window``, with ``window + n_draft + 1 <=
    max_len`` each — solo speculative's ring bound), lanes decode past
    ``max_len`` on the ring caches with no total-length cap (prompts
    still must fit the ring), matching solo windowed
    ``speculative_generate`` per request; and the draft-fault FALLBACK
    is ring-compatible — it inherits the lanes' unbounded positions
    and ring slabs mid-wrap, so greedy parity with solo rolling
    ``generate`` holds past ``max_len`` through a degradation.

    **Pod-sharded** (round 17, ``plan=``/``mesh=``): the TARGET model
    shards per the plan's rules exactly like the dense engine (params
    TP-placed, ``tcache``'s kv-heads dim over the derived axis,
    GSPMD's per-token collectives compiled in) while the DRAFT model
    replicates whole — a draft is small by design, so replication
    costs little and keeps the draft chunks collective-free.  Every
    serve-phase program warms at construction
    (:meth:`_warm_sharded`); emitted tokens stay bit-exact vs the
    solo engine.  Full-cache configs only; rejects ``prefix_pool=``
    (one slab placement cannot serve a sharded target and a
    replicated draft).
    """

    def __init__(self, params, draft_params, cfg: TransformerConfig,
                 draft_cfg: TransformerConfig, lanes: int = 8,
                 n_draft: int = 4, temperature: float = 0.0,
                 eos_token=None, prompt_buckets=(8, 32, 128, 512),
                 max_queue: int = 0, clock=None, prefix_pool=None,
                 plan=None, mesh=None):
        # Windowed configs run ROLLING speculative lanes (round-7): the
        # verify chunk writes through _decode_chunk's modular ring
        # scatter under the same bound as solo speculative_generate —
        # window + n_draft + 1 <= max_len keeps every rejected tail's
        # slots outside every live query's band — and lanes decode past
        # max_len with no total-length cap, exactly like rolling
        # ContinuousBatcher lanes.  Crucially the DEGRADED path stays
        # ring-compatible too: the target-only fallback advances the
        # same unbounded per-lane positions over the same ring slabs,
        # so a draft fault mid-wrap preserves greedy solo parity past
        # max_len.  Mixed full/windowed model
        # pairs stay rejected: their caches disagree on what a
        # position IS past the smaller ring.
        self._rolling = False
        if (cfg.attention_window is None) != (draft_cfg.attention_window
                                              is None):
            raise ValueError(
                "speculative serving needs the target and draft caches "
                "to agree: both full-cache or both windowed (got "
                f"target window={cfg.attention_window}, draft "
                f"window={draft_cfg.attention_window})")
        if cfg.attention_window is not None:
            if prefix_pool is not None:
                raise ValueError("prefix_pool requires full-cache "
                                 "configs (no attention_window)")
            for name, c in (("cfg", cfg), ("draft_cfg", draft_cfg)):
                if not rolling_eligible(c):
                    raise ValueError(
                        f"windowed speculative serving runs rolling "
                        f"lanes, which needs {name}.rope=True and "
                        f"attention_window <= max_len")
                if c.attention_window + n_draft + 1 > c.max_len:
                    raise ValueError(
                        f"rolling speculative lanes need "
                        f"{name}.attention_window "
                        f"({c.attention_window}) + n_draft + 1 "
                        f"({n_draft + 1}) <= max_len ({c.max_len}): "
                        "the verify chunk's rejected tail must alias "
                        "outside every live query's band")
            self._rolling = True
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab_size {draft_cfg.vocab_size} != target "
                f"{cfg.vocab_size} — the models must share a tokenizer")
        if n_draft < 1:
            raise ValueError(f"n_draft must be >= 1, got {n_draft}")
        # Eager impossibility check: _cap = min(max_len) - n_draft - 1
        # is the largest prompt+generation budget any request can use;
        # _cap <= 0 means NO request can ever be admitted, so fail at
        # construction naming the real culprits instead of letting
        # every submit() blame the prompt.
        if min(cfg.max_len, draft_cfg.max_len) <= n_draft + 1:
            raise ValueError(
                f"n_draft={n_draft} leaves no decode budget: the verify "
                f"chunk needs n_draft + 1 cache slots of slack, but "
                f"min(max_len)={min(cfg.max_len, draft_cfg.max_len)} "
                f"(target {cfg.max_len}, draft {draft_cfg.max_len}) <= "
                f"n_draft + 1 = {n_draft + 1}; lower n_draft or raise "
                "max_len")
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if eos_token is not None and not 0 <= eos_token < cfg.vocab_size:
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{cfg.vocab_size})")
        if prefix_pool is not None:
            if prefix_pool.draft_cfg is None:
                raise ValueError(
                    "SpeculativeBatcher needs a speculative pool — "
                    "PrefixPool(cfg, slots, draft_cfg=draft_cfg), whose "
                    "segments are (target, draft) cache pairs")
            want = jax.eval_shape(lambda: (init_cache(cfg, 1),
                                           init_cache(draft_cfg, 1)))
            got = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
                prefix_pool.slab)
            if (jax.tree.structure(want) != jax.tree.structure(got)
                    or jax.tree.leaves(want) != jax.tree.leaves(got)):
                raise ValueError(
                    f"prefix_pool was built for different configs "
                    f"(pool segments {got}, engine caches {want})")
        # Pod-sharded speculative serving (round 17): the TARGET model
        # shards per the plan's rules exactly like the dense engine —
        # params TP-placed, tcache's kv-heads dim over the derived
        # axis — while the DRAFT model replicates whole (a draft is
        # small by design; replicating it sidesteps any
        # head-divisibility question on its config and keeps the
        # draft chunks collective-free).  Full-cache configs only;
        # every serve-phase program warms at construction
        # (_warm_sharded), same zero-compile contract as the dense
        # engine.
        if (plan is None) != (mesh is None):
            raise ValueError(
                "pass plan= and mesh= together: the plan's rules only "
                "mean something against a concrete mesh (use "
                "parallel.sharding.serving_plan() for the standard TP "
                "layout)")
        if plan is not None:
            if cfg.attention_window is not None:
                raise ValueError(
                    "pod-sharded speculative serving needs full-cache "
                    "configs (no attention_window): the ring slab's "
                    "rolling scatter has no stable sharded layout to "
                    "pin")
            if prefix_pool is not None:
                raise ValueError(
                    "plan= does not compose with prefix_pool= on the "
                    "speculative engine: pooled segments are (target, "
                    "draft) cache pairs and the draft half replicates "
                    "while the target shards — one slab placement "
                    "cannot satisfy both; use the dense engine for "
                    "pooled sharded serving")
        self.plan, self.mesh = plan, mesh
        if plan is not None:
            from distkeras_tpu.parallel.rules import serving_kv_axis

            self._kv_axis = serving_kv_axis(plan, mesh, cfg)
        self._prefix_pool = prefix_pool
        if plan is not None:
            self.params = jax.device_put(
                params, plan.tree_shardings(mesh, params))
            self.draft_params = self._place_replicated(draft_params)
        else:
            self.params = _device_tree(params)
            self.draft_params = _device_tree(draft_params)
        self.cfg, self.draft_cfg = cfg, draft_cfg
        self.lanes, self.n_draft = lanes, n_draft
        self.temperature = temperature
        self.eos_token = eos_token
        # The verify chunk writes k+1 slots past the frontier on BOTH
        # caches; bucket admission caps prompts the same way.  Rolling
        # engines have no frontier cap (positions are unbounded on the
        # ring) — only the prompt must fit it: the admission warm
        # chunk is uniform-pos and must not wrap, so p - 1 <= ring - 1.
        if self._rolling:
            self._cap = None
            bucket_cap = min(cfg.max_len, draft_cfg.max_len) - 1
        else:
            self._cap = min(cfg.max_len, draft_cfg.max_len) - n_draft - 1
            bucket_cap = self._cap
        self._buckets = tuple(sorted(
            {min(int(w), bucket_cap) for w in prompt_buckets}
            | {bucket_cap}))
        self._lane_state: list[_Lane | None] = [None] * lanes
        self._next_id = 0
        self._init_admission(max_queue, clock)
        # Graceful degradation: when the draft half of the step faults
        # (chaos-injected, or a real dispatch failure caught with the
        # engine state intact), the engine permanently switches to a
        # plain target-only decode step — requests still complete,
        # just without the speculative speedup.  Greedy engines keep
        # exact solo-generate parity through the switch (greedy
        # speculative == greedy generate by construction); sampled
        # engines keep drawing valid samples but on a different PRNG
        # stream than the solo speculative rollout.
        self._degraded = False
        self.degraded_error = None
        self._fallback = None

        # Sharded engines commit the target cache under the plan's KV
        # sharding, the draft cache and row state replicated —
        # placement is part of the jit cache key for committed arrays,
        # so live state and warm-up dummies must agree (identity
        # placements unsharded).
        self.tcache = self._place_kv(init_cache(cfg, lanes))
        self.dcache = self._place_replicated(init_cache(draft_cfg,
                                                        lanes))
        self.pos = jnp.zeros((lanes,), jnp.int32)   # last FINAL position
        self.cur = jnp.zeros((lanes,), jnp.int32)   # token at pos
        self.prev = jnp.zeros((lanes,), jnp.int32)  # token at pos - 1
        # Sampled mode: per-lane request keys + per-lane ITERATION
        # counters — a lane's draws are keyed fold_in(key, iter) like
        # the solo loop's, so wherever the lane was admitted it
        # replays its solo b=1 run's PRNG stream exactly (RNG bits are
        # shape-row invariant: (V,) and (1, V) draws agree).
        self.keys = jnp.stack([jax.random.key(0)] * lanes)
        self.iters = jnp.zeros((lanes,), jnp.int32)
        if mesh is not None:
            (self.pos, self.cur, self.prev, self.keys, self.iters) = (
                self._place_replicated(x)
                for x in (self.pos, self.cur, self.prev, self.keys,
                          self.iters))

        k = n_draft
        idx = jnp.arange(k + 1)
        rolling = self._rolling
        cap = None if rolling else jnp.int32(self._cap)
        sampled = temperature > 0
        constrain = self._kv_constraint

        def step_fn(tcache, dcache, prev, cur, pos, keys, iters):
            if constrain is not None:
                # Pin the target cache's sharded layout inside the
                # compiled program (the draft cache is replicated —
                # replicated in, replicated out, nothing to pin).
                tcache = constrain(tcache)
            # ---- draft: first chunk T=2 rewrites [pos-1, pos] (the
            # full-acceptance gap closure, exactly the solo body's).
            pos0 = jnp.maximum(pos - 1, 0)
            first = jnp.where(
                (pos == 0)[:, None],
                jnp.stack([cur, jnp.zeros_like(cur)], axis=1),
                jnp.stack([prev, cur], axis=1))
            lg2, dcache = _decode_chunk(self.draft_params, dcache,
                                        first, pos0, draft_cfg)
            lg = jnp.take_along_axis(
                lg2, (pos - pos0)[:, None, None], axis=1)[:, 0]
            kit = jax.vmap(jax.random.fold_in)(keys, iters)
            d_toks, q_logps = [], []
            for j in range(k):
                if sampled:
                    logp = jax.nn.log_softmax(lg / temperature, axis=-1)
                    nxt = jax.vmap(
                        lambda kk, row, _j=j: jax.random.categorical(
                            jax.random.fold_in(kk, _j), row))(kit, logp)
                    q_logps.append(logp)
                else:
                    nxt = lg.argmax(axis=-1)
                nxt = nxt.astype(jnp.int32)
                d_toks.append(nxt)
                if j < k - 1:
                    lgj, dcache = _decode_chunk(
                        self.draft_params, dcache, nxt[:, None],
                        pos + 1 + j, draft_cfg)
                    lg = lgj[:, 0]
            d = jnp.stack(d_toks, axis=1)               # [lanes, k]

            # ---- one target verify chunk over [cur, d_1..d_k]
            chunk = jnp.concatenate([cur[:, None], d], axis=1)
            tlog, tcache = _decode_chunk(self.params, tcache, chunk,
                                         pos, cfg)
            if sampled:
                # The Leviathan/Chen rule via the ONE shared
                # definition (speculative.speculative_accept); only
                # the draw keys differ from the solo loop — per-lane
                # iteration-keyed so each lane replays its solo run.
                p_logp = jax.nn.log_softmax(tlog / temperature, -1)
                q_logp = jnp.stack(q_logps, axis=1)
                u = jax.vmap(lambda kk: jax.random.uniform(
                    jax.random.fold_in(kk, k + 1), (k,)))(kit)
                n, corr_logits = speculative_accept(p_logp, q_logp,
                                                    d, u)
                corrective = jax.vmap(
                    lambda kk, row: jax.random.categorical(
                        jax.random.fold_in(kk, k + 2),
                        row))(kit, corr_logits).astype(jnp.int32)
            else:
                t_pred = tlog.argmax(axis=-1).astype(jnp.int32)
                match = d == t_pred[:, :k]
                n = jnp.cumprod(match, axis=1).sum(axis=1)   # [lanes]
                corrective = jnp.take_along_axis(t_pred, n[:, None],
                                                 axis=1)[:, 0]
            d_ext = jnp.concatenate([d, d[:, -1:]], axis=1)
            win = jnp.where(idx[None, :] < n[:, None], d_ext,
                            corrective[:, None]).astype(jnp.int32)

            # ---- advance: accepted + corrective.  Full-cache: the
            # frontier clamps at the budget-safe cap (live lanes never
            # reach it — submit guarantees total - 1 <= cap; clamped
            # lanes spin and the host discards their output).
            # Rolling: positions are unbounded — the ring absorbs any
            # advance (idle/done lanes keep rolling too; their writes
            # land in slots admission reseeds, like the rolling
            # ContinuousBatcher).
            if rolling:
                adv = (n + 1).astype(jnp.int32)
            else:
                adv = jnp.where(pos >= cap, 0,
                                jnp.minimum(n + 1, cap - pos)
                                ).astype(jnp.int32)
            new_pos = pos + adv
            last = jnp.take_along_axis(
                win, jnp.maximum(adv - 1, 0)[:, None], axis=1)[:, 0]
            new_cur = jnp.where(adv > 0, last, cur)
            second_last = jnp.take_along_axis(
                win, jnp.maximum(adv - 2, 0)[:, None], axis=1)[:, 0]
            new_prev = jnp.where(adv >= 2, second_last,
                                 jnp.where(adv == 1, cur, prev))
            return (tcache, dcache, new_prev, new_cur, new_pos,
                    iters + 1, win, adv)

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

        # Admission: one jitted program per MODEL (jit specializes per
        # bucket-padded rows shape); pooled engines gather the
        # per-model prefix segment inside the same program.
        pooled = prefix_pool is not None
        self._admit_t = _make_lane_admit(self.params, cfg,
                                         pooled=pooled,
                                         constrain=self._kv_constraint)
        self._admit_d = _make_lane_admit(self.draft_params, draft_cfg,
                                         pooled=pooled)
        if pooled:
            self._reseed_t = _make_lane_reseed(pooled=True)
            self._reseed_d = _make_lane_reseed(pooled=True)
        if plan is not None:
            self._warm_sharded()

    # ---------------------------------------------- sharded warm-up

    def _warm_sharded(self) -> None:
        """Compile every serve-phase program at construction (the
        sharded zero-compile contract): the speculative step and both
        per-bucket admission programs run once against dummy state
        with EXACTLY the live arrays' avals and placements, plus the
        tiny host-scatter programs ``submit`` touches.  After this the
        serve phase never compiles (the ``spec_sharded`` compile
        session asserts it); only the degraded fallback still
        compiles lazily — a draft fault is not a steady state."""
        with obs.span("serving.compile_warm", lanes=self.lanes):
            fresh = lambda: (
                self._place_kv(init_cache(self.cfg, self.lanes)),
                self._place_replicated(init_cache(self.draft_cfg,
                                                  self.lanes)))
            ints = lambda: self._place_replicated(
                jnp.zeros((self.lanes,), jnp.int32))
            keys = self._place_replicated(
                jnp.stack([jax.random.key(0)] * self.lanes))
            tc, dc = fresh()           # the step donates both caches
            self._step(tc, dc, ints(), ints(), ints(), keys, ints())
            for width in self._buckets:
                rows = jnp.zeros((1, width), jnp.int32)
                tc, dc = fresh()       # admission donates its cache
                self._admit_t(tc, rows, jnp.int32(0), jnp.int32(0))
                self._admit_d(dc, rows, jnp.int32(0), jnp.int32(0))
            # submit()'s host lane-slot writes specialize per shape
            # and placement too — tiny scatters, but a compile is a
            # compile.
            ints().at[0].set(0)
            keys.at[0].set(jax.random.key(0))

    # -------------------------------------------------------------- API

    def traced_for_analysis(self):
        """Trace targets for the IR lint: the jitted speculative
        draft+verify step over the engine's live lane state, plus the
        target-model admission chunk at the smallest bucket."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        mode = "sampled" if self.temperature > 0 else "greedy"
        if self._prefix_pool is not None:
            mode += "_pooled"
        rows = jnp.zeros((1, self._buckets[0]), jnp.int32)
        admit_args = (self.tcache, rows, jnp.int32(0), jnp.int32(0))
        if self._prefix_pool is not None:
            admit_args += (self._prefix_pool.slab[0], jnp.int32(0))
        return [
            TraceSpec(
                name=f"speculativebatcher_{mode}/step",
                fn=self._step,
                args=(self.tcache, self.dcache, self.prev, self.cur,
                      self.pos, self.keys, self.iters),
                donate_argnums=(0, 1)),
            TraceSpec(
                name=f"speculativebatcher_{mode}/admit_b"
                     f"{self._buckets[0]}",
                fn=self._admit_t, args=admit_args,
                donate_argnums=(0,)),
        ]

    def _validate_budget(self, p: int, max_new_tokens: int,
                         off: int = 0) -> None:
        if self._rolling:
            # No total-length cap: lanes roll past max_len on the
            # ring.  Only the PROMPT is bounded — its warm chunk is
            # uniform-pos and must not wrap.
            if p - 1 > self._buckets[-1]:
                raise ValueError(
                    f"prompt length {p} exceeds the largest admission "
                    f"bucket ({self._buckets[-1]} + 1); rolling "
                    "speculative prompts must fit the ring")
            return
        if off + p + max_new_tokens - 1 > self._cap:
            raise ValueError(
                f"prefix ({off}) + prompt ({p}) + max_new_tokens "
                f"({max_new_tokens}) + n_draft ({self.n_draft}) exceeds "
                f"max_len={min(self.cfg.max_len, self.draft_cfg.max_len)}"
                " (the verify chunk needs n_draft + 1 slots of slack)")
        warm = p - 1
        if warm and next((w for w in self._buckets
                          if w >= warm
                          and off + w <= min(self.cfg.max_len,
                                             self.draft_cfg.max_len)),
                         None) is None:
            raise ValueError(
                f"no admission bucket fits {warm} prompt tokens past a "
                f"{off}-token prefix (buckets {self._buckets}); raise "
                "prompt_buckets or add a finer width")

    def submit(self, prompt, max_new_tokens: int, key=None,
               eos_token=None, ttl=None, deadline=None, prefix_id=None):
        """Admit one request; returns its lane id, or None if full.
        ``key``: per-request PRNG key (required iff the engine
        samples, i.e. ``temperature > 0``).  ``ttl``/``deadline``:
        request deadline, same contract as
        :meth:`ContinuousBatcher.submit` — including holding the
        engine lock for the whole admission, so a submit racing
        ``begin_shutdown`` is either drained or raises EngineClosed.
        ``prefix_id``: decode past a pooled (target, draft) prefix
        pair — see the class docstring."""
        with self._admission_lock:
            return self._submit_locked(prompt, max_new_tokens, key,
                                       eos_token, ttl, deadline,
                                       prefix_id)

    def _submit_locked(self, prompt, max_new_tokens, key, eos_token,
                       ttl, deadline, prefix_id=None):
        self._check_open()
        prompt = self._validate_request_args(prompt, max_new_tokens)
        p = prompt.size
        if (key is None) == (self.temperature > 0):
            raise ValueError(
                "pass a per-request key iff the engine samples "
                f"(temperature={self.temperature})")
        off, slot, pre_last = 0, None, None
        if prefix_id is not None:
            # Pin FIRST (engine._pin_prefix): a concurrent pool.put
            # can never evict a pinned entry, so the slot stays ours
            # through both slab gathers below.  Every non-admission
            # exit releases the pin.
            off, slot, pre_last = self._pin_prefix(prefix_id)
        try:
            if prefix_id is not None and p == 1 and pre_last is None:
                raise ValueError(
                    "a 1-token prompt against a pooled prefix needs "
                    "the prefix's last token recorded at "
                    "PrefixPool.put(last_token=...): the draft chunk "
                    "rewrites the position before the prompt")
            self._validate_budget(p, max_new_tokens, off=off)
            if eos_token is not None and not (
                    0 <= eos_token < self.cfg.vocab_size):
                raise ValueError(
                    f"eos_token {eos_token} outside vocab [0, "
                    f"{self.cfg.vocab_size})")
            dl = self._deadline_of(ttl, deadline)
            if self._expired_on_arrival(dl, prompt, p):
                if prefix_id is not None:
                    self._prefix_pool.release(prefix_id)
                return None
            free = self.free_lanes()
            if not free:
                self._decline_full()
                if prefix_id is not None:
                    self._prefix_pool.release(prefix_id)
                return None
            lane = free[0]
            chaos.probe("serving.admit")
            rid = self._claim_rid()
            if not self._admitting_internal:
                obs.event("serving.submit", request_id=rid,
                          prompt_len=p, max_new=int(max_new_tokens))
            warm = p - 1
            if warm:
                # The budget check above bounds warm and the bucket
                # fit, so a bucket always exists.
                width = next(w for w in self._buckets
                             if w >= warm and off + w <= min(
                                 self.cfg.max_len,
                                 self.draft_cfg.max_len))
                rows = np.zeros((1, width), np.int32)
                rows[0, :warm] = prompt[:-1]
                rows_j = jnp.asarray(rows)
                with obs.span("serving.admit", bucket=width, lane=lane,
                              request_id=rid):
                    if slot is not None:
                        t_slab, d_slab = self._prefix_pool.slab
                        self.tcache = self._admit_t(
                            self.tcache, rows_j, jnp.int32(lane),
                            jnp.int32(off), t_slab, jnp.int32(slot))
                        self.dcache = self._admit_d(
                            self.dcache, rows_j, jnp.int32(lane),
                            jnp.int32(off), d_slab, jnp.int32(slot))
                    elif self._prefix_pool is not None:
                        t_slab, d_slab = self._prefix_pool.slab
                        self.tcache = self._admit_t(
                            self.tcache, rows_j, jnp.int32(lane),
                            jnp.int32(0), t_slab, jnp.int32(-1))
                        self.dcache = self._admit_d(
                            self.dcache, rows_j, jnp.int32(lane),
                            jnp.int32(0), d_slab, jnp.int32(-1))
                    else:
                        self.tcache = self._admit_t(
                            self.tcache, rows_j, jnp.int32(lane),
                            jnp.int32(0))
                        self.dcache = self._admit_d(
                            self.dcache, rows_j, jnp.int32(lane),
                            jnp.int32(0))
            elif slot is not None:
                # 1-token prompt on a pooled prefix: no admission
                # chunk, but both lane caches still need the prefix
                # K/V.
                t_slab, d_slab = self._prefix_pool.slab
                self.tcache = self._reseed_t(
                    self.tcache, jnp.int32(lane), t_slab,
                    jnp.int32(slot))
                self.dcache = self._reseed_d(
                    self.dcache, jnp.int32(lane), d_slab,
                    jnp.int32(slot))
            # else: stale slots stay masked until overwritten.
            self.pos = self.pos.at[lane].set(off + p - 1)
            self.cur = self.cur.at[lane].set(int(prompt[-1]))
            # prev seeds the draft's T=2 gap-closure chunk: the token
            # at pos - 1 — the second-to-last prompt token, or
            # (1-token prompt on a prefix) the prefix's recorded last
            # token.
            self.prev = self.prev.at[lane].set(
                int(prompt[-2]) if p > 1
                else int(pre_last) if pre_last is not None else 0)
            if key is not None:
                self.keys = self.keys.at[lane].set(key)
            self.iters = self.iters.at[lane].set(0)
            # The pin taken above becomes the lane's reference here.
            self._lane_state[lane] = _Lane(
                request_id=rid, prompt_len=p,
                max_new=max_new_tokens, key=key, tokens=list(prompt),
                eos=self.eos_token if eos_token is None else eos_token,
                deadline=dl, born=self._clock(), off=off,
                prefix_id=prefix_id)
            if not self._admitting_internal:
                self.last_request_id = rid
        except Exception:
            if prefix_id is not None:
                self._prefix_pool.release(prefix_id)
            raise
        return lane

    # ------------------------------------------------- degraded mode

    @property
    def degraded(self) -> bool:
        """True once the engine fell back to the plain decode path."""
        return self._degraded

    def degrade(self, error=None) -> None:
        """Permanently switch to the target-only fallback decode step
        (see the constructor's degradation note).  Called automatically
        when the draft half of a step faults; callable directly by an
        operator who knows the draft model is bad."""
        if not self._degraded:
            obs.count("serving.degraded")
            # Event name differs from the counter: one name must map
            # to one instrument kind (contract lint, metric-collision).
            obs.event("serving.degrade",
                      error=None if error is None else repr(error))
        self._degraded = True
        if error is not None and self.degraded_error is None:
            self.degraded_error = error

    def _note_draft_fault(self, e: BaseException) -> None:
        intact = not any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(
                (self.tcache, self.cur, self.pos, self.keys)))
        if not intact:
            raise RuntimeError(
                "draft fault surfaced after the speculative step "
                "consumed its donated state; the fallback path has "
                "nothing valid to decode from") from e
        self.degrade(e)

    def _make_fallback(self):
        """Plain target-only decode step over the SAME engine state
        (tcache/cur/pos): one token per lane per call, frontier clamped
        at the budget-safe cap exactly like the speculative step —
        except on ROLLING engines, where the fallback preserves the
        ring-slot arithmetic instead: positions stay unbounded and each
        row keeps writing slot ``pos % max_len``, so a draft fault
        mid-wrap hands the plain path a cache whose implied positions
        it continues exactly (greedy parity past max_len; pinned by
        tests/test_speculative.py's chaos regression)."""
        cfg = self.cfg
        temperature = self.temperature
        rolling = self._rolling
        cap = None if rolling else jnp.int32(self._cap)
        constrain = self._kv_constraint

        def pick(k, row, q):
            return jax.random.categorical(jax.random.fold_in(k, q), row)

        def one(tcache, cur, pos, keys):
            if constrain is not None:
                tcache = constrain(tcache)
            logits, tcache = _decode_chunk(self.params, tcache,
                                           cur[:, None], pos, cfg)
            logits = logits[:, 0]
            if temperature > 0:
                nxt = jax.vmap(pick)(keys, logits / temperature, pos)
            else:
                nxt = logits.argmax(axis=-1)
            nxt = nxt.astype(jnp.int32)
            if rolling:
                adv = jnp.ones_like(pos)
                new_pos = pos + 1
            else:
                adv = (pos < cap).astype(jnp.int32)
                new_pos = jnp.minimum(pos + 1, cap)
            new_cur = jnp.where(adv > 0, nxt, cur)
            return tcache, new_cur, new_pos, nxt, adv

        return jax.jit(one, donate_argnums=0)

    def step(self):
        """One decode round for every lane; returns
        ``{lane: [tokens...]}`` — up to ``n_draft + 1`` tokens per
        lane per call (exactly 1 once the engine is degraded).  Runs
        under the engine lock, like :meth:`ContinuousBatcher.step`, so
        a concurrent locked ``submit``/``enqueue`` never rebinds the
        lane state mid-round-trip."""
        with self._admission_lock:
            return self._step_locked()

    def _step_locked(self):
        self.pump()
        if all(s is None or s.done for s in self._lane_state):
            return {}
        chaos.probe("serving.step")
        live = () if obs.active() is None else self.running()
        obs.gauge("serving.lanes_busy", len(live))
        if not self._degraded:
            try:
                chaos.probe("serving.draft")
                with obs.span("serving.step", speculative=True):
                    (tcache, dcache, prev, cur, pos, iters, win,
                     adv) = self._step(
                        self.tcache, self.dcache, self.prev, self.cur,
                        self.pos, self.keys, self.iters)
                    # Force async dispatch errors to surface INSIDE the
                    # try, before the engine state is rebound: a fault
                    # arriving here finds self.* still naming the donated
                    # (now consumed) inputs, and _note_draft_fault reports
                    # the unrecoverable case with a clear error instead of
                    # leaving poisoned state behind.
                    win, adv = np.asarray(win), np.asarray(adv)
            except Exception as e:  # noqa: BLE001 — degrade, not die
                self._note_draft_fault(e)
            else:
                (self.tcache, self.dcache, self.prev, self.cur,
                 self.pos, self.iters) = (tcache, dcache, prev, cur,
                                          pos, iters)
                if obs.active() is not None:
                    # Speculative accept rate, host-visible for free:
                    # each live lane advanced accepted + 1 positions.
                    accepted = int(sum(max(int(adv[l]) - 1, 0)
                                       for l in live))
                    obs.count("serving.spec.proposed",
                              self.n_draft * len(live))
                    obs.count("serving.spec.accepted", accepted)
                out = self._emit(
                    lambda lane: win[lane, :adv[lane]].tolist())
                self._reap()
                return out
        # Degraded: plain target decode — requests still complete.
        if self._fallback is None:
            self._fallback = self._make_fallback()
        with obs.span("serving.step", speculative=False):
            self.tcache, self.cur, self.pos, nxt, adv = self._fallback(
                self.tcache, self.cur, self.pos, self.keys)
            nxt, adv = np.asarray(nxt), np.asarray(adv)
        out = self._emit(
            lambda lane: [int(nxt[lane])] if adv[lane] else [])
        self._reap()
        return out


__all__ = ["SpeculativeBatcher"]
