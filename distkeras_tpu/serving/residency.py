"""Content-hash stem residency: the digest language the paged engine
and the fleet router share.

The paged engine (:mod:`distkeras_tpu.serving.paged`) identifies a
resident KV block by the chain hash of the whole token prefix up to
and including that block; the cache-aware router
(:mod:`distkeras_tpu.serving.router`) routes a request to the replica
whose resident digest set covers the longest prefix of the request's
prompt.  Both sides MUST compute the same bytes for the same tokens —
one definition lives here, jax-free (the router runs on hosts that
never import jax; source lint ``jax-free`` rule), and everything else
imports it.

A digest is a pure function of ``(block size, token content,
position)``: equal digests imply equal full-block prefixes, so a
digest set is a complete description of which prompt stems a replica
can serve without re-prefilling — the "residency digest" the
``/residency`` telemetry endpoint publishes and the router's affinity
table consumes.

Deliberately PLACEMENT-independent (round 14): digests hash token
content on the host, never device layout, so a pod-sharded engine
(``plan=``/``mesh=``) publishes exactly the digests its solo twin
would — the router routes to a whole mesh through one replica handle
without knowing the mesh exists (tests/test_serving_sharded.py pins
the sharded-vs-solo digest equality).
"""

from __future__ import annotations

import hashlib

import numpy as np


def chain_hash(prev: bytes, tokens) -> bytes:
    """Chain hash of one full block of prompt tokens: a pure function
    of the whole token prefix up to and including this block, so equal
    digests imply equal (position, content) — the stem-sharing key."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


def stem_hashes(tokens, block: int) -> list[bytes]:
    """Chain hashes of every FULL ``block``-token block of ``tokens``
    (a partial tail block has no stable identity and gets no digest).

    NOTE for routing: engines prefill the WARM prompt — every token
    but the last, which the decode loop processes — so the residency
    a request can hit is ``stem_hashes(prompt[:-1], block)``, not the
    full prompt's.
    """
    if block < 1:
        raise ValueError(f"block must be >= 1, got {block}")
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    out: list[bytes] = []
    digest = b""
    for k in range(tokens.size // block):
        digest = chain_hash(digest, tokens[k * block:(k + 1) * block])
        out.append(digest)
    return out


def stem_hexes(tokens, block: int) -> list[str]:
    """:func:`stem_hashes` rendered as hex strings — the JSON-safe
    spelling ``/residency`` serves and the router's affinity table
    stores."""
    return [h.hex() for h in stem_hashes(tokens, block)]


__all__ = ["chain_hash", "stem_hashes", "stem_hexes"]
