"""Continuous batching: lane-based serving engines over the decode step.

Round 10 split the five-PR serving monolith into this package; the
public API is unchanged — ``from distkeras_tpu.serving import
ContinuousBatcher`` (and every other name below) works exactly as it
did against the old ``serving.py``.  Layout:

- :mod:`~distkeras_tpu.serving.engine` — the shared host lane
  machinery (`_LaneEngine`): lane table, emission loop, chunked-
  prefill scheduler, and the single-lane admission program factories.
- :mod:`~distkeras_tpu.serving.lanes` — :class:`ContinuousBatcher`,
  the plain/per-request-sampling/rolling/kv-int8 engine.
- :mod:`~distkeras_tpu.serving.admission` — the admission-control
  mixin (deadlines, bounded queue + :class:`QueueFull` backpressure,
  structured :class:`RequestResult`, drain-then-shutdown) and the
  re-exported result/exception types.
- :mod:`~distkeras_tpu.serving.speculative` —
  :class:`SpeculativeBatcher`, draft-assisted lanes.
- :mod:`~distkeras_tpu.serving.elastic` — elastic lane tiers
  (pre-compiled load-driven resizing).
- :mod:`~distkeras_tpu.serving.prefix` — :class:`PrefixPool`, the
  refcounted multi-prefix KV pool (round 10), and
  :class:`PinnedStems`, the paged engine's pinned-prefix bookkeeping
  (round 12).
- :mod:`~distkeras_tpu.serving.paged` — :class:`PagedBatcher` +
  :class:`BlockAllocator`: block-granular paged KV with per-lane page
  tables, content-hash stem sharing, and copy-on-write lane forks
  (round 12).
- :mod:`~distkeras_tpu.serving.router` — :class:`Router`: the
  jax-free fleet layer over N engine replicas (round 13) —
  cache-aware routing off each replica's residency digest,
  health-gated membership, drain-and-reroute, ``QueueFull``
  spillover, and cross-process trace propagation; with
  :class:`InProcessReplica` / :class:`HttpReplica` handles and the
  :class:`EngineEndpoint` HTTP admission server.
- :mod:`~distkeras_tpu.serving.residency` — the jax-free chain-hash
  digest language the paged engine and the router share.
- :mod:`~distkeras_tpu.serving.autoscale` — :class:`Autoscaler` +
  :class:`WarmPool`: the jax-free SLO-driven autoscaling control
  plane (round 19) — warm-pool zero-compile scale-up, lossless
  drain-and-reroute scale-down, hysteresis/cooldown, and the
  pinned-state retire guard.
- :mod:`~distkeras_tpu.serving.traffic` — :class:`TraceReplay`: the
  seeded deterministic trace-replay load driver (diurnal / spike /
  locality-shuffle / tenant-mix shapes; pure function of
  ``(seed, tick)``) the autoscale benches and chaos legs replay.
- :mod:`~distkeras_tpu.serving.disagg` — :class:`BlockShipment` and
  the jax-free block-transfer wire codec for disaggregated
  prefill/decode fleets (round 17): a prefill replica exports a
  prompt's KV blocks, the router ships them, a decode replica adopts
  them by page-table splice.
- :mod:`~distkeras_tpu.serving.publish` — :class:`SnapshotPublisher`
  / :class:`SnapshotReader` (round 20): the trainer side of the live
  train→serve weight push — versioned param snapshots in the same
  dtype-grouped fusion buckets the gradient exchange wires (optional
  int8 coding), published atomically (bucket files → checksummed
  manifest → version pointer) so a reader NEVER adopts a torn
  publish.
- :mod:`~distkeras_tpu.serving.canary` — :class:`CanaryController`
  (round 20): SLO-gated canary rollout of a published version over a
  ``hot_swap=True`` fleet — canary-subset swap, pinned-prompt
  logit-drift probe, promote-or-rollback under a bumped router
  epoch; rollback is first-class (the ``train_kill_push`` /
  ``canary_bad_push`` chaos legs).

The reference has no serving story at all (its ModelPredictor runs the
training forward over a static batch — reference:
distkeras/predictors.py); this package is TPU-first surplus on the
serving axis.  Start at docs/serving_guide.md.

Contract (both engines): every request's emitted tokens are EXACTLY
what its solo ``generate``/``speculative_generate`` run would emit —
per-lane PRNG streams are position/iteration-keyed, lane-local
positions start at the request's prefix offset, and stale cache slots
from a lane's previous occupant are masked until overwritten.  Pinned
by tests/test_serving.py and tests/test_speculative.py.
"""

from distkeras_tpu.serving.admission import (EngineClosed, QueueFull,
                                             RequestResult)
from distkeras_tpu.serving.autoscale import (Autoscaler,
                                             AutoscalePolicy, WarmPool)
from distkeras_tpu.serving.canary import CanaryController
from distkeras_tpu.serving.disagg import (BlockShipment,
                                          decode_shipment,
                                          encode_shipment)
from distkeras_tpu.serving.lanes import (KV_INT8_LANE_ADVISORY,
                                         ContinuousBatcher)
from distkeras_tpu.serving.paged import BlockAllocator, PagedBatcher
from distkeras_tpu.serving.prefix import PinnedStems, PrefixPool
from distkeras_tpu.serving.publish import (SnapshotCorrupt,
                                           SnapshotError,
                                           SnapshotPublisher,
                                           SnapshotReader,
                                           StaleSnapshot)
from distkeras_tpu.serving.router import (EngineEndpoint, HttpReplica,
                                          InProcessReplica,
                                          ReplicaUnreachable, Router,
                                          discover_replicas)
from distkeras_tpu.serving.speculative import SpeculativeBatcher
from distkeras_tpu.serving.traffic import (TRACE_SHAPES, TraceReplay,
                                           TraceRequest)

__all__ = [
    "ContinuousBatcher",
    "SpeculativeBatcher",
    "PagedBatcher",
    "BlockAllocator",
    "PrefixPool",
    "PinnedStems",
    "Router",
    "InProcessReplica",
    "HttpReplica",
    "EngineEndpoint",
    "ReplicaUnreachable",
    "discover_replicas",
    "BlockShipment",
    "encode_shipment",
    "decode_shipment",
    "Autoscaler",
    "AutoscalePolicy",
    "WarmPool",
    "SnapshotPublisher",
    "SnapshotReader",
    "SnapshotError",
    "SnapshotCorrupt",
    "StaleSnapshot",
    "CanaryController",
    "TraceReplay",
    "TraceRequest",
    "TRACE_SHAPES",
    "RequestResult",
    "QueueFull",
    "EngineClosed",
    "KV_INT8_LANE_ADVISORY",
]
