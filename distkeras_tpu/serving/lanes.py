"""ContinuousBatcher: lane-based continuous batching over one jitted
decode step.

Static-shape serving loop for interactive workloads: requests arrive
at different times, but the chip wants one fixed-shape program.  The
engine holds ``lanes`` decode rows in ONE KV cache and ONE jitted
per-row-position decode step; a new request is admitted into any free
lane mid-flight with a bucket-padded chunked prefill of just that
lane, while the other lanes keep decoding.  No compiled shape ever
depends on arrival times.

Contract: every request's emitted tokens are EXACTLY what
``generate(params, prompt, cfg, max_new_tokens, ...)`` would emit for
it alone — the per-lane PRNG stream is position-keyed like generate's
(``fold_in(request_key, pos)``), lane-local positions start at 0 per
request, and stale cache slots from the lane's previous occupant are
masked until overwritten (the ``_decode_chunk`` staleness argument).
Pinned by tests/test_serving.py against solo ``generate`` runs,
including staggered admission and lane reuse.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.resilience import chaos

from distkeras_tpu.models.generate import (
    _decode_chunk,
    _device_tree,
    _resolve_prompt_cache,
    init_cache,
    min_p_mask,
    rolling_eligible,
    top_k_mask,
    top_p_mask,
)
from distkeras_tpu.models.transformer import TransformerConfig
from distkeras_tpu.serving.elastic import _ElasticLanesMixin
from distkeras_tpu.serving.engine import (_Lane, _LaneEngine,
                                          _make_lane_admit,
                                          _make_lane_reseed)

# The measured cache-bound crossover for the int8 KV cache: +33% at
# b64, -15% at b8 (docs/serving_guide.md's byte-lever table).  Engines
# built with kv_int8 below this lane count get a construction-time
# advisory — the cache-byte saving cannot pay for the dequant cost at
# batch sizes where weights, not cache, dominate the step's traffic.
KV_INT8_LANE_ADVISORY = 16


class ContinuousBatcher(_ElasticLanesMixin, _LaneEngine):
    """Lane-based continuous batching over one jitted decode step.

    Args mirror ``generate``'s sampling surface: ``temperature``,
    ``top_k`` / ``top_p`` / ``min_p``, ``eos_token``, ``exact_top_k``
    — fixed per engine (they are compiled into the step).  Per-request
    PRNG keys arrive with ``submit``.

    ``per_request_sampling=True`` compiles the vectorized step instead
    (per-lane temperature/top_p/min_p carried as [lanes] device
    arrays): ``submit`` then takes per-request ``temperature`` /
    ``top_p`` / ``min_p`` / ``eos_token`` overrides — greedy and
    sampled requests mix in one batch, each still matching its solo
    ``generate`` run exactly.  The constructor values become the
    per-request DEFAULTS.  Off by default because the general program
    pays the nucleus sort and the sampling draw every step even for a
    greedy-only workload; ``top_k`` stays engine-level either way (a
    static shape baked into the program).

    ``lanes``: decode rows held by the engine; ``prompt_buckets``:
    admission pad widths (a prompt of length P uses the smallest
    bucket >= P - 1; one admission program compiles per bucket).

    Full-cache configs, or rope + ``attention_window`` configs — the
    latter run ROLLING lanes: every lane decodes past ``max_len`` on
    the ring-buffer cache with no total-length cap (prompts still must
    fit the ring), each request matching its solo rolling
    ``generate()`` run exactly.  No quantized-tree restriction — int8
    weights decode on the same chunk path — and every engine shape
    takes ``kv_int8=True`` (int8 KV cache; parity vs
    ``generate(kv_int8=True, use_prefill=False)``), rolling ring
    lanes included (round-5: the scale slabs ride the same ring-slot
    updates as the K/V).

    **Chunked prefill** (round-10, ``prefill_chunk=``): admission of a
    prompt longer than ``prefill_chunk`` tokens no longer runs as one
    monolithic chunk that stalls every lane — it is split into
    fixed-size, bucket-padded chunks, the first executed at ``submit``
    and the rest interleaved one per ``step()`` between decode
    dispatches, so concurrently decoding lanes' inter-token gap is
    bounded by ONE chunk's compute.  The parked lane joins decode the
    step its last chunk lands; emitted tokens are identical to
    monolithic admission (the chunks write exactly the same K/V).
    Full-cache configs only, and every chunk program compiles at
    construction (the ``serving_chunked`` compile session pins a
    zero-recompile serve phase).  The ``prefill_chunk`` width is added
    to ``prompt_buckets``.

    **Prefix pool** (round-10, ``prefix_pool=``): attach a
    :class:`~distkeras_tpu.serving.PrefixPool` and ``submit`` /
    ``enqueue`` take ``prefix_id=`` — the lane is seeded from the
    pooled prefilled segment by a device gather, so the prefix tokens
    cost ZERO prefill work per request, across N distinct prefixes on
    one engine (the generalization of the single ``prompt_cache=``
    prefix, ``kv_int8`` layouts included — the pool's quantization
    must match the engine's).  Requests pin their entry (refcount)
    until the lane is vacated; queued requests do not pin, so a prefix
    evicted while its request queues surfaces as a structured
    ``"error"`` result.  Parity: a pooled request matches
    ``generate(tail, prompt_cache=(segment, P))`` exactly, greedy and
    sampled.  Mutually exclusive with ``prompt_cache`` and with
    rolling (windowed) engines.

    **Elastic lane tiers** (round-7, resilience subsystem):
    ``lane_tiers=(2, 4, 8)`` starts the engine at 2 lanes and moves it
    between the declared tiers under load — ``scale_up_after``
    consecutive queue overflows step the tier up (the overflowing
    enqueue is absorbed instead of raising :class:`QueueFull`);
    ``scale_down_after`` consecutive steps with the queue empty and
    occupancy fitting the next tier down step it back (free lanes burn
    a decode row per step — shrinking recovers that compute).  EVERY
    tier's programs — each ``step_windows`` decode window, each
    admission bucket, the inter-tier resize gathers — compile at
    construction, so no request ever pays a recompile
    (``scripts/check_compile_counts.py``'s ``serving_elastic`` budget
    pins it).  A resize compacts occupied lanes; lane ids are
    therefore unstable, so elastic engines admit through the id-keyed
    :meth:`enqueue` surface only (bare ``submit`` rejects).
    ``serving.lanes_tier`` / ``serving.resizes`` /
    ``serving.resize`` events expose the tier trajectory through obs,
    and ``tier_epoch`` counts resizes for drain/debug correlation.

    ``step_windows`` declares the ``step(n)`` window sizes to
    pre-compile.  Elastic engines are restricted to the declared set;
    chunked-prefill and prefix-pool engines warm the declared set at
    construction (undeclared windows still compile lazily); plain
    engines ignore it beyond validation.

    **Pod-sharded serving** (round 14, ``plan=``/``mesh=``): ONE
    engine spans a whole device mesh — params placed by the plan's
    regex partition rules (``serving_plan()`` is the standard TP
    layout; ``fsdp_plan()`` works too), the KV cache's kv-heads
    dimension sharded over whatever axis the plan shards attention
    heads over (derived — ``parallel/rules.py``), row state
    replicated, every program compiled at construction under sharding
    constraints so GSPMD inserts the per-token collectives and the
    serve phase never compiles.  Emitted tokens are bit-exact vs the
    solo engine, greedy and sampled; per-device param+KV bytes drop
    ~axis-size× (see :meth:`memory_footprint`).  Composes with paged
    KV, chunked prefill, mesh-matched prefix pools, and (round 17)
    ``lane_tiers`` — every tier and resize gather compiles at
    construction under the plan's constraints; rejects
    ``prompt_cache``/rolling configs (the composition table lives in
    docs/serving_guide.md "Pod-sharded serving").

    **Live weight push** (round 20, ``hot_swap=True``): every decode
    and admission program takes the param tree as an explicit jit
    argument (never donated), so :meth:`swap_params` can replace the
    served weights BETWEEN steps with zero recompiles — the swap
    rebinds a host-side reference under the live placement
    (``jnp.asarray`` re-placement unsharded, ``device_put`` onto the
    live leaves' shardings under ``plan=``), it never re-keys the jit
    cache (the ``serving_weight_push`` compile session pins it).
    Swaps are version-monotone (``allow_downgrade=True`` is the
    canary rollback's exception), validated against the live tree's
    treedef/shapes/dtypes, and atomic under the admission lock — a
    request's next step either wholly sees version N or wholly sees
    N+1.  ``residency()`` reports ``param_version`` so the router's
    fleet snapshot carries per-replica versions.  Rejects
    ``prompt_cache``/``prefix_pool`` (prefilled K/V baked from old
    params would mix versions) and forces always-warm admission.  The
    policy layer above is :class:`~distkeras_tpu.serving.canary.
    CanaryController` over :class:`~distkeras_tpu.serving.publish.
    SnapshotReader`.
    """

    def __init__(self, params, cfg: TransformerConfig, lanes: int = 8,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 min_p=None, eos_token=None, exact_top_k: bool = False,
                 prompt_buckets=(8, 32, 128, 512), prompt_cache=None,
                 kv_int8: bool = False,
                 per_request_sampling: bool = False,
                 max_queue: int = 0, clock=None,
                 lane_tiers=None, scale_up_after: int = 2,
                 scale_down_after: int = 8, step_windows=(1,),
                 prefill_chunk: int | None = None, prefix_pool=None,
                 plan=None, mesh=None, hot_swap: bool = False):
        # Windowed configs: the engine runs ROLLING lanes — each lane
        # decodes past max_len on the ring-buffer cache (the unbounded
        # streaming-chat shape), which needs rope (positions beyond
        # max_len have no learned-table embedding) and a window that
        # fits the ring.  Non-rope windowed configs have no rolling
        # semantics, so they stay rejected rather than silently
        # becoming bounded.
        # Pod-sharded serving (round 14, ``plan=``/``mesh=``): one
        # engine replica spans a whole device mesh.  Params are placed
        # by the plan's regex partition rules (the same TP/FSDP
        # spellings training uses), the KV cache's kv-heads dimension
        # shards over whatever mesh axis the plan shards attention
        # heads over (DERIVED, never authored — parallel/rules.py's
        # serving_kv_axis), row metadata replicates, and every program
        # compiles ONCE with sharding constraints so GSPMD inserts the
        # per-token collectives — emitted tokens stay bit-exact vs the
        # solo engine (tests/test_serving_sharded.py).
        if (plan is None) != (mesh is None):
            raise ValueError(
                "pass plan= and mesh= together: the plan's rules only "
                "mean something against a concrete mesh (use "
                "parallel.sharding.serving_plan() for the standard TP "
                "layout)")
        if plan is not None:
            if cfg.attention_window is not None:
                raise ValueError(
                    "pod-sharded serving needs a full-cache config "
                    "(no attention_window): the ring slab's rolling "
                    "scatter has no stable sharded layout to pin")
            # lane_tiers composes (round 17): every tier's programs —
            # and the inter-tier resize gathers — compile at
            # construction under the same sharding constraints, so a
            # tier move on a sharded engine is still zero serve-phase
            # compiles (the serving_disagg compile session pins it).
            if prompt_cache is not None:
                raise ValueError(
                    "plan= does not compose with prompt_cache= (one "
                    "baked-in prefix); use prefix_pool= built with "
                    "the same mesh, or a PagedBatcher pinned stem")
        self.plan, self.mesh = plan, mesh
        if plan is not None:
            # Any ShardingPlan works (fsdp_plan/tp_plan/serving_plan):
            # the KV axis derives from its attention rules, with the
            # head-divisibility rejection naming the offending rule.
            from distkeras_tpu.parallel.rules import serving_kv_axis

            self._kv_axis = serving_kv_axis(plan, mesh, cfg)
        self._rolling = False
        if cfg.attention_window is not None:
            if not rolling_eligible(cfg):
                raise ValueError(
                    "windowed continuous batching runs rolling lanes, "
                    "which needs rope=True and attention_window <= "
                    "max_len (full-cache configs need no window)")
            if prompt_cache is not None:
                raise ValueError("prompt_cache requires a full-cache "
                                 "config (no attention_window)")
            if prefix_pool is not None:
                raise ValueError("prefix_pool requires a full-cache "
                                 "config (no attention_window)")
            if prefill_chunk is not None:
                raise ValueError(
                    "chunked prefill (prefill_chunk=) requires a "
                    "full-cache config: a rolling ring has no parking "
                    "slot whose garbage writes stay masked, and ring "
                    "prompts are already bounded by the ring size")
            # kv_int8 composes: the int8 ring slab is the same
            # slot-addressed slab update with scale slabs riding along.
            self._rolling = True
        # Elastic lane tiers (resilience subsystem): the engine starts
        # at the smallest tier and moves between PRE-COMPILED tiers
        # under load — every tier's programs compile at construction,
        # so no request ever pays a recompile (the admission-latency
        # analogue of the prompt-bucket contract).
        _tiers = None
        _windows = tuple(sorted({int(n) for n in step_windows}))
        if not _windows or _windows[0] < 1:
            raise ValueError(
                f"step_windows must be positive ints, got "
                f"{step_windows}")
        if lane_tiers is not None:
            _tiers = tuple(sorted({int(t) for t in lane_tiers}))
            if len(_tiers) < 2:
                raise ValueError(
                    f"lane_tiers needs >= 2 distinct tiers, got "
                    f"{lane_tiers} (a single fixed size is just lanes=)")
            if _tiers[0] < 1:
                raise ValueError(f"lane tiers must be >= 1, got {_tiers}")
            if scale_up_after < 1 or scale_down_after < 1:
                raise ValueError(
                    "scale_up_after/scale_down_after must be >= 1 "
                    f"(got {scale_up_after}, {scale_down_after})")
            if 1 not in _windows:
                raise ValueError(
                    "step_windows must include 1 — drain/shutdown "
                    "steps one token at a time")
            if max_queue < 1:
                raise ValueError(
                    "lane_tiers needs max_queue >= 1: the queue "
                    "overflow IS the scale-up signal")
            lanes = _tiers[0]
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if not isinstance(kv_int8, bool):
            # PagedBatcher validates its own tri-state and passes a
            # bool down; a string reaching a monolithic engine would
            # otherwise silently truthy-coerce into plain int8.
            raise ValueError(
                f"kv_int8 must be a bool here (got {kv_int8!r}); "
                'kv_int8="prefill" is a PagedBatcher admission mode')
        if prompt_cache is not None and prefix_pool is not None:
            raise ValueError(
                "pass prompt_cache (ONE engine-level prefix, baked "
                "into admission) OR prefix_pool (per-request pooled "
                "prefixes), not both")
        if prompt_cache is not None and prompt_cache[1] >= cfg.max_len:
            raise ValueError(
                f"shared prefix length {prompt_cache[1]} must leave "
                f"room under max_len={cfg.max_len}")
        if (temperature <= 0
                and (top_k
                     or (top_p is not None and top_p < 1.0)
                     or (min_p is not None and min_p > 0.0))
                and not per_request_sampling):
            # With per-request sampling the constructor values are only
            # DEFAULTS; a filter default alongside a greedy default
            # temperature is legal (it applies to requests that
            # override the temperature).  The explicit no-op values
            # (top_p=1.0 / min_p=0.0) are legal everywhere — the same
            # round-6 contract as generate and submit().
            raise ValueError(
                "top_k/top_p/min_p need temperature > 0 (greedy always "
                "takes the argmax)")
        # Eager range checks: the scalar step validates these lazily at
        # first trace, but the per-request path bakes them into device
        # arrays where a bad value would sample silent garbage
        # (log of a negative min_p is NaN, which masks every token).
        if temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # min_p=0.0 is the explicit "no filter" value on EVERY engine
        # mode (round-6: same contract as generate and submit()).
        if min_p is not None and not 0.0 <= min_p <= 1.0:
            raise ValueError(f"min_p must be in [0, 1], got {min_p}")
        if eos_token is not None and not 0 <= eos_token < cfg.vocab_size:
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{cfg.vocab_size})")
        # Live weight push (round 20, ``hot_swap=``): compile every
        # decode/admission program to take the param tree as an
        # ARGUMENT instead of closing over it, so swap_params() is a
        # warm-cache argument change — zero recompiles (the
        # serving_weight_push compile session pins it).  Prefilled
        # prefixes are rejected: their K/V was computed under the
        # params they were built with, so a swap would silently serve
        # a version mix (re-prefill and rebuild instead).
        self._hot_swap = bool(hot_swap)
        if self._hot_swap:
            if prompt_cache is not None or prefix_pool is not None:
                raise ValueError(
                    "hot_swap=True does not compose with "
                    "prompt_cache=/prefix_pool=: prefix K/V is baked "
                    "from the params it was prefilled with, so a "
                    "weight swap would silently mix param versions "
                    "mid-sequence — rebuild the prefix under the new "
                    "version instead")
            # Every program must exist before the first request: a
            # lazy serve-phase compile would land INSIDE the push
            # window the zero-compile budget pins.
            self._always_warm = True
        if plan is not None:
            # Sharded device placement per the plan's rules: the big
            # matmul operands scatter over the mesh, small leaves
            # (norm scales) replicate — per-device param bytes drop
            # ~axis-size×, asserted from addressable shards by
            # memory_footprint().  Already-placed trees re-place
            # cheaply (device_put is a no-op per unchanged leaf).
            self.params = jax.device_put(
                params, plan.tree_shardings(mesh, params))
            # Every program must exist before the first request: the
            # serving_sharded compile sessions assert a zero-compile
            # serve phase, same contract as elastic/paged engines.
            self._always_warm = True
        else:
            self.params = _device_tree(params)
        self.cfg = cfg
        self.lanes = lanes
        # Shared prefix (system prompt): every lane's request decodes
        # past a common prefilled prefix — same contract as
        # generate(prompt_cache=...); admission seeds the lane from the
        # prefix instead of zeros and all positions shift by its length.
        self._off = 0
        self._prefix_lane = None
        if prompt_cache is not None:
            # The ONE prompt_cache contract (generate's helper): batch
            # must be 1 here (b=1), the prefix quantization must match
            # the engine cache (build it with prefill(kv_int8=...)),
            # and the loosest budget (p=1, one new token) must fit;
            # per-request budgets are re-checked at submit.
            pc, self._off = _resolve_prompt_cache(
                prompt_cache, cfg, b=1, p=1, max_new_tokens=1,
                kv_int8=kv_int8, use_prefill=None)
            self._prefix_lane = jax.tree.map(jnp.asarray, pc)
        if prefix_pool is not None:
            if prefix_pool.draft_cfg is not None:
                raise ValueError(
                    "this pool holds (target, draft) speculative "
                    "pairs; build a plain PrefixPool(cfg, ...) for "
                    "ContinuousBatcher")
            if prefix_pool.kv_int8 != kv_int8:
                raise ValueError(
                    "prefix_pool quantization must match kv_int8= "
                    "(build the pool with the engine's kv_int8)")
            if getattr(prefix_pool, "mesh", None) != mesh:
                raise ValueError(
                    "prefix_pool placement must match the engine's: "
                    "build the pool with PrefixPool(..., mesh=, "
                    "kv_axis=) matching plan=/mesh= (a slab placed "
                    "differently from the cache would make every "
                    "pooled admission reshard the segment)")
            if (mesh is not None
                    and getattr(prefix_pool, "kv_axis", None)
                    != self._kv_axis):
                raise ValueError(
                    f"prefix_pool kv_axis="
                    f"{getattr(prefix_pool, 'kv_axis', None)!r} does "
                    f"not match the plan-derived KV axis "
                    f"{self._kv_axis!r}")
            want = jax.eval_shape(
                lambda: init_cache(cfg, 1, kv_int8=kv_int8))
            got = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
                prefix_pool.slab)
            if (jax.tree.structure(want) != jax.tree.structure(got)
                    or jax.tree.leaves(want) != jax.tree.leaves(got)):
                raise ValueError(
                    f"prefix_pool was built for a different config "
                    f"(pool segment {got}, engine cache {want})")
        self._prefix_pool = prefix_pool
        self.eos_token = eos_token
        self.temperature = temperature
        self.top_p = top_p
        self.min_p = min_p
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            if self._off + prefill_chunk > cfg.max_len:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} exceeds the cache "
                    f"slots past the prefix "
                    f"({cfg.max_len - self._off})")
        self.prefill_chunk = prefill_chunk
        # Buckets clamp to the cache slots past the shared prefix and
        # always include the largest legal width (and the chunk width,
        # so chunked admission's full chunks have an exact program).
        cap = cfg.max_len - self._off
        self._buckets = tuple(sorted(
            {min(int(w), cap) for w in prompt_buckets} | {cap}
            | ({prefill_chunk} if prefill_chunk else set())))
        self._lane_state: list[_Lane | None] = [None] * lanes
        self._next_id = 0
        # Admission control (resilience subsystem): ``max_queue`` bounds
        # the enqueue() backlog (0 = no queue: enqueue needs a free
        # lane); ``clock`` is the deadline clock (monotonic seconds;
        # injectable for deterministic chaos tests).
        self._init_admission(max_queue, clock)
        if _tiers is not None:
            self.lane_tiers = _tiers
            self.scale_up_after = scale_up_after
            self.scale_down_after = scale_down_after
        self._step_windows = _windows

        # Device state: one cache, per-lane next-position, per-lane
        # current token (the one the next step processes), per-lane key.
        # ``kv_int8``: the cache stores int8 K/V + f32 scales — halves
        # the dominant HBM term at batch where cache bytes rule
        # (+33% measured at b64, a LOSS at b8; see perf_serving.md) —
        # and every request still matches its solo
        # ``generate(kv_int8=True, use_prefill=False)`` run exactly:
        # both the admission chunk and the sequential path attend the
        # ALREADY-QUANTIZED cache position by position, unlike
        # prefill() which attends the prompt in full precision.
        # (Stored for introspection only, like ``lanes``; the runtime
        # switch is the ``k_scale`` leaf in ``self.cache``.)
        self.kv_int8 = bool(kv_int8)
        if kv_int8 and max(_tiers or (lanes,)) < KV_INT8_LANE_ADVISORY:
            # Construction-time advisory (round-10 satellite): at small
            # lane counts decode is weight-bound and the int8 cache is
            # a measured LOSS (-15% at b8); the lever pays only where
            # cache bytes dominate.  See docs/serving_guide.md's
            # byte-lever table for the regime boundary.
            msg = (f"kv_int8=True with {max(_tiers or (lanes,))} lanes:"
                   f" the int8 KV cache is a measured loss below "
                   f"~{KV_INT8_LANE_ADVISORY} lanes (-15% at b8; "
                   "docs/serving_guide.md byte-lever table) — decode "
                   "is weight-bound there, so the cache-byte saving "
                   "cannot pay for the dequant")
            warnings.warn(msg, RuntimeWarning, stacklevel=2)
            obs.event("serving.advisory", kind="kv_int8_small_lanes",
                      lanes=max(_tiers or (lanes,)), detail=msg)
        self.per_request_sampling = per_request_sampling
        # Engine-level sampling statics the compiled step closes over
        # (stored so the paged subclass's step factory reuses the ONE
        # per-token body — see _build_one_step).
        self.top_k = top_k
        self.exact_top_k = exact_top_k
        self._init_device_state(lanes)
        self._one_step = self._build_one_step()
        self._steps = {}
        self._build_admission_programs()

        if self.lane_tiers is not None:
            self._resize = self._make_resize()
            self._compile_tiers()
        elif (prefill_chunk is not None or self._prefix_pool is not None
                or self._always_warm):
            # Chunked/pooled engines make the elastic construction-time
            # promise too: every admission bucket (seeded + chunk
            # continuation + pool gather) and every DECLARED step
            # window compiles here, so the serve phase is recompile-
            # free (the serving_chunked / serving_prefix_pool compile
            # sessions assert it).  Undeclared step(n) windows still
            # compile lazily, as on a plain engine.  Engines that set
            # ``_always_warm`` (the paged engine) take this path
            # unconditionally — every one of their programs is built
            # here or nowhere.
            with obs.span("serving.compile_warm", lanes=lanes):
                self._warm_tier(lanes)

    # ----------------------------------------- device-state factories
    #
    # Split out of __init__ (round 12) so the paged engine
    # (serving/paged.py) can swap the STORAGE — a block slab + page
    # tables instead of the monolithic [lanes, max_len] cache — while
    # the host machinery, the per-token sampling body, and therefore
    # the exact-parity contract stay literally shared.

    # Engines that must compile every program at construction even
    # without chunked prefill / a pool / tiers (the paged engine).
    _always_warm = False

    def _fresh_cache(self, lanes: int):
        """A zeroed KV store for ``lanes`` decode rows — the ONE
        cache-layout decision point (monolithic here; the paged
        engine overrides with its block slab).  Sharded engines place
        it with the plan-derived kv-heads sharding (``_place_kv`` is a
        no-op unsharded) — warm-up dummies come through here too, so
        they always carry the live layout."""
        return self._place_kv(
            init_cache(self.cfg, lanes, kv_int8=self.kv_int8))

    def _init_device_state(self, lanes: int) -> None:
        self.cache = self._fresh_cache(lanes)
        self._init_lane_rows(lanes)

    def _place_rows(self, cur, pos, keys, temps, tps, mps):
        """Commit per-lane row state REPLICATED over the serving mesh
        (identity unsharded).  Shared by the live init and the warm-up
        dummies: for committed arrays the sharding is part of the jit
        cache key, so the two must agree or the serve phase pays a
        recompile."""
        if self.mesh is None:
            return cur, pos, keys, temps, tps, mps
        return tuple(self._place_replicated(x)
                     for x in (cur, pos, keys, temps, tps, mps))

    def _init_lane_rows(self, lanes: int) -> None:
        """Per-lane row state shared by every storage layout: next
        position, current token, PRNG key, per-request sampling
        params."""
        self.pos = jnp.zeros((lanes,), jnp.int32)
        self.cur = jnp.zeros((lanes,), jnp.int32)
        sampling = self.temperature > 0 or self.per_request_sampling
        self.keys = (jnp.stack([jax.random.key(0)] * lanes)
                     if sampling else None)
        # Per-lane sampling params (per_request_sampling only):
        # constructor values are the defaults; submit() overrides the
        # admitted lane's slots.  top_p 1.0 / min_p 0.0 are exact
        # no-ops in the row-wise masks.
        if self.per_request_sampling:
            # Explicit dtype: weak-typed f32 and plain f32 are distinct
            # jit avals, and the elastic warmup's dummy states must hit
            # the exact programs the live state will use.
            self.temps = jnp.full((lanes,), float(self.temperature),
                                  jnp.float32)
            self.tps = jnp.full((lanes,), float(self.top_p or 1.0),
                                jnp.float32)
            self.mps = jnp.full((lanes,), float(self.min_p or 0.0),
                                jnp.float32)
        else:
            # Placeholder args keep one step signature across modes
            # (allocated once — step() is the latency-floor hot loop).
            self.temps = self.tps = self.mps = jnp.zeros((lanes,),
                                                         jnp.float32)
        if self.keys is None:
            self.keys = jnp.zeros((lanes,), jnp.int32)  # unused filler
            self._keyed = False
        else:
            self._keyed = True
        (self.cur, self.pos, self.keys, self.temps, self.tps,
         self.mps) = self._place_rows(self.cur, self.pos, self.keys,
                                      self.temps, self.tps, self.mps)

    def _build_one_step(self):
        """The per-token decode body over a CONTIGUOUS [lanes, S]
        cache tree: attention + sampling + position advance.  ONE
        definition for every storage layout — the monolithic step
        scans it over the live cache, the paged step scans it over
        the page-table-gathered view — so emitted tokens cannot drift
        between the two engines."""
        cfg = self.cfg
        per_request_sampling = self.per_request_sampling
        temperature, top_p, min_p = (self.temperature, self.top_p,
                                     self.min_p)
        top_k, exact_top_k = self.top_k, self.exact_top_k

        def pick(k, row, q):
            return jax.random.categorical(
                jax.random.fold_in(k, q), row)

        def one_step_p(params, cache, cur, pos, keys, temps, tps, mps):
            logits, cache = _decode_chunk(
                params, cache, cur[:, None], pos, cfg)
            logits = logits[:, 0]                      # [lanes, V]
            if per_request_sampling:
                # Vectorized per-lane params: greedy lanes (t <= 0)
                # take the argmax of the RAW logits; the sampled draw
                # is computed for every lane (one static program) and
                # selected per lane.
                safe_t = jnp.where(temps > 0, temps, 1.0)
                scaled = logits / safe_t[:, None]
                if top_k is not None:
                    scaled = top_k_mask(scaled, top_k, exact=exact_top_k)
                # tps == 1.0 rows bypass the nucleus mask entirely:
                # float cumsum can overshoot 1.0 and mask an
                # underflowed-tail token that solo generate (which
                # skips the mask when top_p is None) could sample —
                # the bypass keeps the exact-parity contract.
                # min_p's 0.0 no-op is exact as-is (log 0 = -inf).
                scaled = jnp.where(tps[:, None] >= 1.0, scaled,
                                   top_p_mask(scaled, tps[:, None]))
                scaled = min_p_mask(scaled, mps[:, None])
                nxt = jnp.where(temps > 0,
                                jax.vmap(pick)(keys, scaled, pos),
                                logits.argmax(axis=-1))
            elif temperature > 0:
                scaled = logits / temperature
                if top_k is not None:
                    scaled = top_k_mask(scaled, top_k, exact=exact_top_k)
                # top_p >= 1.0 bypasses the mask, like the per-request
                # path and generate's scalar path (round-6 parity fix):
                # the sorted cumsum can float-overshoot 1.0 and mask an
                # underflowed tail token "no filter" could sample.
                if top_p is not None and top_p < 1.0:
                    scaled = top_p_mask(scaled, top_p)
                # min_p 0.0 likewise means "no filter" (and the scalar
                # mask rejects a concrete 0.0 outright).
                if min_p is not None and min_p > 0.0:
                    scaled = min_p_mask(scaled, min_p)
                nxt = jax.vmap(pick)(keys, scaled, pos)
            else:
                nxt = logits.argmax(axis=-1)
            # Device-side invariant (full-cache engines): pos NEVER
            # exceeds max_len - 1.  Free/done lanes keep decoding (the
            # price of one static program) and would otherwise advance
            # unboundedly; the clamp pins them to re-processing the
            # last slot — their outputs are discarded and admission
            # reseeds the lane, so correctness no longer leans on
            # dynamic_update_slice's start-clamping.  Live lanes are
            # unaffected: submit() budgets guarantee they finish at
            # pos <= max_len - 1.  Chunk-ADMITTING lanes park here too:
            # their garbage writes pin to the last slot, which the
            # request's own final decode step rewrites.  ROLLING
            # (windowed) engines are the exception by design: pos is
            # unbounded (the ring slot is pos % max_len), for idle
            # lanes too — harmless, since their writes land in slots
            # admission reseeds and the all-idle early-out in step()
            # stops the clock entirely.
            nxt_pos = (pos + 1 if self._rolling
                       else jnp.minimum(pos + 1, cfg.max_len - 1))
            return cache, nxt.astype(jnp.int32), nxt_pos

        if self._hot_swap:
            # Hot-swap engines thread the params through as the first
            # step argument (the swap is then a warm-cache argument
            # change); the default spelling below bakes self.params in
            # at trace time — its jaxpr, and therefore every recorded
            # compile budget and IR census, is byte-identical to the
            # pre-round-20 one.
            return one_step_p

        def one_step(cache, cur, pos, keys, temps, tps, mps):
            return one_step_p(self.params, cache, cur, pos, keys,
                              temps, tps, mps)
        return one_step

    def _make_step(self, n: int):
        one_step = self._one_step
        constrain = self._kv_constraint

        if self._hot_swap:
            def step_n_p(params, cache, cur, pos, keys, temps, tps,
                         mps):
                if constrain is not None:
                    cache = constrain(cache)

                def body(carry, _):
                    cache, cur, pos = carry
                    cache, cur, pos = one_step(params, cache, cur,
                                               pos, keys, temps, tps,
                                               mps)
                    return (cache, cur, pos), cur
                (cache, cur, pos), toks = jax.lax.scan(
                    body, (cache, cur, pos), None, length=n)
                if constrain is not None:
                    cache = constrain(cache)
                return cache, cur, pos, toks.T    # [lanes, n]
            # Donate the cache (now argument 1); params are NOT
            # donated — version N must survive the swap for rollback.
            return jax.jit(step_n_p, donate_argnums=1)

        def step_n(cache, cur, pos, keys, temps, tps, mps):
            if constrain is not None:
                # Pod-sharded engines pin the cache layout here: GSPMD
                # then inserts the per-token collectives (psum per
                # block + the unembed gather) against the DECLARED
                # kv-heads sharding — compiled once, zero steady-state
                # compiles (the serving_sharded session asserts it).
                cache = constrain(cache)

            def body(carry, _):
                cache, cur, pos = carry
                cache, cur, pos = one_step(cache, cur, pos, keys,
                                           temps, tps, mps)
                return (cache, cur, pos), cur
            (cache, cur, pos), toks = jax.lax.scan(
                body, (cache, cur, pos), None, length=n)
            if constrain is not None:
                cache = constrain(cache)
            return cache, cur, pos, toks.T        # [lanes, n]
        return jax.jit(step_n, donate_argnums=0)

    def _build_admission_programs(self) -> None:
        # Admission: prefill `width` positions of ONE lane (lane-sliced
        # cache write; padded tail slots stay masked until the decode
        # loop overwrites them).  ONE jitted program per bucket shape —
        # the start offset and pool slot are traced, so every prefix
        # length and chunk offset shares it.
        pooled = self._prefix_pool is not None
        constrain = self._kv_constraint
        self._admit = _make_lane_admit(self.params, self.cfg,
                                       prefix_lane=self._prefix_lane,
                                       pooled=pooled,
                                       constrain=constrain,
                                       take_params=self._hot_swap)
        # Chunked prefill: the continuation program lands chunk k > 0
        # on the lane's existing cache (no reseed — that would erase
        # the earlier chunks).
        self._admit_cont = (_make_lane_admit(self.params, self.cfg,
                                             seed=False,
                                             constrain=constrain,
                                             take_params=self._hot_swap)
                            if self.prefill_chunk is not None else None)
        self._reseed = (_make_lane_reseed(prefix_lane=self._prefix_lane,
                                          constrain=constrain)
                        if self._prefix_lane is not None else None)
        self._reseed_pool = (_make_lane_reseed(pooled=True,
                                               constrain=constrain)
                             if pooled else None)

    # ------------------------------------------------------------ API

    def _validate_budget(self, p: int, max_new_tokens: int,
                         off: int | None = None) -> None:
        off = self._off if off is None else off
        if (not self._rolling
                and off + p + max_new_tokens > self.cfg.max_len):
            # Rolling engines have no total-length cap: lanes decode
            # past max_len on the ring (the admission bucket check
            # below still caps the PROMPT at the ring size — a longer
            # prompt's chunk would wrap mid-write).
            raise ValueError(
                f"prefix ({off}) + prompt ({p}) + "
                f"max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len={self.cfg.max_len}")
        warm = p - 1
        if warm:
            # Every chunk of the admission plan must have a padded
            # write that fits the cache (dynamic_update_slice would
            # otherwise clamp the start and clobber earlier slots).
            self._chunk_plan(off, warm)

    def _bucket_for(self, width: int, start: int) -> int:
        """Smallest admission bucket >= ``width`` whose padded write at
        ``start`` stays inside the cache."""
        b = next((w for w in self._buckets
                  if w >= width and start + w <= self.cfg.max_len),
                 None)
        if b is None:
            raise ValueError(
                f"no admission bucket fits {width} prompt tokens at "
                f"cache offset {start} (buckets {self._buckets}, "
                f"max_len={self.cfg.max_len}); raise prompt_buckets "
                "or add a finer width")
        return b

    def _chunk_plan(self, off: int, warm: int, skip: int = 0) -> list:
        """The admission plan for ``warm`` prompt tokens decoding past
        ``off`` cached positions: a list of ``(start, width)`` — rows
        are materialized at execution.  Monolithic (one bucket-padded
        chunk at ``off``) unless chunked prefill is on and the warm
        length exceeds the chunk width; then full ``W``-wide chunks on
        the ``off + skip + k*W`` grid plus a bucket-padded tail whose
        start backs up so its padded end lands exactly at the warm
        frontier (re-prefilling the overlap is idempotent — same
        tokens, same cache prefix, same K/V).  ``skip`` drops the
        first ``skip`` warm tokens from the plan — the paged engine's
        stem-sharing admission, whose shared blocks already hold those
        positions' K/V (the backed-up tail can never reach into the
        skipped region: its width is at most one chunk, and the
        chunked branch only runs when more than a chunk remains).
        Raises if any padded write would overflow the cache."""
        if warm <= skip:
            return []
        w_chunk = self.prefill_chunk
        lo, span = off + skip, warm - skip
        if self._rolling or w_chunk is None or span <= w_chunk:
            return [(lo, self._bucket_for(span, lo))]
        m, rem = divmod(span, w_chunk)
        plan = [(lo + k * w_chunk, w_chunk) for k in range(m)]
        if plan[-1][0] + w_chunk > self.cfg.max_len:
            raise ValueError(
                f"chunked admission grid overflows the cache (chunk at "
                f"{plan[-1][0]} + {w_chunk} > {self.cfg.max_len})")
        if rem:
            # The chunk width is always a bucket (the constructor adds
            # it), so the smallest bucket >= rem is <= w_chunk < span:
            # the backed-up start always lands inside the grid, never
            # before lo, and its end off + warm fits by budget.
            b = next(w for w in self._buckets if w >= rem)
            plan.append((off + warm - b, b))
        return plan

    def _admission_plan(self, lane, prompt, off: int, warm: int):
        """Stage lane storage for an admission and return its chunk
        plan, or None to DECLINE for lack of KV storage (the paged
        engine's allocator-exhausted signal — surfaced as ``kv_blocks``
        backpressure by enqueue/pump).  The monolithic engine's storage
        is the lane row itself, so it never declines here."""
        del lane, prompt
        return self._chunk_plan(off, warm)

    def _abort_admission(self, lane) -> None:
        """Failure between storage staging and lane commit: release
        whatever _admission_plan staged (no-op for monolithic lanes;
        the paged engine frees the staged blocks)."""

    def _exec_admit(self, lane, start, rows, slot) -> None:
        """Execute the FIRST admission chunk (the one that seeds the
        lane) — ``slot`` is the pinned prefix-pool slot or None."""
        if slot is not None:
            self.cache = self._admit(
                self.cache, jnp.asarray(rows), jnp.int32(lane),
                jnp.int32(start), self._prefix_pool.slab,
                jnp.int32(slot))
        elif self._prefix_pool is not None:
            # Pooled engine, plain request: the gather program takes
            # slot -1 = "seed zeros".
            self.cache = self._admit(
                self.cache, jnp.asarray(rows), jnp.int32(lane),
                jnp.int32(start), self._prefix_pool.slab,
                jnp.int32(-1))
        else:
            self.cache = self._admit(*self._pargs(), self.cache,
                                     jnp.asarray(rows),
                                     jnp.int32(lane), jnp.int32(start))

    def _exec_reseed(self, lane, slot) -> None:
        """No admission chunk ran (1-token prompt) but the lane still
        needs its prefix K/V seeded."""
        if slot is not None:
            # 1-token prompt on a pooled prefix: no admission chunk
            # runs, but the lane still needs the prefix K/V.
            self.cache = self._reseed_pool(
                self.cache, jnp.int32(lane), self._prefix_pool.slab,
                jnp.int32(slot))
        elif self._prefix_lane is not None:
            # 1-token prompt: no admission chunk runs, but the lane
            # still needs the shared prefix's K/V (code-review
            # regression: skipping this read zeros where the prefix
            # belongs).
            self.cache = self._reseed(self.cache, jnp.int32(lane))
        # else: 1-token prompt, no prefix — stale slots stay masked
        # until the decode loop overwrites them.

    def _chunk_rows(self, prompt, off: int, start: int,
                    width: int) -> np.ndarray:
        """Bucket-padded token rows for the chunk covering positions
        ``[start, start + width)`` (real tokens up to the warm
        frontier, zero pad beyond — masked until overwritten)."""
        warm = prompt.size - 1
        rows = np.zeros((1, width), np.int32)
        lo = start - off
        hi = min(lo + width, warm)
        rows[0, :hi - lo] = prompt[lo:hi]
        return rows

    def _exec_chunk(self, lane, start, rows):
        self.cache = self._admit_cont(*self._pargs(), self.cache,
                                      jnp.asarray(rows),
                                      jnp.int32(lane), jnp.int32(start))

    def _finish_admission(self, lane, st):
        """Last chunk landed: un-park the lane — set its decode
        position past the warm prompt and hand it the final prompt
        token, exactly where monolithic admission leaves a lane."""
        self.pos = self.pos.at[lane].set(st.off + st.prompt_len - 1)
        self.cur = self.cur.at[lane].set(
            int(st.tokens[st.prompt_len - 1]))

    def submit(self, prompt, max_new_tokens: int, key=None,
               temperature=None, top_p=None, min_p=None, eos_token=None,
               ttl=None, deadline=None, prefix_id=None):
        """Admit one request; returns its lane id, or None if the
        engine is full.  ``prompt``: 1-D int tokens; ``key``: per-
        request PRNG key (required iff THIS request samples).

        ``temperature`` / ``top_p`` / ``min_p`` / ``eos_token``:
        per-request overrides of the engine defaults — engines built
        with ``per_request_sampling=True`` only (``eos_token`` is
        host-side bookkeeping and works on every engine).  Pass
        ``top_p=1.0`` / ``min_p=0.0`` (the explicit no-op values) for
        an unfiltered request on an engine whose default filters.
        ``top_p=1.0`` means "no nucleus filter" EVERYWHERE — here,
        the engine scalar path, and solo ``generate`` all bypass the
        mask at >= 1.0 (round-6 parity fix), so a request copying its
        solo call's ``top_p=1.0`` replays that run exactly.

        ``ttl`` (seconds from now) / ``deadline`` (absolute ``clock()``
        time): the request's deadline.  A request that is already
        expired never occupies a lane — its structured timeout result
        is recorded (see :meth:`results`) and None is returned; one
        that expires mid-decode is evicted at the next ``step()`` the
        same way.  Deadline-carrying requests report through
        ``poll``/``take``/``results``, not ``drain``; this request's id
        is exposed as ``self.last_request_id`` (the queue-level
        :meth:`enqueue` API wraps all of this and returns the request
        id directly).

        ``prefix_id``: decode past a pooled prefilled prefix
        (``prefix_pool=`` engines) — the lane is seeded from the
        pool's device slab, the prefix tokens run no prefill work, and
        the output matches ``generate(prompt, cfg, n,
        prompt_cache=(segment, P))`` exactly.  The entry is pinned
        until the lane is vacated.

        On a ``prefill_chunk=`` engine, a prompt longer than the chunk
        width returns its lane immediately but PARKED: the remaining
        prefill chunks run one per ``step()`` interleaved with decode,
        and the lane starts emitting when the last chunk lands.

        Elastic engines (``lane_tiers=``) reject bare ``submit``: lane
        indices are not stable across tier resizes, so requests must go
        through the id-keyed :meth:`enqueue` surface.

        The whole admission runs under the engine lock, so a submit
        racing ``begin_shutdown`` either lands its lane before the
        drain looks (and is drained) or raises EngineClosed — the same
        contract :meth:`enqueue` documents.
        """
        with self._admission_lock:
            return self._submit_locked(prompt, max_new_tokens, key,
                                       temperature, top_p, min_p,
                                       eos_token, ttl, deadline,
                                       prefix_id)

    def _submit_locked(self, prompt, max_new_tokens, key, temperature,
                       top_p, min_p, eos_token, ttl, deadline,
                       prefix_id=None):
        if self.lane_tiers is not None and not self._admitting_internal:
            raise ValueError(
                "elastic engines (lane_tiers=...) admit through "
                "enqueue(): a tier resize compacts lanes, so the lane "
                "id submit() would return can dangle")
        self._check_open()
        prompt = self._validate_request_args(prompt, max_new_tokens)
        p = prompt.size
        if ((temperature is not None or top_p is not None
             or min_p is not None) and not self.per_request_sampling):
            raise ValueError(
                "per-request temperature/top_p/min_p need "
                "ContinuousBatcher(per_request_sampling=True) — the "
                "default engine compiles the constructor's sampling "
                "params into the step")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if min_p is not None and not 0.0 <= min_p <= 1.0:
            # 0.0 is the explicit "no min-p filter" override.
            raise ValueError(f"min_p must be in [0, 1], got {min_p}")
        if temperature is not None and temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if eos_token is not None and not (
                0 <= eos_token < self.cfg.vocab_size):
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{self.cfg.vocab_size})")
        eff_t = self.temperature if temperature is None else temperature
        if eff_t <= 0 and ((top_p is not None and top_p < 1.0)
                           or (min_p is not None and min_p > 0.0)):
            # The explicit no-op values (top_p=1.0 / min_p=0.0) stay
            # legal on greedy requests — they turn a default filter OFF.
            raise ValueError(
                "per-request top_p/min_p need a sampling temperature "
                f"(effective temperature is {eff_t})")
        off, slot, lane = self._off, None, None
        if prefix_id is not None:
            # Pin FIRST (see _pin_prefix): from here on, a concurrent
            # pool.put can never evict this entry, so the slot stays
            # ours through the slab gather below.  Every non-admission
            # exit must release the pin.
            off, slot, _ = self._pin_prefix(prefix_id)
        try:
            self._validate_budget(p, max_new_tokens, off=off)
            if (key is None) == (eff_t > 0):
                raise ValueError(
                    "pass a per-request key iff this request samples "
                    f"(effective temperature={eff_t})")
            dl = self._deadline_of(ttl, deadline)
            if self._expired_on_arrival(dl, prompt, p):
                # The acceptance contract: an already-dead request
                # never occupies a lane; its timeout is a structured
                # result.
                if prefix_id is not None:
                    self._prefix_pool.release(prefix_id)
                return None
            free = self.free_lanes()
            if not free:
                self._decline_full()
                if prefix_id is not None:
                    self._prefix_pool.release(prefix_id)
                return None
            lane = free[0]
            chaos.probe("serving.admit")
            # The request's id (enqueue-assigned for internal
            # admission, fresh otherwise) — claimed BEFORE the
            # admission chunk so every span/event below carries it.
            rid = self._claim_rid()
            if not self._admitting_internal:
                obs.event("serving.submit", request_id=rid,
                          prompt_len=p, max_new=int(max_new_tokens))

            warm = p - 1
            plan = self._admission_plan(lane, prompt, off, warm)
            if plan is None:
                # KV-storage decline (the paged allocator is out of
                # blocks): no lane is occupied; enqueue/pump treat it
                # as backpressure, not a timeout.
                self._decline("kv_blocks")
                if prefix_id is not None:
                    self._prefix_pool.release(prefix_id)
                return None
            chunks = None
            if plan:
                start0, width0 = plan[0]
                rows = self._chunk_rows(prompt, off, start0, width0)
                with obs.span("serving.admit", bucket=width0,
                              chunks=len(plan), lane=lane,
                              request_id=rid):
                    self._exec_admit(lane, start0, rows, slot)
                if len(plan) > 1:
                    chunks = [(s, self._chunk_rows(prompt, off, s, w))
                              for s, w in plan[1:]]
            else:
                self._exec_reseed(lane, slot)
            if chunks is None:
                self.pos = self.pos.at[lane].set(off + warm)
                self.cur = self.cur.at[lane].set(int(prompt[-1]))
            else:
                # Parked: the lane burns decode rows at the clamp slot
                # until its last chunk lands (one_step's clamp note).
                self.pos = self.pos.at[lane].set(self.cfg.max_len - 1)
                self.cur = self.cur.at[lane].set(0)
            if self._keyed and key is not None:
                self.keys = self.keys.at[lane].set(key)
            if self.per_request_sampling:
                self.temps = self.temps.at[lane].set(float(eff_t))
                self.tps = self.tps.at[lane].set(float(
                    (self.top_p or 1.0) if top_p is None else top_p))
                self.mps = self.mps.at[lane].set(float(
                    (self.min_p or 0.0) if min_p is None else min_p))

            # The pin taken above becomes the lane's reference here.
            self._lane_state[lane] = _Lane(
                request_id=rid, prompt_len=p,
                max_new=max_new_tokens, key=key, tokens=list(prompt),
                eos=self.eos_token if eos_token is None else eos_token,
                deadline=dl, born=self._clock(), chunks=chunks,
                off=off, prefix_id=prefix_id)
            if not self._admitting_internal:
                self.last_request_id = rid
        except Exception:
            # Any failure between pin and lane commit (validation, a
            # chaos-injected admit fault, a dispatch error) must not
            # leak the prefix reference — nor, on the paged engine,
            # the KV blocks the admission plan staged.
            if prefix_id is not None:
                self._prefix_pool.release(prefix_id)
            if lane is not None:
                self._abort_admission(lane)
            raise
        if chunks is not None:
            self._admitting.append(lane)
        return lane

    def traced_for_analysis(self):
        """Trace targets for the IR lint (analysis/ir_lint.py): the
        jitted single-token decode step over the engine's live lane
        state, plus the admission chunk program at the smallest bucket
        (the round-10 engine builds — chunked continuations and pool
        gathers ride the same program shape).  Nothing executes — the
        lint traces and lowers only."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        if 1 not in self._steps:
            self._steps[1] = self._make_step(1)
        mode = ("per_request" if self.per_request_sampling
                else "sampled" if self.temperature > 0 else "greedy")
        if self._prefix_pool is not None:
            mode += "_pooled"
        if self._kv_axis is not None:
            # Pod-sharded engine: the census pins this step's per-token
            # collectives (scripts/comm_budget.json).
            mode += f"_tp{int(self.mesh.shape[self._kv_axis])}"
        rows = jnp.zeros((1, self._buckets[0]), jnp.int32)
        pargs = self._pargs()  # hot-swap engines take params first
        d = len(pargs)
        admit_args = pargs + (self.cache, rows, jnp.int32(0),
                              jnp.int32(self._off))
        if self._prefix_pool is not None:
            admit_args += (self._prefix_pool.slab, jnp.int32(0))
        return [
            TraceSpec(
                name=f"continuousbatcher_{mode}/decode_step",
                fn=self._steps[1],
                args=pargs + (self.cache, self.cur, self.pos,
                              self.keys, self.temps, self.tps,
                              self.mps),
                donate_argnums=(d,)),
            TraceSpec(
                name=f"continuousbatcher_{mode}/admit_b"
                     f"{self._buckets[0]}",
                fn=self._admit, args=admit_args, donate_argnums=(d,)),
        ]

    def step(self, n: int = 1):
        """Advance every lane ``n`` tokens in ONE device round-trip;
        returns ``{lane: [tokens...]}`` for lanes that emitted.

        ``n > 1`` amortizes the per-dispatch host/relay latency (the
        measured floor is ~1.6 ms — comparable to a whole decode step
        at batch 8) at the cost of admission granularity: new requests
        wait for the window to finish, and a lane that hits its
        eos/budget mid-window keeps decoding privately — the surplus
        tokens are discarded here, identical to truncating generate()'s
        sticky-fill output.  Emitted tokens are EXACTLY step(1)'s.

        Chunked prefill runs here too: at most ONE pending admission
        chunk executes per call (FIFO across parked lanes) before the
        decode dispatch, so a long prompt admitting never inserts more
        than one chunk's compute between any two decode rounds.

        Runs under the engine lock end to end: a concurrent
        ``enqueue`` can trigger a tier resize (scale-up), and the
        device state this step captures must not be swapped and
        compacted under it mid-round-trip.
        """
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if self.lane_tiers is not None and n not in self._step_windows:
            raise ValueError(
                f"elastic engines pre-compile their decode windows; "
                f"step({n}) is not in step_windows={self._step_windows}"
                " — declare it at construction (a lazy compile here "
                "would break the no-recompile contract across tiers)")
        with self._admission_lock:
            self.pump()
            # Tier hysteresis BEFORE the idle early-out: an idle
            # elastic engine must still step its lane count back down.
            self._maybe_scale_down()
            self._run_pending_chunk()
            # Idle engine (every lane empty, finished-but-undrained,
            # or still admitting): nothing can emit, so skip the
            # device round-trip entirely instead of burning a full
            # decode window.  Reap first: a parked (admitting) lane
            # whose deadline expired must still be evicted promptly,
            # not only once decode resumes.
            if all(s is None or s.done or s.chunks is not None
                   for s in self._lane_state):
                self._reap()
                return {}
            chaos.probe("serving.step")
            if obs.active() is not None:  # running() is O(lanes)
                obs.gauge("serving.lanes_busy", len(self.running()))
            with obs.span("serving.step", n=n):
                toks = self._dispatch_step(n)
            out = self._emit(lambda lane: toks[lane].tolist())
            # Deadline granularity is one step window: tokens emitted
            # in the window that straddles the deadline are kept in
            # the partial result.
            self._reap()
            return out

    def _dispatch_step(self, n: int):
        """ONE device round-trip of the ``n``-token decode window over
        the engine's storage; returns the emitted-token matrix
        ``[lanes, n]`` (host numpy).  The paged engine overrides this
        to grow page tables first and thread them through its step."""
        if n not in self._steps:
            self._steps[n] = self._make_step(n)
        self.cache, self.cur, self.pos, toks = self._steps[n](
            *self._pargs(), self.cache, self.cur, self.pos, self.keys,
            self.temps, self.tps, self.mps)
        return np.asarray(toks)


__all__ = ["ContinuousBatcher", "KV_INT8_LANE_ADVISORY"]
