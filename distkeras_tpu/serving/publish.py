"""Versioned weight snapshots: the trainer -> serving-fleet transport.

The reference stack was train-then-predict as one static pipeline — a
trainer ran to completion and handed its final weights to a
``ModelPredictor``.  The production shape is continuous: a trainer
publishes weights into a *live* fleet without dropping a request.  This
module is the wire format of that loop; ``serving/canary.py`` is the
rollout policy on top.

Design:

* **Snapshots are fusion buckets.**  A snapshot is the parameter pytree
  packed through the exact dtype-grouped bucket layout the gradient
  exchange already wires (:class:`~distkeras_tpu.parallel.collectives.
  Zero1Layout` with ``n=1``) — same leaf-order placement, same
  dtype-homogeneous buckets, so the optional ``int8`` coding is the
  exchange layer's symmetric per-row quantization for free.  Packing is
  pure numpy over ``layout.slots``: a publisher never traces or
  compiles anything.
* **A reader never adopts a partial publish.**  Bucket files land
  first; the manifest (per-bucket CRCs + a SHA-256 over its own body)
  is written last via tmp + ``os.replace``, and the ``LATEST`` pointer
  after that.  A publisher killed mid-publish (the ``train_kill_push``
  chaos leg probes ``publish.commit`` right before the manifest
  rename) leaves bucket files but no manifest — :class:`SnapshotReader`
  raises :class:`SnapshotCorrupt` instead of adopting, and ``LATEST``
  still names the previous good version.
* **Versions are monotone.**  A reader records the version it last
  adopted and declines anything ≤ it (:class:`StaleSnapshot`), so a
  replayed or re-pointed ``LATEST`` can never roll a fleet backward
  silently — downgrades are a first-class *rollback* in the canary
  controller, not an accident here.

Locking: ``serving.publish`` (a leaf lock — nothing else is taken
while it is held) serializes concurrent publishes of one publisher;
reader adoption state is a single int assignment guarded by the same
discipline on the caller (the canary controller holds
``serving.canary``).  See docs/concurrency.md.
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile
import zlib

import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.parallel.collectives import DEFAULT_BUCKET_MB, Zero1Layout
from distkeras_tpu.resilience import chaos
from distkeras_tpu.utils.locks import TracedLock

__all__ = [
    "SnapshotPublisher", "SnapshotReader",
    "SnapshotError", "SnapshotCorrupt", "StaleSnapshot",
]

_MANIFEST = "MANIFEST.json"
_LATEST = "LATEST"
# Matches parallel/exchange.py's int8 zero-scale guard.
_EPS = np.float32(1.1754944e-38)


class SnapshotError(RuntimeError):
    """Base error for snapshot publish/load failures."""


class SnapshotCorrupt(SnapshotError):
    """Torn or corrupt snapshot: missing manifest, manifest-hash
    mismatch, or a bucket whose checksum does not match.  Never
    adopted — the reader stays on its current version."""


class StaleSnapshot(SnapshotError):
    """Snapshot version ≤ the reader's adopted version."""


def _version_dir(root: str, version: int) -> str:
    return os.path.join(root, f"v{int(version):08d}")


def _dtype(name: str) -> np.dtype:
    """dtype-by-name, including the ml_dtypes extension types (e.g.
    ``bfloat16``) numpy itself cannot spell."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def _is_float(dtype: np.dtype) -> bool:
    try:
        if np.issubdtype(dtype, np.floating):
            return True
    except TypeError:
        pass
    return dtype.name in ("bfloat16", "float16", "float32", "float64")


def _manifest_hash(body: dict) -> str:
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def _int8_encode(x: np.ndarray):
    """Numpy spelling of ``parallel.exchange.int8_encode``: symmetric
    per-row quantization over the last axis."""
    xf = np.asarray(x, dtype=np.float32)
    scale = np.max(np.abs(xf), axis=-1, keepdims=True) / 127.0
    scale = np.maximum(scale, _EPS).astype(np.float32)
    q = np.clip(np.rint(xf / scale), -127, 127).astype(np.int8)
    return q, scale


class SnapshotPublisher:
    """Trainer-side writer of versioned parameter snapshots.

    ``root`` is the snapshot directory (one subdirectory per version);
    ``coding`` is ``None`` (raw buckets) or ``"int8"`` (the exchange
    layer's symmetric per-row int8 on floating buckets — lossy, the
    serving-side weights are the dequantized values); ``bucket_mb``
    must match what readers rebuild, so it is recorded in the manifest.
    """

    def __init__(self, root: str, coding: str | None = None,
                 bucket_mb: float = DEFAULT_BUCKET_MB):
        if coding not in (None, "int8"):
            raise ValueError(
                f"unknown snapshot coding {coding!r}; known: None, 'int8'")
        self.root = str(root)
        self.coding = coding
        self.bucket_mb = float(bucket_mb)
        self._lock = TracedLock("serving.publish")
        self._layout: Zero1Layout | None = None
        self.published = 0
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------ pack

    def _layout_for(self, leaves, treedef) -> Zero1Layout:
        # Layout is geometry-only; cache it across rounds (every round
        # publishes the same pytree geometry).
        lay = self._layout
        if (lay is None or lay.treedef != treedef
                or any(tuple(np.shape(x)) != s.shape
                       for s, x in zip(lay.slots, leaves))):
            lay = Zero1Layout.for_tree(
                [np.asarray(x) for x in leaves], n=1,
                bucket_mb=self.bucket_mb)
            lay = Zero1Layout(
                n=1, treedef=treedef, slots=lay.slots,
                bucket_cols=lay.bucket_cols,
                bucket_dtypes=lay.bucket_dtypes,
                bucket_groups=lay.bucket_groups)
            self._layout = lay
        return lay

    @staticmethod
    def _np_pack(layout: Zero1Layout, leaves) -> list[np.ndarray]:
        """Pure-numpy ``Zero1Layout.pack`` for ``n=1`` (cols == size,
        zero pad): no tracing, no device transfers."""
        buckets = [np.zeros((1, c), dtype=d)
                   for c, d in zip(layout.bucket_cols,
                                   layout.bucket_dtypes)]
        for slot, leaf in zip(layout.slots, leaves):
            flat = np.asarray(leaf).reshape(-1)
            buckets[slot.bucket][0, slot.offset:slot.offset + slot.cols] \
                = flat
        return buckets

    # --------------------------------------------------------- publish

    def publish(self, tree, version: int) -> str:
        """Write ``tree`` as snapshot ``version``; returns the snapshot
        directory.  Atomic from any reader's point of view: bucket
        files first, manifest via tmp + ``os.replace`` second, the
        ``LATEST`` pointer last."""
        version = int(version)
        with self._lock:
            import jax.tree_util as jtu

            leaves, treedef = jtu.tree_flatten(tree)
            layout = self._layout_for(leaves, treedef)
            buckets = self._np_pack(layout, leaves)
            vdir = _version_dir(self.root, version)
            os.makedirs(vdir, exist_ok=True)
            entries, total = [], 0
            for i, bucket in enumerate(buckets):
                fname = f"bucket_{i:04d}.npz"
                coded = (self.coding
                         if self.coding and _is_float(bucket.dtype)
                         else None)
                if coded == "int8":
                    q, scale = _int8_encode(bucket)
                    payload = {"q": q, "scale": scale}
                    crc = zlib.crc32(scale.tobytes(),
                                     zlib.crc32(q.tobytes()))
                    nbytes = q.nbytes + scale.nbytes
                else:
                    raw = np.frombuffer(bucket.tobytes(), dtype=np.uint8)
                    payload = {"raw": raw}
                    crc = zlib.crc32(raw.tobytes())
                    nbytes = raw.nbytes
                np.savez(os.path.join(vdir, fname), **payload)
                entries.append({
                    "file": fname, "crc": int(crc),
                    "dtype": np.dtype(bucket.dtype).name,
                    "cols": int(bucket.shape[1]), "coding": coded,
                })
                total += nbytes
            body = {"version": version, "bucket_mb": self.bucket_mb,
                    "n_leaves": len(leaves), "buckets": entries}
            manifest = dict(body, manifest_hash=_manifest_hash(body))
            # The commit point: everything before this line is
            # invisible to readers; everything after is atomic.  The
            # train_kill_push chaos leg SIGKILLs here — the torn
            # version directory has buckets but no manifest.
            chaos.probe("publish.commit", step=version)
            tmp = os.path.join(vdir, _MANIFEST + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(vdir, _MANIFEST))
            ltmp = os.path.join(self.root, _LATEST + ".tmp")
            with open(ltmp, "w") as f:
                f.write(str(version))
                f.flush()
                os.fsync(f.fileno())
            os.replace(ltmp, os.path.join(self.root, _LATEST))
            self.published += 1
            obs.count("publish.snapshots")
            obs.event("publish.commit", version=version,
                      buckets=len(entries), bytes=total,
                      coding=self.coding)
            return vdir


class SnapshotReader:
    """Engine-side loader of published snapshots.

    Tracks the last *adopted* version; :meth:`poll` surfaces only a
    strictly newer, fully verified snapshot.  Verification order:
    manifest present -> manifest hash -> per-bucket CRC -> geometry
    against the caller's template pytree.  Any failure raises
    :class:`SnapshotCorrupt` (counted as ``publish.torn``) and leaves
    the adopted version untouched.
    """

    def __init__(self, root: str):
        self.root = str(root)
        self.version = 0  # last adopted version; 0 = none yet

    # ----------------------------------------------------------- state

    def latest_version(self) -> int | None:
        """The publisher's ``LATEST`` pointer, or ``None`` before the
        first complete publish."""
        try:
            with open(os.path.join(self.root, _LATEST)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def adopt(self, version: int) -> None:
        """Record ``version`` as adopted (the caller swapped it into
        an engine); later polls only surface strictly newer ones."""
        self.version = max(self.version, int(version))
        obs.event("publish.adopt", version=int(version))

    # ------------------------------------------------------------ load

    def _manifest(self, version: int) -> dict:
        path = os.path.join(_version_dir(self.root, version), _MANIFEST)
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            obs.count("publish.torn")
            raise SnapshotCorrupt(
                f"snapshot v{version}: no readable manifest at {path} "
                f"({e}) — torn publish, not adopting") from e
        body = {k: v for k, v in manifest.items() if k != "manifest_hash"}
        if manifest.get("manifest_hash") != _manifest_hash(body):
            obs.count("publish.torn")
            raise SnapshotCorrupt(
                f"snapshot v{version}: manifest hash mismatch — torn or "
                "tampered publish, not adopting")
        return manifest

    def load(self, version: int, template):
        """Verify and decode snapshot ``version`` into the geometry of
        ``template`` (a pytree of arrays or ShapeDtypeStructs); returns
        a numpy pytree.  Does NOT mark the version adopted — callers
        adopt only after the swap lands (see ``CanaryController``)."""
        version = int(version)
        if version <= self.version:
            obs.count("publish.stale")
            raise StaleSnapshot(
                f"snapshot v{version} ≤ adopted v{self.version}")
        manifest = self._manifest(version)
        import jax.tree_util as jtu

        leaves, treedef = jtu.tree_flatten(template)
        layout = Zero1Layout.for_tree(
            [np.asarray(x) if not hasattr(x, "dtype") else x
             for x in leaves],
            n=1, bucket_mb=float(manifest.get("bucket_mb",
                                              DEFAULT_BUCKET_MB)))
        entries = manifest["buckets"]
        if (len(entries) != len(layout.bucket_cols)
                or manifest.get("n_leaves") != len(leaves)):
            obs.count("publish.torn")
            raise SnapshotCorrupt(
                f"snapshot v{version}: {len(entries)} buckets /"
                f" {manifest.get('n_leaves')} leaves do not match the"
                f" template layout ({len(layout.bucket_cols)} buckets /"
                f" {len(leaves)} leaves)")
        vdir = _version_dir(self.root, version)
        buckets: list[np.ndarray] = []
        for i, ent in enumerate(entries):
            dtype = _dtype(ent["dtype"])
            cols = int(ent["cols"])
            if (cols != layout.bucket_cols[i]
                    or dtype != np.dtype(layout.bucket_dtypes[i])):
                obs.count("publish.torn")
                raise SnapshotCorrupt(
                    f"snapshot v{version} bucket {i}: "
                    f"[{ent['dtype']} x {cols}] does not match template "
                    f"[{np.dtype(layout.bucket_dtypes[i]).name} x "
                    f"{layout.bucket_cols[i]}]")
            try:
                with np.load(os.path.join(vdir, ent["file"])) as z:
                    payload = {k: z[k] for k in z.files}
            except (OSError, ValueError, KeyError, zlib.error,
                    zipfile.BadZipFile) as e:
                obs.count("publish.torn")
                raise SnapshotCorrupt(
                    f"snapshot v{version}: bucket file {ent['file']} "
                    f"unreadable ({e})") from e
            if ent.get("coding") == "int8":
                q, scale = payload["q"], payload["scale"]
                crc = zlib.crc32(scale.tobytes(),
                                 zlib.crc32(q.tobytes()))
                bucket = (q.astype(np.float32) * scale).astype(dtype)
            else:
                raw = payload["raw"]
                crc = zlib.crc32(raw.tobytes())
                bucket = np.frombuffer(
                    raw.tobytes(), dtype=dtype).reshape(1, cols)
            if int(crc) != int(ent["crc"]):
                obs.count("publish.torn")
                raise SnapshotCorrupt(
                    f"snapshot v{version} bucket {i} ({ent['file']}): "
                    f"checksum mismatch (manifest {ent['crc']}, "
                    f"payload {crc}) — not adopting")
            buckets.append(np.asarray(bucket))
        out = []
        for s in layout.slots:
            flat = buckets[s.bucket][:, s.offset:s.offset + s.cols]
            out.append(flat.reshape(-1)[:s.size].reshape(s.shape))
        return treedef.unflatten(out)

    def poll(self, template):
        """``(version, tree)`` for the newest fully-verified snapshot
        strictly above the adopted version, else ``None``.  Raises
        :class:`SnapshotCorrupt` if the newest snapshot is torn — the
        caller decides whether to abort or retry."""
        latest = self.latest_version()
        if latest is None or latest <= self.version:
            return None
        return latest, self.load(latest, template)
