"""Paged KV: block-granular cache with per-lane page tables.

The monolithic engines allocate every lane a full ``[max_len]`` KV
row, so HBM — not compute — caps the lane count, and two requests
sharing a common stem share nothing unless it was pre-registered in a
:class:`~distkeras_tpu.serving.prefix.PrefixPool`.  This module is the
vLLM-style fix (round 12):

- **One slab, fixed-size blocks.**  The whole cache is ONE device
  allocation of ``n_blocks`` blocks of ``block`` positions each
  (``[L, n_blocks, block, kv_heads, head_dim]`` per K/V leaf — i.e.
  ``init_cache`` with ``batch=n_blocks, max_len=block``).  Block 0 is
  the reserved TRASH block: unallocated page-table entries point at
  it, so idle/done/parked lanes' clamped garbage writes land there and
  admission pad writes are redirected there — allocated memory tracks
  *live tokens*, not bucket roundup.
- **Per-lane page tables.**  Each lane carries a ``[max_blocks]``
  int32 row mapping logical block k to a physical slab block.  The
  host owns the authoritative numpy copy (the allocator is host-side
  bookkeeping); the device copy is re-pushed on change — a transfer,
  never a compile.
- **The paged step gathers by page table inside the compiled
  program** and then runs the EXACT monolithic per-token body
  (:meth:`ContinuousBatcher._build_one_step` — one definition) over
  the gathered contiguous view, scattering the window's new K/V back
  into the slab afterwards.  Because ``block`` must divide
  ``max_len``, the gathered view is exactly ``[lanes, max_len]`` with
  the same mask arithmetic, so greedy AND seeded-sampled tokens are
  bit-identical to the monolithic engine (pinned by
  tests/test_serving_paged.py).
- **Content-hash stem sharing at admission.**  Every full block of
  warm prompt tokens is chain-hashed; a new request whose prompt
  prefix hashes to resident blocks refcounts them instead of
  re-prefilling — the :class:`PrefixPool` generalized to ANY common
  stem, with pinned prefixes (:meth:`PagedBatcher.pin_prefix`) just
  refcount-held block runs in the same slab: one allocator, one slab,
  one mechanism.  Hashes register only once the block's content has
  actually been dispatched (chunked prefill lands over several
  steps), so a concurrent admission can never share an unwritten
  block.
- **Copy-on-write fork** (:meth:`PagedBatcher.fork`): beam branches
  and speculative checkpoints fork the page table — full blocks are
  refcount-shared, only the divergent tail block is copied — instead
  of copying whole lane caches.

Safety invariant the whole design leans on: a block becomes shared
(by stem hit, pin, or fork) only when it lies wholly BELOW its
owner's write frontier, and every device write lands at or above the
writer's frontier (or in trash), so a shared block is immutable for
as long as it is shared.

Allocator exhaustion is backpressure, not corruption: admission
declines (``enqueue`` queues, then raises
:class:`~distkeras_tpu.serving.QueueFull`); a lane that cannot grow
mid-decode is evicted with a structured ``"error"`` result and its
private blocks return to the free list (shared blocks survive — the
chaos leg in tests/test_serving_paged.py).

When monolithic still wins: the XLA gather materializes a
``[lanes, max_len]`` working view per step, so per-step HBM *traffic*
is higher than the monolithic read — the paged win is *resident*
bytes (lane count at fixed slab), sharing, and O(block) forks.  See
docs/serving_guide.md#paged-kv.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.models.generate import _decode_chunk, init_cache, prefill
from distkeras_tpu.models.quant import is_quantized
from distkeras_tpu.models.transformer import TransformerConfig
from distkeras_tpu.serving.engine import _Lane
from distkeras_tpu.serving.lanes import ContinuousBatcher
from distkeras_tpu.serving.prefix import PinnedStems
from distkeras_tpu.serving.disagg import BlockShipment
from distkeras_tpu.serving.residency import chain_hash as _chain_hash
from distkeras_tpu.serving.residency import stem_hashes as _stem_hashes
from distkeras_tpu.utils.locks import TracedRLock

# Physical block 0 is never handed out: unallocated page-table entries
# read it (masked anyway) and redirected pad/clamp writes land in it.
TRASH_BLOCK = 0

# kv_int8="prefill" parity bound: max |logit delta| of the first
# decode step after a prefill-BUILT int8 admission vs the exact
# decode-built cache.  Measured 0.005-0.017 across seeds on the d32/L2
# test config (argmax preserved everywhere); pinned at ~3x the worst
# measurement by tests/test_serving_paged.py::
# test_kv_int8_prefill_admission_tolerance — if this grows, the
# prefill-built write path regressed, not the tolerance.
KV_INT8_PREFILL_LOGIT_TOL = 0.05


def _gather_view(leaf, tables):
    """``leaf [L, N, B, ...]`` gathered through ``tables [rows, mb]``
    into the contiguous per-lane view ``[L, rows, mb*B, ...]`` the
    shared decode body expects."""
    g = jnp.take(leaf, tables, axis=1)
    return g.reshape(g.shape[:2] + (g.shape[2] * g.shape[3],)
                     + g.shape[4:])


class BlockAllocator:
    """Host-side refcounted block allocator with content-hash
    residency.

    Blocks live in one of two states: **live** (refcount > 0 — some
    lane's page table, a pinned stem, or a fork holds them) or on the
    **free list** (refcount 0).  A freed block keeps its content hash
    until the free list recycles it, so a later request can revive it
    by hash — cross-request stem sharing even when the requests never
    overlap in time (the vLLM cached-allocator idea).  ``alloc`` pops
    the oldest free block and purges its hash; ``share_by_hash``
    revives or refcounts a resident block.

    Thread-safe leaf lock (engines call under their admission lock —
    the same admission -> pool ordering docs/concurrency.md pins).
    """

    def __init__(self, n_blocks: int, block: int, reserved: int = 1):
        if n_blocks <= reserved:
            raise ValueError(
                f"n_blocks ({n_blocks}) must exceed the {reserved} "
                "reserved trash block(s)")
        self.block = int(block)
        self.n_blocks = int(n_blocks)
        self.capacity = self.n_blocks - reserved
        # dict-as-ordered-set: FIFO free list with O(1) revival.
        self._free: dict[int, None] = dict.fromkeys(
            range(reserved, n_blocks))
        self._refs: dict[int, int] = {}
        self._hash_of: dict[int, bytes] = {}
        self._by_hash: dict[bytes, int] = {}
        self._lock = TracedRLock("serving.kv_allocator")

    # ------------------------------------------------------ lifecycle

    def alloc(self) -> int | None:
        """Pop the oldest free block (purging any resident hash) with
        one reference, or None when exhausted — the backpressure
        signal, never an exception (the engine decides the policy)."""
        with self._lock:
            if not self._free:
                return None
            bid = next(iter(self._free))
            del self._free[bid]
            h = self._hash_of.pop(bid, None)
            if h is not None and self._by_hash.get(h) == bid:
                del self._by_hash[h]
            self._refs[bid] = 1
            return bid

    def free(self, bid: int) -> None:
        """Drop one reference; at zero the block moves to the free
        list (its hash stays resident until recycled)."""
        with self._lock:
            r = self._refs.get(bid)
            if r is None:
                raise ValueError(f"block {bid} is not live (double "
                                 "free, or never allocated)")
            if r > 1:
                self._refs[bid] = r - 1
            else:
                del self._refs[bid]
                self._free[bid] = None

    def share(self, bid: int) -> None:
        """One more reference to a LIVE block (fork/pin)."""
        with self._lock:
            if bid not in self._refs:
                raise ValueError(f"block {bid} is not live")
            self._refs[bid] += 1

    def share_by_hash(self, digest: bytes) -> int | None:
        """Refcount the resident block holding ``digest``'s content
        (reviving it off the free list if unreferenced); None on a
        miss."""
        with self._lock:
            bid = self._by_hash.get(digest)
            if bid is None:
                return None
            if bid in self._free:
                del self._free[bid]
                self._refs[bid] = 1
            else:
                self._refs[bid] += 1
            return bid

    def register(self, bid: int, digest: bytes) -> None:
        """Publish a live block's content hash for future sharing.
        First writer wins: if the digest is already mapped (a
        concurrent identical admission that both missed), the second
        block simply stays private — same content either way."""
        with self._lock:
            if bid not in self._refs:
                raise ValueError(f"block {bid} is not live")
            if digest in self._by_hash:
                return
            old = self._hash_of.pop(bid, None)
            if old is not None and self._by_hash.get(old) == bid:
                del self._by_hash[old]
            self._hash_of[bid] = digest
            self._by_hash[digest] = bid

    # ----------------------------------------------------- inspection

    def refs_of(self, bid: int) -> int:
        with self._lock:
            return self._refs.get(bid, 0)

    def resident_hashes(self) -> list[bytes]:
        """Every digest currently resident (live OR free-but-not-yet-
        recycled — both hit on :meth:`share_by_hash`): the paged half
        of the engine's residency digest (round 13)."""
        with self._lock:
            return list(self._by_hash)

    def stats(self) -> dict:
        """``used``/``free``/``shared`` block counts (shared = live
        with more than one reference) + hash residency."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "used": len(self._refs),
                "free": len(self._free),
                "shared": sum(1 for r in self._refs.values() if r > 1),
                "resident_hashes": len(self._by_hash),
            }


class PagedBatcher(ContinuousBatcher):
    """:class:`ContinuousBatcher` on block-granular paged KV storage.

    Same host API (``submit``/``enqueue``/``step``/``drain``, the full
    admission-control surface, ``per_request_sampling``, chunked
    prefill) and the same exact-parity contract — every request's
    emitted tokens are bit-identical to the monolithic engine's and to
    solo ``generate`` — plus:

    - ``block`` / ``n_blocks``: the slab geometry.  ``block`` must
      divide ``cfg.max_len``; ``n_blocks`` defaults to the
      monolithic-equivalent ``lanes * max_len/block + 1`` — shrink it
      to serve more lanes than monolithic HBM would allow (memory is
      consumed by actual tokens, not ``max_len`` rows), at the price
      of ``QueueFull`` backpressure when the allocator runs dry and
      structured ``"error"`` eviction if a lane cannot grow mid-decode.
    - **stem sharing** is automatic: a prompt whose full-block prefix
      was already prefilled (by any resident request, or a pinned
      prefix) refcounts those blocks and prefills only the remainder.
    - :meth:`pin_prefix` / :meth:`unpin_prefix`: the prefix-pool story
      on the one slab — pinned block runs any matching prompt hits by
      hash, no ``prefix_id`` plumbing at submit.
    - :meth:`fork`: copy-on-write lane fork (beam branching,
      speculative checkpoint/rollback) — shares full blocks, copies
      only the divergent tail block.
    - ``kv_int8``: ``True`` is the exact-parity decode-built int8
      cache (vs the monolithic ``kv_int8=True`` engine); ``"prefill"``
      additionally builds from-scratch single-chunk admissions through
      the batched ``prefill(kv_int8=True)`` forward — faster
      admission at a measured, test-pinned parity tolerance
      (full-precision in-chunk attention, quantized once at the end).

    - ``plan=``/``mesh=`` (round 14): pod-sharded paging — the block
      slab's kv-heads dimension shards over the plan-derived mesh
      axis exactly like the monolithic cache (the slab layout ends
      ``[..., kv_heads, head_dim]`` too), page tables and the
      allocator stay host-side/replicated, so stem sharing, pinned
      stems, and CoW forks work unchanged on a slab that spans the
      mesh.  Same bit-parity/bytes/zero-compile contract as the
      sharded ContinuousBatcher (docs/serving_guide.md "Pod-sharded
      serving").

    - ``lane_tiers=`` (round 17): elastic paging — the slab and the
      block allocator are lane-count-independent, so a tier move is a
      rows-only gather plus a host-side page-table remap: zero KV
      bytes move and zero serve-phase compiles (every tier's programs
      and the inter-tier row gathers warm at construction, sharded
      engines included).  ``n_blocks`` defaults to covering the TOP
      tier.  :meth:`fork` is rejected (lane ids are not stable across
      a resize).

    Not supported (structurally): ``attention_window`` (ring slots
    have no stable block identity), ``prompt_cache=`` / ``prefix_pool=``
    (subsumed by pinned stems).

    Every program — the step windows, one admission program per
    bucket, the CoW block copy and row fork — compiles at
    construction; the ``serving_paged`` / ``serving_paged_cow``
    compile sessions pin a zero-recompile serve phase.
    """

    _always_warm = True

    def __init__(self, params, cfg: TransformerConfig, lanes: int = 8,
                 block: int = 16, n_blocks: int | None = None,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 min_p=None, eos_token=None, exact_top_k: bool = False,
                 prompt_buckets=(8, 32, 128, 512), kv_int8=False,
                 per_request_sampling: bool = False,
                 max_queue: int = 0, clock=None,
                 lane_tiers=None, scale_up_after: int = 2,
                 scale_down_after: int = 8, step_windows=(1,),
                 prefill_chunk: int | None = None, plan=None,
                 mesh=None):
        if cfg.attention_window is not None:
            raise ValueError(
                "paged KV needs a full-cache config (no "
                "attention_window): a ring slot has no stable block "
                "identity to share or fork")
        block = int(block)
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        if cfg.max_len % block:
            raise ValueError(
                f"block ({block}) must divide max_len ({cfg.max_len}): "
                "the page-table gather must tile the position axis "
                "exactly or the step's mask arithmetic (and the "
                "bit-parity contract) would drift from the monolithic "
                "engine")
        if kv_int8 not in (False, True, "prefill"):
            raise ValueError(
                f'kv_int8 must be False, True, or "prefill", got '
                f"{kv_int8!r}")
        self.kv_int8_prefill = kv_int8 == "prefill"
        if self.kv_int8_prefill and is_quantized(params):
            raise ValueError(
                'kv_int8="prefill" runs the batched prefill forward '
                "at admission, which needs full-precision params "
                "(decode-built kv_int8=True composes with int8 "
                "weights)")
        self.block = block
        self._mb = cfg.max_len // block
        if n_blocks is None:
            # Monolithic-equivalent default: every lane can hold
            # max_len tokens.  The paged WIN comes from shrinking it.
            # Elastic engines size for the TOP tier — the slab never
            # resizes (rows and tables do), so the default must cover
            # the widest lane count a scale-up can reach.
            cap = max(int(t) for t in lane_tiers) if lane_tiers \
                else lanes
            n_blocks = cap * self._mb + 1
        self.n_blocks = int(n_blocks)
        self._alloc = BlockAllocator(self.n_blocks, block)
        # Per-lane block lists are built in _init_device_state (sized
        # to the STARTING lane count — elastic engines start at the
        # smallest tier and remap them on every resize).  Admission
        # bookkeeping keyed by lane: the warm frontier the
        # pad-redirect uses, and hashes awaiting their block's content
        # to be dispatched before they may be shared.
        self._lane_limit: dict[int, int] = {}
        self._pending_hashes: dict[int, list] = {}
        self._stems = PinnedStems()
        # Cumulative admission stem hits (blocks refcounted instead of
        # re-prefilled) — host-visible without an obs session; the
        # ``serving.stem_hit_blocks`` counter mirrors it into
        # /metrics.
        self.stem_hit_blocks = 0
        super().__init__(params, cfg, lanes=lanes,
                         temperature=temperature, top_k=top_k,
                         top_p=top_p, min_p=min_p, eos_token=eos_token,
                         exact_top_k=exact_top_k,
                         prompt_buckets=prompt_buckets,
                         kv_int8=bool(kv_int8),
                         per_request_sampling=per_request_sampling,
                         max_queue=max_queue, clock=clock,
                         lane_tiers=lane_tiers,
                         scale_up_after=scale_up_after,
                         scale_down_after=scale_down_after,
                         step_windows=step_windows,
                         prefill_chunk=prefill_chunk, plan=plan,
                         mesh=mesh)

    # ------------------------------------------------ storage layout

    def _fresh_cache(self, lanes: int):
        # The slab's capacity is n_blocks — independent of lane count
        # (that decoupling IS the feature).  init_cache with
        # batch=n_blocks, max_len=block is exactly the block layout,
        # scale leaves included.
        del lanes
        slab_cfg = dataclasses.replace(self.cfg, max_len=self.block)
        # _place_kv: pod-sharded engines shard the slab's kv-heads
        # dimension exactly like the monolithic cache (the block
        # layout ends [..., kv_heads, head_dim] too) — the per-lane
        # gather/scatter stays lane-and-position-local, so sharding
        # composes with paging for free.
        return self._place_kv(init_cache(slab_cfg, self.n_blocks,
                                         kv_int8=self.kv_int8))

    def _init_device_state(self, lanes: int) -> None:
        super()._init_device_state(lanes)
        self._lane_blocks: list[list[int]] = [[] for _ in range(lanes)]
        self._tables_np = np.zeros((lanes, self._mb), np.int32)
        self.tables = self._put_host(self._tables_np.copy())

    def _push_tables(self) -> None:
        # Authoritative copy is host-side numpy; the device array is
        # re-materialized on change (replicated over the mesh on
        # sharded engines).  An explicit copy: device_put may
        # alias host memory on CPU, and the host copy keeps mutating.
        self.tables = self._put_host(self._tables_np.copy())

    # ------------------------------------------------- elastic tiers

    def _make_resize(self):
        # Rows-only: the slab is lane-count-independent (that
        # decoupling IS the feature), so a tier move gathers just the
        # per-lane row metadata — no KV byte moves, and the page
        # tables remap host-side in _resize_state.
        def resize(cur, pos, keys, temps, tps, mps, idx):
            g = lambda a: jnp.take(a, idx, axis=0)
            return (g(cur), g(pos), g(keys), g(temps), g(tps), g(mps))

        return jax.jit(resize)

    def _warm_resize(self, frm: int, to: int) -> None:
        # The post-resize table push reuses _warm_steps' per-tier
        # [tier, _mb] device_put — nothing extra to warm here.
        _, cur, pos, keys, temps, tps, mps = self._tier_state(frm)
        self._resize(cur, pos, keys, temps, tps, mps,
                     jnp.zeros((to,), jnp.int32))

    def _resize_state(self, idx) -> None:
        idx = np.asarray(idx, np.int32)
        tier = int(idx.shape[0])
        (self.cur, self.pos, self.keys, self.temps, self.tps,
         self.mps) = self._resize(self.cur, self.pos, self.keys,
                                  self.temps, self.tps, self.mps, idx)
        # Host bookkeeping follows the same compaction _resize_to is
        # about to apply to _lane_state: occupied lanes move to the
        # low slots in index order; fresh lanes arrive with empty
        # block lists and all-TRASH page tables (their stale rows are
        # masked until admission overwrites them, the lane-reuse
        # contract).  Block refcounts are untouched — lanes keep their
        # blocks, only the lane ids naming them change.
        keep = [i for i, s in enumerate(self._lane_state)
                if s is not None]
        blocks: list[list[int]] = [[] for _ in range(tier)]
        tables = np.full((tier, self._mb), TRASH_BLOCK, np.int32)
        limits: dict[int, int] = {}
        pending: dict[int, list] = {}
        for j, i in enumerate(keep):
            blocks[j] = self._lane_blocks[i]
            tables[j] = self._tables_np[i]
            if i in self._lane_limit:
                limits[j] = self._lane_limit[i]
            if i in self._pending_hashes:
                pending[j] = self._pending_hashes[i]
        self._lane_blocks = blocks
        self._tables_np = tables
        self._lane_limit = limits
        self._pending_hashes = pending
        self._push_tables()

    # ---------------------------------------------- compiled programs

    def _make_step(self, n: int):
        one_step = self._one_step
        B, s_len = self.block, self.cfg.max_len
        constrain = self._kv_constraint

        def step_n(slab, tables, cur, pos, keys, temps, tps, mps):
            if constrain is not None:
                slab = constrain(slab)
            # Gather every lane's contiguous [max_len] view through its
            # page table, run the SHARED monolithic window body on it,
            # then scatter only the window's new K/V back to the slab.
            view = jax.tree.map(lambda a: _gather_view(a, tables), slab)

            def body(carry, _):
                view, cur, pos = carry
                view, cur, pos = one_step(view, cur, pos, keys, temps,
                                          tps, mps)
                return (view, cur, pos), cur

            (view, cur2, pos2), toks = jax.lax.scan(
                body, (view, cur, pos), None, length=n)
            # Positions this window wrote: pos..pos+n-1, clamped like
            # the body's own advance (duplicates at the clamp carry
            # identical final-view values, so scatter order is moot).
            q = jnp.minimum(pos[:, None] + jnp.arange(n)[None, :],
                            s_len - 1)                   # [lanes, n]
            blk = jnp.take_along_axis(tables, q // B, axis=1)
            off = q % B

            def write_back(s, vw):
                idx = q.reshape((1,) + q.shape
                                + (1,) * (vw.ndim - 3))
                vals = jnp.take_along_axis(vw, idx, axis=2)
                return s.at[:, blk, off].set(vals.astype(s.dtype))

            slab = jax.tree.map(write_back, slab, view)
            if constrain is not None:
                slab = constrain(slab)
            return slab, cur2, pos2, toks.T
        return jax.jit(step_n, donate_argnums=0)

    def _build_admission_programs(self) -> None:
        params, cfg, B = self.params, self.cfg, self.block
        constrain = self._kv_constraint

        def admit(slab, table_row, rows, start, limit):
            if constrain is not None:
                slab = constrain(slab)
            # One program per bucket width (start/limit traced): the
            # lane's view is gathered, the chunk runs the SAME
            # uniform-pos _decode_chunk as monolithic admission, and
            # the chunk span scatters back — pad positions past the
            # warm frontier ``limit`` redirect to the trash block, so
            # allocated blocks hold live tokens only.
            view = jax.tree.map(
                lambda a: _gather_view(a, table_row[None]), slab)
            _, view = _decode_chunk(
                params, view, rows,
                jnp.reshape(start, (1,)).astype(jnp.int32), cfg,
                uniform_pos=True)
            w = rows.shape[1]
            q = start + jnp.arange(w)
            blk = jnp.where(q < limit, table_row[q // B], TRASH_BLOCK)
            off = q % B

            def write_back(s, vw):
                seg = jax.lax.dynamic_slice_in_dim(vw, start, w,
                                                   axis=2)[:, 0]
                return s.at[:, blk, off].set(seg.astype(s.dtype))
            out = jax.tree.map(write_back, slab, view)
            return constrain(out) if constrain is not None else out

        self._admit = jax.jit(admit, donate_argnums=0)
        # The chunked-prefill continuation IS the same program (no
        # seed/continuation split: fresh blocks need no zeroing — a
        # vacated lane's table is reset to trash, and stale block
        # content is masked until overwritten, the same staleness
        # argument as monolithic lane reuse).
        self._admit_cont = None
        self._reseed = self._reseed_pool = None

        self._admit_prefill = None
        if self.kv_int8_prefill:
            def admit_prefill(slab, table_row, rows, limit):
                # Prefill-built int8 admission (round-12 satellite):
                # the batched prefill forward attends the chunk in
                # FULL precision and quantizes once at the end —
                # cheaper than the masked full-cache chunk for a
                # from-scratch prompt, at a bounded parity cost
                # (pinned by test_kv_int8_prefill_tolerance).
                cache, _ = prefill(params, rows, cfg,
                                   last_logits=False, kv_int8=True)
                w = rows.shape[1]
                q = jnp.arange(w)
                blk = jnp.where(q < limit, table_row[q // B],
                                TRASH_BLOCK)
                off = q % B

                def write_back(s, c):
                    return s.at[:, blk, off].set(
                        c[:, 0, :w].astype(s.dtype))
                out = jax.tree.map(write_back, slab, cache)
                return (constrain(out) if constrain is not None
                        else out)
            self._admit_prefill = jax.jit(admit_prefill,
                                          donate_argnums=0)

        def copy_block(slab, src, dst):
            # The CoW fork's divergent-tail copy: O(block) bytes, the
            # whole point vs copying a max_len lane cache.
            out = jax.tree.map(
                lambda a: jax.lax.dynamic_update_slice_in_dim(
                    a, jax.lax.dynamic_slice_in_dim(a, src, 1, axis=1),
                    dst, axis=1),
                slab)
            return constrain(out) if constrain is not None else out
        self._copy_block = jax.jit(copy_block, donate_argnums=0)

        def extract_block(slab, src):
            # Disagg export (round 17): read ONE block off the slab —
            # all layers, scale leaves included.  No donation: the
            # slab keeps serving.
            return jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, src, 1,
                                                       axis=1),
                slab)
        self._extract_block = jax.jit(extract_block)

        def adopt_block(slab, blk, dst):
            # Disagg import: splice a shipped block's content into the
            # slab at ``dst`` — the write half of _copy_block with the
            # source coming off the wire instead of the slab.
            out = jax.tree.map(
                lambda a, b: jax.lax.dynamic_update_slice_in_dim(
                    a, b.astype(a.dtype), dst, axis=1),
                slab, blk)
            return constrain(out) if constrain is not None else out
        self._adopt_block = jax.jit(adopt_block, donate_argnums=0)

        def fork_rows(cur, pos, keys, temps, tps, mps, src, dst,
                      token):
            g = lambda x: x.at[dst].set(x[src])
            return (cur.at[dst].set(token), g(pos), g(keys), g(temps),
                    g(tps), g(mps))
        self._fork_rows = jax.jit(fork_rows)

        def fork_rows_key(cur, pos, keys, temps, tps, mps, src, dst,
                          token, key):
            g = lambda x: x.at[dst].set(x[src])
            return (cur.at[dst].set(token), g(pos),
                    keys.at[dst].set(key), g(temps), g(tps), g(mps))
        self._fork_rows_key = jax.jit(fork_rows_key)

    # ------------------------------------------------------- warm-up

    def _warm_steps(self, tier: int) -> None:
        for n in self._step_windows:
            if n not in self._steps:
                self._steps[n] = self._make_step(n)
        tabs = self._put_host(np.zeros((tier, self._mb), np.int32))
        for n in self._step_windows:
            cache, cur, pos, keys, temps, tps, mps = \
                self._tier_state(tier)
            self._steps[n](cache, tabs, cur, pos, keys, temps, tps,
                           mps)

    def _warm_admission(self, tier: int) -> None:
        row = self._put_host(np.zeros((self._mb,), np.int32))
        for width in self._buckets:
            rows = jnp.zeros((1, width), jnp.int32)
            self._admit(self._fresh_cache(tier), row, rows,
                        jnp.int32(0), jnp.int32(0))
            if self._admit_prefill is not None:
                self._admit_prefill(self._fresh_cache(tier), row, rows,
                                    jnp.int32(0))
        # CoW programs (block copy + row fork, keyed variant too).
        self._copy_block(self._fresh_cache(tier), jnp.int32(0),
                         jnp.int32(0))
        # Disagg block-transfer programs (export read + import
        # splice): warm with a template block placed exactly like a
        # live import places wire payloads, so adoption never
        # compiles (the ``serving_disagg`` session pins it).
        self._extract_block(self._fresh_cache(tier), jnp.int32(0))
        self._adopt_block(self._fresh_cache(tier),
                          self._place_kv(self._block_template()),
                          jnp.int32(0))
        cache, cur, pos, keys, temps, tps, mps = self._tier_state(tier)
        z = jnp.int32(0)
        self._fork_rows(cur, pos, keys, temps, tps, mps, z, z, z)
        if self._keyed:
            self._fork_rows_key(cur, pos, keys, temps, tps, mps, z, z,
                                z, jax.random.key(0))

    # ----------------------------------------------------- admission

    def _stage_blocks(self, tokens, warm: int):
        """The ONE stem-share + allocate staging path (admission AND
        pin_prefix — duplicating it is how rollback bugs breed):
        chain-hash the full blocks of ``tokens[:warm]``, refcount the
        longest resident hashed prefix, resolve the chunk plan for the
        remainder, and allocate fresh blocks for it.  Returns
        ``(blocks, shared, hashes, plan)``, or None when the allocator
        is exhausted — with every reference this attempt took rolled
        back either way on failure.

        A resident stem hit must never make a valid request
        UNPLANNABLE: if no admission bucket fits the unshared span at
        the skip offset, shared blocks are handed back (longest prefix
        first shrinking from the end) until the plan fits — skip=0 was
        already validated by ``_validate_budget``."""
        B = self.block
        full = warm // B
        hashes, digest = [], b""
        for k in range(full):
            digest = _chain_hash(digest, tokens[k * B:(k + 1) * B])
            hashes.append(digest)
        shared_blocks = []
        for h in hashes:
            bid = self._alloc.share_by_hash(h)
            if bid is None:
                break
            shared_blocks.append(bid)
        while shared_blocks:
            try:
                plan = self._chunk_plan(0, warm,
                                        skip=len(shared_blocks) * B)
                break
            except ValueError:
                # No bucket fits the span at this offset: give back
                # the last shared block and retry with a smaller skip.
                self._alloc.free(shared_blocks.pop())
        else:
            plan = self._chunk_plan(0, warm)
        shared = len(shared_blocks)
        need = (-(-warm // B) - shared) if warm else 0
        fresh = []
        for _ in range(need):
            bid = self._alloc.alloc()
            if bid is None:
                # Exhausted: no half-staged lane, no leak.
                for b in fresh:
                    self._alloc.free(b)
                for b in shared_blocks:
                    self._alloc.free(b)
                return None
            fresh.append(bid)
        return shared_blocks + fresh, shared, hashes, plan

    def _admission_plan(self, lane, prompt, off: int, warm: int):
        assert off == 0, "paged engines carry no engine-level prefix"
        staged = self._stage_blocks(prompt, warm)
        if staged is None:
            # DECLINE — the caller surfaces kv_blocks backpressure.
            return None
        blocks, shared, hashes, plan = staged
        self._lane_blocks[lane] = blocks
        self._lane_limit[lane] = warm
        # Fresh full blocks become shareable only once their content
        # has been dispatched (_register_written) — chunked prefill
        # lands over several steps and an unwritten block must never
        # hash-hit.
        self._pending_hashes[lane] = [(k, hashes[k])
                                      for k in range(shared,
                                                     warm // self.block)]
        row = self._tables_np[lane]
        row[:] = TRASH_BLOCK
        row[:len(blocks)] = blocks
        self._push_tables()
        if shared:
            self.stem_hit_blocks += shared
            obs.count("serving.stem_hit_blocks", shared)
            obs.event("serving.stem_hit", lane=lane,
                      shared_blocks=shared,
                      shared_tokens=shared * self.block)
        self._obs_blocks()
        return plan

    def _abort_admission(self, lane) -> None:
        if self._lane_state[lane] is not None:
            return  # committed; the failure happened later
        for bid in self._lane_blocks[lane]:
            self._alloc.free(bid)
        self._lane_blocks[lane] = []
        self._pending_hashes.pop(lane, None)
        self._lane_limit.pop(lane, None)
        self._tables_np[lane, :] = TRASH_BLOCK
        self._push_tables()

    def _exec_admit(self, lane, start, rows, slot) -> None:
        assert slot is None  # no prefix pool on paged engines
        self._exec_chunk(lane, start, rows)

    def _exec_chunk(self, lane, start, rows) -> None:
        limit = self._lane_limit[lane]
        row = self._put_host(self._tables_np[lane].copy())
        w = rows.shape[1]
        if (self._admit_prefill is not None and start == 0
                and w >= limit):
            # From-scratch single-chunk admission under
            # kv_int8="prefill": the batched prefill forward.  Chunked
            # continuations and stem-shared tails keep the decode-built
            # path (they must attend PRIOR cache, which prefill
            # cannot).
            self.cache = self._admit_prefill(
                self.cache, row, jnp.asarray(rows), jnp.int32(limit))
        else:
            self.cache = self._admit(
                self.cache, row, jnp.asarray(rows), jnp.int32(start),
                jnp.int32(limit))
        self._register_written(lane, min(start + w, limit))

    def _register_written(self, lane, end: int) -> None:
        pend = self._pending_hashes.get(lane)
        if not pend:
            return
        blocks = self._lane_blocks[lane]
        keep = []
        for k, h in pend:
            if (k + 1) * self.block <= end:
                self._alloc.register(blocks[k], h)
            else:
                keep.append((k, h))
        self._pending_hashes[lane] = keep

    # -------------------------------------------------- decode growth

    def _dispatch_step(self, n: int):
        self._ensure_growth(n)
        if n not in self._steps:
            self._steps[n] = self._make_step(n)
        self.cache, self.cur, self.pos, toks = self._steps[n](
            self.cache, self.tables, self.cur, self.pos, self.keys,
            self.temps, self.tps, self.mps)
        return np.asarray(toks)

    def _ensure_growth(self, n: int) -> None:
        """Allocate the blocks this window's writes need, per live
        lane — memory tracks live tokens.  A lane the allocator cannot
        grow is evicted with a structured ``"error"`` result; its
        private blocks return to the free list immediately (possibly
        unblocking the remaining lanes), shared blocks survive."""
        changed = False
        for lane, st in enumerate(self._lane_state):
            if st is None or st.done or st.chunks is not None:
                continue
            pos = st.off + len(st.tokens) - 1
            # The last K/V write this REQUEST can ever need: its final
            # emitted token is never processed, so the frontier stops
            # at prompt + max_new - 2.  Window positions past it (or
            # past max_len) are discarded garbage that redirects to
            # trash — allocating for them would turn step-window
            # roundup into spurious OOM evictions.
            last = min(pos + n - 1, self.cfg.max_len - 1,
                       st.off + st.prompt_len + st.max_new - 2)
            hi = last // self.block
            blocks = self._lane_blocks[lane]
            while len(blocks) <= hi:
                bid = self._alloc.alloc()
                if bid is None:
                    obs.count("serving.kv_oom_evictions")
                    obs.event("serving.kv_oom_evict", lane=lane,
                              request_id=st.request_id,
                              live_tokens=len(st.tokens))
                    self._finish(
                        st.request_id, st.tokens, "error",
                        st.prompt_len,
                        error="KV block allocator exhausted mid-"
                              "growth: raise n_blocks, lower lane "
                              "count, or bound request budgets",
                        born=st.born)
                    self._vacate(lane)
                    break
                blocks.append(bid)
                self._tables_np[lane, len(blocks) - 1] = bid
                changed = True
        if changed:
            self._push_tables()
            self._obs_blocks()

    def _release_lane_storage(self, lane, st) -> None:
        del st
        for bid in self._lane_blocks[lane]:
            self._alloc.free(bid)
        self._lane_blocks[lane] = []
        self._pending_hashes.pop(lane, None)
        self._lane_limit.pop(lane, None)
        self._tables_np[lane, :] = TRASH_BLOCK
        self._push_tables()
        self._obs_blocks()

    # -------------------------------------------------- CoW forking

    def fork(self, lane: int, token: int, key=None):
        """Copy-on-write fork of a live lane into a free lane; returns
        the new lane id, or None under backpressure (no free lane /
        no free block).

        The fork diverges at the source's CURRENT position: its
        transcript is the source's with the LAST token replaced by
        ``token`` (pass ``st.tokens[-1]`` back for an exact replica —
        the speculative checkpoint/rollback shape; pass the runner-up
        token for a beam branch).  Full blocks below the write
        frontier are refcount-shared; only the partially-written tail
        block is copied (O(block) device bytes — vs O(max_len) for a
        monolithic cache fork).  ``key`` replaces the per-request PRNG
        key on sampling engines (a fork replaying its source's key
        and positions would replay its draws).

        The forked lane is a bare-submit-style occupant: poll it with
        ``running()`` and collect with ``drain()``.  Rejected on
        elastic (``lane_tiers=``) engines: a tier resize compacts
        lane ids, so the id this returns could silently dangle.
        """
        if self.lane_tiers is not None:
            raise ValueError(
                "fork() is not available on elastic (lane_tiers=) "
                "paged engines: a tier resize compacts lane ids, so "
                "the lane id fork returns could silently dangle — "
                "use a fixed lanes= engine to fork")
        with self._admission_lock:
            self._check_open()
            st = self._lane_state[lane]
            if st is None:
                raise ValueError(f"lane {lane} is empty")
            if st.chunks is not None:
                raise ValueError(
                    f"lane {lane} is still admitting (fork after its "
                    "prefill chunks land)")
            if st.done:
                raise ValueError(
                    f"lane {lane} already finished; drain it instead")
            token = int(token)
            if not 0 <= token < self.cfg.vocab_size:
                raise ValueError(
                    f"fork token {token} outside vocab "
                    f"[0, {self.cfg.vocab_size})")
            if key is not None and not self._keyed:
                raise ValueError(
                    "fork key= needs a sampling engine (greedy "
                    "engines carry no per-lane keys)")
            free = self.free_lanes()
            if not free:
                self._decline_full()
                return None
            dst = free[0]
            frontier = st.off + len(st.tokens) - 1  # written slots
            j = frontier // self.block
            src_blocks = self._lane_blocks[lane]
            shared = src_blocks[:min(j, len(src_blocks))]
            for bid in shared:
                self._alloc.share(bid)
            new_blocks = list(shared)
            if frontier % self.block and j < len(src_blocks):
                # Divergent tail: both lanes will write into block j's
                # position range — copy it for the fork.
                bid = self._alloc.alloc()
                if bid is None:
                    for b in shared:
                        self._alloc.free(b)
                    self._decline("kv_blocks")
                    return None
                try:
                    self.cache = self._copy_block(
                        self.cache, jnp.int32(src_blocks[j]),
                        jnp.int32(bid))
                except Exception:
                    # The fresh block and the refcount bumps are not
                    # yet reachable from any table row — roll them
                    # back or they leak for the engine's lifetime.
                    self._alloc.free(bid)
                    for b in shared:
                        self._alloc.free(b)
                    raise
                new_blocks.append(bid)
            self._lane_blocks[dst] = new_blocks
            row = self._tables_np[dst]
            row[:] = TRASH_BLOCK
            row[:len(new_blocks)] = new_blocks
            self._push_tables()
            args = (self.cur, self.pos, self.keys, self.temps,
                    self.tps, self.mps, jnp.int32(lane),
                    jnp.int32(dst), jnp.int32(token))
            if key is not None:
                out = self._fork_rows_key(*args, key)
            else:
                out = self._fork_rows(*args)
            (self.cur, self.pos, self.keys, self.temps, self.tps,
             self.mps) = out
            rid = self._next_id
            self._next_id += 1
            self._lane_state[dst] = _Lane(
                request_id=rid, prompt_len=st.prompt_len,
                max_new=st.max_new,
                key=key if key is not None else st.key,
                tokens=st.tokens[:-1] + [token], eos=st.eos,
                deadline=st.deadline, born=self._clock(), off=st.off)
            self.last_request_id = rid
            obs.count("serving.cow_forks")
            obs.event("serving.fork", src=lane, dst=dst,
                      request_id=rid, shared_blocks=len(shared),
                      copied_blocks=len(new_blocks) - len(shared))
            self._obs_blocks()
            return dst

    # ------------------------------------- disaggregated block transfer

    def _block_template(self):
        """Zero tree shaped like ONE slab block (``[L, 1, block, ...]``
        per leaf) — the adopt program's wire-side operand aval."""
        slab_cfg = dataclasses.replace(self.cfg, max_len=self.block)
        return init_cache(slab_cfg, 1, kv_int8=self.kv_int8)

    def export_blocks(self, tokens) -> BlockShipment:
        """Prefill ``tokens``' full blocks and read them off the slab
        into a host-side :class:`BlockShipment` — the prefill half of
        disaggregated serving (round 17).

        Staging goes through :meth:`pin_prefix` (the ONE share+alloc
        path): resident stems are reused, only the cold remainder
        prefills.  The pin is released before returning — the
        shipment owns host copies, and the blocks stay hash-resident
        locally until the free list recycles them, so back-to-back
        exports of a common stem prefill once.  Raises ``ValueError``
        for spans below one block and ``RuntimeError`` when the
        allocator cannot hold the run (the router's fallback
        signals).
        """
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        pid = self.pin_prefix(tokens)
        try:
            with self._admission_lock:
                blocks = self._stems.blocks_of(pid)
                span = self._stems.length_of(pid)
                hashes = _stem_hashes(tokens[:span], self.block)
                runs = []
                for bid in blocks:
                    blk = self._extract_block(self.cache,
                                              jnp.int32(bid))
                    runs.append(tuple(np.asarray(a) for a in
                                      jax.tree.leaves(blk)))
        finally:
            self.unpin_prefix(pid)
        ship = BlockShipment(block=self.block, hashes=tuple(hashes),
                             blocks=tuple(runs))
        obs.count("serving.disagg.blocks_out", len(ship))
        obs.count("serving.disagg.bytes_out", ship.nbytes)
        obs.event("serving.block_export", blocks=len(ship),
                  bytes=ship.nbytes, span=span)
        return ship

    def import_blocks(self, shipment: BlockShipment) -> dict | None:
        """Adopt a shipped block run by page-table splice and PIN it
        (refcount held through :class:`PinnedStems`, exactly like
        :meth:`pin_prefix`) — the decode half of disaggregated
        serving.

        Blocks whose chain digest is already resident are refcounted
        in place — zero device writes for warm stems (the
        adoption-hit counter the router's transfer-skip leans on);
        cold blocks are allocated, spliced in by the pre-compiled
        adopt program, and hash-registered so later prompts (and
        re-imports) hit them.

        Returns ``{"prefix_id", "blocks", "hits", "bytes"}`` — the
        caller owns the pin and MUST :meth:`unpin_prefix` it when the
        consuming request goes terminal — or ``None`` when the
        allocator cannot hold the run (backpressure, never an
        exception: the router falls back to routing the raw prompt).
        Any failure mid-adopt hands back every reference this import
        took — a torn transfer leaks nothing (the chaos contract).
        """
        with self._admission_lock:
            self._check_open()
            if shipment.block != self.block:
                raise ValueError(
                    f"shipment carries {shipment.block}-token blocks; "
                    f"this slab is paged at {self.block}")
            if not len(shipment):
                raise ValueError("refusing to adopt an empty shipment")
            if shipment.span > self.cfg.max_len - 2:
                raise ValueError(
                    f"shipment spans {shipment.span} tokens; pinned "
                    f"runs must leave room for a tail token and one "
                    f"generated token under max_len={self.cfg.max_len}")
            slab_leaves = jax.tree.leaves(self.cache)
            treedef = jax.tree.structure(self.cache)
            taken: list[int] = []
            hits = 0
            try:
                for h, leaves in zip(shipment.hashes,
                                     shipment.blocks):
                    bid = self._alloc.share_by_hash(h)
                    if bid is not None:
                        # Content already resident: refcount, no
                        # device write.
                        taken.append(bid)
                        hits += 1
                        continue
                    if len(leaves) != len(slab_leaves):
                        raise ValueError(
                            f"shipment blocks carry {len(leaves)} "
                            f"leaves; this slab has "
                            f"{len(slab_leaves)}")
                    for a, s in zip(leaves, slab_leaves):
                        want = (s.shape[0], 1) + tuple(s.shape[2:])
                        if (tuple(a.shape) != want
                                or a.dtype != s.dtype):
                            raise ValueError(
                                f"shipment leaf {a.shape}/{a.dtype} "
                                f"does not match slab block "
                                f"{want}/{s.dtype} (model config or "
                                "kv_int8 mode mismatch)")
                    bid = self._alloc.alloc()
                    if bid is None:
                        for b in taken:
                            self._alloc.free(b)
                        obs.count("serving.disagg.import_declines")
                        return None
                    taken.append(bid)
                    blk = self._place_kv(
                        jax.tree.unflatten(treedef, list(leaves)))
                    self.cache = self._adopt_block(self.cache, blk,
                                                   jnp.int32(bid))
                    self._alloc.register(bid, h)
                pid = self._stems.add(taken, shipment.span)
            except Exception:
                for b in taken:
                    self._alloc.free(b)
                raise
            obs.count("serving.disagg.blocks_in", len(taken))
            obs.count("serving.disagg.adopt_hits", hits)
            obs.count("serving.disagg.bytes_in", shipment.nbytes)
            obs.event("serving.block_import", prefix_id=pid,
                      blocks=len(taken), hits=hits,
                      bytes=shipment.nbytes)
            self._obs_blocks()
            return {"prefix_id": pid, "blocks": len(taken),
                    "hits": hits, "bytes": shipment.nbytes}

    # ------------------------------------------------ pinned prefixes

    def pin_prefix(self, tokens) -> int:
        """Prefill ``tokens``' full blocks into the slab and PIN them
        (refcount held by the registry): the prefix-pool story on the
        one allocator.  Any later prompt starting with those tokens
        hash-hits the blocks through ordinary stem sharing — zero
        prefill work for the pinned span, no id plumbing at submit.
        The prefix length rounds DOWN to a block multiple (the
        partial tail block would be mutable, so it can't be shared);
        returns the ``prefix_id`` for :meth:`unpin_prefix`.  Raises
        ``RuntimeError`` when the allocator cannot hold the run
        (operator-paced — no silent shed)."""
        with self._admission_lock:
            self._check_open()
            tokens = np.asarray(tokens, np.int32).reshape(-1)
            B = self.block
            span = (tokens.size // B) * B
            if span < B:
                raise ValueError(
                    f"a pinned prefix needs at least one full block "
                    f"({B} tokens); got {tokens.size}")
            if span > self.cfg.max_len - 2:
                raise ValueError(
                    f"pinned prefix of {span} tokens must leave room "
                    f"for a tail token and one generated token under "
                    f"max_len={self.cfg.max_len}")
            full = span // B
            staged = self._stage_blocks(tokens, span)
            if staged is None:
                raise RuntimeError(
                    "no free KV blocks to pin the prefix; grow "
                    "n_blocks, or drain/unpin first")
            blocks, shared, hashes, plan = staged
            try:
                if shared < full:
                    row = np.full((self._mb,), TRASH_BLOCK, np.int32)
                    row[:len(blocks)] = blocks
                    row_j = self._put_host(row)
                    # _chunk_rows reads warm = prompt.size - 1 tokens;
                    # the pseudo prompt makes the pinned span exactly
                    # the warm region.
                    pseudo = np.zeros((span + 1,), np.int32)
                    pseudo[:span] = tokens[:span]
                    for start, w in plan:
                        rows = jnp.asarray(
                            self._chunk_rows(pseudo, 0, start, w))
                        if (self._admit_prefill is not None
                                and start == 0 and len(plan) == 1):
                            # Same mode choice as request admission: a
                            # from-scratch single chunk may
                            # prefill-build.
                            self.cache = self._admit_prefill(
                                self.cache, row_j, rows,
                                jnp.int32(span))
                        else:
                            self.cache = self._admit(
                                self.cache, row_j, rows,
                                jnp.int32(start), jnp.int32(span))
                    for k in range(shared, full):
                        self._alloc.register(blocks[k], hashes[k])
                pid = self._stems.add(blocks, span)
            except Exception:
                # A failure after staging (a dispatch fault, a chaos
                # probe) must hand every staged reference back — the
                # pin was never published, so a leak here would shrink
                # the slab forever.
                for b in blocks:
                    self._alloc.free(b)
                raise
            obs.event("serving.pin_prefix", prefix_id=pid,
                      length=span, shared_blocks=shared)
            self._obs_blocks()
            return pid

    def unpin_prefix(self, prefix_id: int) -> None:
        """Release a pinned prefix's block references.  In-flight
        lanes sharing the blocks keep their own references; the
        blocks stay hash-resident until the free list recycles them,
        so recently-unpinned prefixes may still hit."""
        with self._admission_lock:
            for bid in self._stems.pop(prefix_id):
                self._alloc.free(bid)
            self._obs_blocks()

    def residency(self) -> dict:
        """The paged residency digest: the base load/pool fields plus
        the slab geometry and every resident stem hash (hex, JSON-
        safe) — the ground truth a cache-aware router's affinity
        table is built from, matching
        :func:`distkeras_tpu.serving.residency.stem_hexes` digests by
        construction (one chain-hash definition)."""
        out = super().residency()
        out["block"] = self.block
        out["stem_hashes"] = [h.hex()
                              for h in self._alloc.resident_hashes()]
        out["prefix_ids"] = self._stems.ids()
        out["kv_blocks_free"] = self._alloc.stats()["free"]
        return out

    @property
    def pinned(self) -> PinnedStems:
        return self._stems

    @property
    def allocator(self) -> BlockAllocator:
        return self._alloc

    # -------------------------------------------------------- obs

    def _obs_blocks(self) -> None:
        if obs.active() is None:
            return
        st = self._alloc.stats()
        obs.gauge("serving.kv_blocks_used", st["used"])
        obs.gauge("serving.kv_blocks_free", st["free"])
        obs.gauge("serving.kv_shared_blocks", st["shared"])

    # ---------------------------------------------------- analysis

    def traced_for_analysis(self):
        """Trace targets for the IR lint: the paged decode step (page-
        table gather + the shared window body + slab scatter), the
        paged admission program at the smallest bucket, and the
        round-17 disaggregated block-transfer pair — the export read
        (one block off the slab, no donation: the slab keeps serving)
        and the import splice (the decode-side adoption write, shaped
        exactly like a wire payload placement)."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        if 1 not in self._steps:
            self._steps[1] = self._make_step(1)
        mode = ("per_request" if self.per_request_sampling
                else "sampled" if self.temperature > 0 else "greedy")
        if self._kv_axis is not None:
            mode += f"_tp{int(self.mesh.shape[self._kv_axis])}"
        rows = jnp.zeros((1, self._buckets[0]), jnp.int32)
        row = self._put_host(np.zeros((self._mb,), np.int32))
        return [
            TraceSpec(
                name=f"pagedbatcher_{mode}/decode_step",
                fn=self._steps[1],
                args=(self.cache, self.tables, self.cur, self.pos,
                      self.keys, self.temps, self.tps, self.mps),
                donate_argnums=(0,)),
            TraceSpec(
                name=f"pagedbatcher_{mode}/admit_b{self._buckets[0]}",
                fn=self._admit,
                args=(self.cache, row, rows, jnp.int32(0),
                      jnp.int32(0)),
                donate_argnums=(0,)),
            TraceSpec(
                name=f"pagedbatcher_{mode}/disagg_extract",
                fn=self._extract_block,
                args=(self.cache, jnp.int32(0))),
            TraceSpec(
                name=f"pagedbatcher_{mode}/disagg_adopt",
                fn=self._adopt_block,
                args=(self.cache,
                      self._place_kv(self._block_template()),
                      jnp.int32(0)),
                donate_argnums=(0,)),
        ]


__all__ = ["PagedBatcher", "BlockAllocator", "TRASH_BLOCK",
           "KV_INT8_PREFILL_LOGIT_TOL"]
