"""Seeded deterministic trace-replay load driver for the serving fleet.

The autoscaling control plane (:mod:`~distkeras_tpu.serving.autoscale`)
is a feedback loop, and a feedback loop is only testable against a
load signal that is *reproducible*: the same trace must produce the
same queue build-up, the same breach timing, and therefore the same
scaling decisions on every run.  This module is that signal — a
:class:`TraceReplay` whose request schedule is a **pure function of
``(seed, tick)``** under a virtual clock, the same determinism
contract as the async tier's
:class:`~distkeras_tpu.parallel.async_tier.AsyncSchedule` (independent
``SeedSequence`` draws per tick, so ticks can be generated in any
order and two runs are bit-identical).

Four trace shapes, each one axis of the autoscaler's job:

==============  =====================================================
shape           offered load per tick
==============  =====================================================
``diurnal``     a smooth ramp ``base -> peak -> base`` over
                ``period`` ticks (``sin(pi * t / period)``) — the
                slow swing scale-up/scale-down must track without
                thrashing.
``spike``       flat ``base_rate`` except a flash window
                ``[spike_at, spike_at + spike_len)`` at
                ``spike_rate`` — the event a warm pool exists for.
``shuffle``     flat ``base_rate`` with **stem locality destroyed**:
                every request gets a unique stem, so the affinity
                table buys nothing and routing degenerates to
                least-loaded (the adversarial floor for cache-aware
                fleets).
``tenant_mix``  flat ``base_rate`` split across weighted tenants —
                the multi-tenant fairness axis (per-tenant request
                counters let a report attribute load).
==============  =====================================================

Requests are (tenant, stem, tail) triples: ``stem`` indexes a small
shared stem pool (the locality handle — repeated stems are what the
router's affinity table keys on), ``tail`` is unique per request, and
:meth:`TraceReplay.prompt` expands the triple into deterministic
tokens.  :meth:`TraceReplay.replay` additionally emits the
``traffic.offered`` gauge and ``traffic.requests`` counter so bench
rows and the chaos harness carry an auditable offered-load record.

Guaranteed jax-free (source lint ledger): trace generation is host
arithmetic — a load driver must never compile a program.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from distkeras_tpu import obs

TRACE_SHAPES = ("diurnal", "spike", "shuffle", "tenant_mix")

# Independent SeedSequence lanes: shape-id keys the per-tick arrival
# stream, the STEM/TAIL keys derive prompt tokens — disjoint from the
# arrival lane so reading a prompt never perturbs the schedule.
_SHAPE_IDS = {s: i for i, s in enumerate(TRACE_SHAPES)}
_STEM_KEY = 101
_TAIL_KEY = 202
# Unique-id span per tick: tails (and shuffle stems) are
# ``tick * _TAIL_SPAN + index`` — collision-free for any tick, no RNG
# involved, so uniqueness survives reordering.
_TAIL_SPAN = 1 << 20


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One scheduled arrival: ``tick``/``index`` place it in the
    trace, ``tenant`` labels it, ``stem`` is the shared-prefix handle
    (equal stems -> equal warm prompt -> an affinity hit), ``tail``
    is unique per request, ``max_new`` the decode budget."""

    tick: int
    index: int
    tenant: str
    stem: int
    tail: int
    max_new: int


class TraceReplay:
    """The deterministic trace (module docstring has the shapes).

    ``tenants`` is ``((name, weight), ...)``; weights are normalized.
    ``max_new`` is an inclusive ``(lo, hi)`` decode-budget range.
    ``stems`` sizes the shared stem pool (ignored by ``shuffle``,
    which makes every stem unique on purpose).
    """

    def __init__(self, shape: str, seed: int = 0, *,
                 base_rate: float = 2.0, peak_rate: float = 8.0,
                 period: int = 64, spike_at: int = 16,
                 spike_len: int = 8, spike_rate: float = 12.0,
                 stems: int = 4, tenants=(("t0", 1.0),),
                 max_new=(4, 8)):
        if shape not in TRACE_SHAPES:
            raise ValueError(
                f"shape must be one of {TRACE_SHAPES}, got {shape!r}")
        if base_rate <= 0 or peak_rate <= 0 or spike_rate <= 0:
            raise ValueError("rates must be > 0")
        if period < 2:
            raise ValueError(f"period must be >= 2, got {period}")
        if spike_len < 1:
            raise ValueError(f"spike_len must be >= 1, got {spike_len}")
        if stems < 1:
            raise ValueError(f"stems must be >= 1, got {stems}")
        if not tenants:
            raise ValueError("need at least one tenant")
        lo, hi = int(max_new[0]), int(max_new[1])
        if not 1 <= lo <= hi:
            raise ValueError(f"max_new must be 1 <= lo <= hi, "
                             f"got ({lo}, {hi})")
        self.shape = shape
        self.seed = int(seed)
        self.base_rate = float(base_rate)
        self.peak_rate = float(peak_rate)
        self.period = int(period)
        self.spike_at = int(spike_at)
        self.spike_len = int(spike_len)
        self.spike_rate = float(spike_rate)
        self.stems = int(stems)
        self.tenant_names = tuple(str(n) for n, _ in tenants)
        w = np.asarray([float(x) for _, x in tenants], float)
        if (w <= 0).any():
            raise ValueError("tenant weights must be > 0")
        self.tenant_weights = w / w.sum()
        self.max_new_range = (lo, hi)

    # ------------------------------------------------------------ shape

    def rate(self, tick: int) -> float:
        """Offered requests per tick — deterministic arithmetic, no
        RNG (the trace's mean-load envelope)."""
        t = int(tick)
        if self.shape == "diurnal":
            phase = (t % self.period) / self.period
            return self.base_rate + (self.peak_rate - self.base_rate) \
                * math.sin(math.pi * phase)
        if self.shape == "spike":
            if self.spike_at <= t < self.spike_at + self.spike_len:
                return self.spike_rate
            return self.base_rate
        return self.base_rate  # shuffle / tenant_mix: flat

    # --------------------------------------------------------- schedule

    def requests_at(self, tick: int) -> tuple[TraceRequest, ...]:
        """The tick's arrivals — a pure function of ``(seed, shape,
        tick)`` via an independent ``SeedSequence`` per tick (the
        AsyncSchedule contract: any tick, any order, bit-identical
        across runs)."""
        t = int(tick)
        if t < 0:
            raise ValueError(f"tick must be >= 0, got {tick}")
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, _SHAPE_IDS[self.shape], t]))
        n = int(rng.poisson(self.rate(t)))
        lo, hi = self.max_new_range
        out = []
        for i in range(n):
            tenant = self.tenant_names[int(rng.choice(
                len(self.tenant_names), p=self.tenant_weights))]
            stem = int(rng.integers(self.stems))
            if self.shape == "shuffle":
                # Adversarial: a unique stem per request means no two
                # prompts share a warm prefix — affinity scores 0
                # everywhere and the cache-aware policy degenerates
                # to least-loaded.
                stem = self.stems + t * _TAIL_SPAN + i
            out.append(TraceRequest(
                tick=t, index=i, tenant=tenant, stem=stem,
                tail=t * _TAIL_SPAN + i,
                max_new=int(rng.integers(lo, hi + 1))))
        return tuple(out)

    def replay(self, tick: int) -> tuple[TraceRequest, ...]:
        """:meth:`requests_at` plus the audit-trail emissions: the
        per-tick ``traffic.offered`` gauge and one
        ``traffic.requests`` increment per arrival."""
        reqs = self.requests_at(tick)
        obs.gauge("traffic.offered", float(len(reqs)),
                  shape=self.shape)
        for r in reqs:
            obs.count("traffic.requests", shape=self.shape,
                      tenant=r.tenant)
        return reqs

    # ---------------------------------------------------------- prompts

    def prompt(self, req: TraceRequest, *, stem_len: int = 8,
               tail_len: int = 2, vocab: int = 64) -> np.ndarray:
        """Expand a request into prompt tokens: ``stem_len`` tokens
        derived from ``req.stem`` (equal stems -> identical warm
        prefix) plus ``tail_len`` unique tokens from ``req.tail``.
        Deterministic and independent of the arrival stream."""
        if stem_len < 1 or tail_len < 1:
            raise ValueError("stem_len and tail_len must be >= 1")
        if vocab < 2:
            raise ValueError(f"vocab must be >= 2, got {vocab}")
        stem_rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, _STEM_KEY, int(req.stem)]))
        tail_rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, _TAIL_KEY, int(req.tail)]))
        return np.concatenate([
            stem_rng.integers(0, vocab, size=stem_len),
            tail_rng.integers(0, vocab, size=tail_len),
        ]).astype(np.int32)


__all__ = ["TraceReplay", "TraceRequest", "TRACE_SHAPES"]
