"""SLO-gated canary rollout of published weight versions.

The policy half of the train→serve loop (``serving/publish.py`` is the
transport): a :class:`CanaryController` pushes version N+1 to a small
canary subset of the router's fleet, gates on a pinned-prompt
logit-drift probe plus the per-replica SLO state the router already
tracks (the ``breach_demoter``'s ``degraded`` flag over live SLO
windows), and then either promotes the version fleet-wide or rolls the
canaries back to version N — **rollback is the first-class path**: it
is exactly a ``swap_params(old, allow_downgrade=True)`` per canary,
exercised by the ``canary_bad_push`` chaos leg (drift probe trips →
automatic rollback, zero lost requests) and by ``train_kill_push``
(trainer SIGKILLed mid-publish → the torn snapshot is never even
offered to a canary).

Both the promote and the rollback commit under a bumped router
membership epoch (:meth:`Router.bump_epoch`): a weight push changes
what the fleet serves, so route state made under the old version set
is re-stamped the same way a drain re-stamps it.

The drift probe is ONE jitted program compiled at construction —
``max |logits_new - logits_old|`` over a pinned prompt, NaN mapped to
+inf so a poisoned push (the classic silent-NaN checkpoint) always
trips regardless of threshold.  It runs on fresh zero caches, so it
never touches an engine's serving state.

Locking: ``serving.canary`` is the OUTERMOST serving-plane lock — a
rollout takes it, then the router's ``serving.router`` lock (via
``fleet_snapshot``/``bump_epoch``), then each engine's admission lock
(via ``swap_params``); never the reverse (docs/concurrency.md).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from distkeras_tpu import obs
from distkeras_tpu.resilience import chaos
from distkeras_tpu.serving.publish import SnapshotCorrupt
from distkeras_tpu.utils.locks import TracedLock

__all__ = ["CanaryController"]


def _make_drift_probe(cfg):
    """The jitted pinned-prompt probe: greedy logits of the candidate
    vs the incumbent params over fresh zero caches.  Returns a scalar
    drift (max-abs over every prompt position's logits), with NaN
    mapped to +inf — a NaN anywhere means the candidate cannot be
    compared, which must TRIP the gate, not sneak past a ``>``
    comparison that NaN always fails."""
    from distkeras_tpu.models.generate import _decode_chunk, init_cache

    def drift(params_new, params_old, rows):
        pos = jnp.zeros((1,), jnp.int32)
        new_logits, _ = _decode_chunk(
            params_new, init_cache(cfg, 1), rows, pos, cfg,
            uniform_pos=True)
        old_logits, _ = _decode_chunk(
            params_old, init_cache(cfg, 1), rows, pos, cfg,
            uniform_pos=True)
        d = jnp.max(jnp.abs(new_logits.astype(jnp.float32)
                            - old_logits.astype(jnp.float32)))
        return jnp.where(jnp.isnan(d), jnp.inf, d)

    return jax.jit(drift)


class CanaryController:
    """Push → gate → promote-or-rollback over a router's fleet.

    ``router``: the :class:`~distkeras_tpu.serving.router.Router`
    whose in-process replicas wrap ``hot_swap=True`` engines.
    ``reader``: a :class:`~distkeras_tpu.serving.publish.
    SnapshotReader` for :meth:`poll` (may be None when the caller
    feeds :meth:`rollout` directly).  ``cfg``/``template``: the model
    config and a param pytree (arrays or ShapeDtypeStructs) — the
    drift probe compiles against them at construction, so a rollout
    never compiles anything (the ``serving_weight_push`` session pins
    it).

    ``canary``: how many replicas take the push first.  ``max_drift``:
    the finite drift budget (default +inf: only a NaN/Inf candidate
    trips — set it when the deploy has a known logit tolerance).
    ``probe_prompt``: the pinned token prompt the probe scores.

    The SLO half of the gate is the router's own state: a canary whose
    ``degraded`` flag is set in the post-push fleet snapshot (the
    ``breach_demoter`` flips it when that replica's live SLO window
    breaches) fails the gate exactly like drift does.
    """

    def __init__(self, router, reader, cfg, template, *, canary: int = 1,
                 max_drift: float = float("inf"),
                 probe_prompt=(1, 2, 3)):
        if canary < 1:
            raise ValueError(f"canary must be >= 1, got {canary}")
        prompt = [int(t) for t in probe_prompt]
        if not prompt:
            raise ValueError("probe_prompt must carry >= 1 token")
        self.router = router
        self.reader = reader
        self.cfg = cfg
        self.template = template
        self.canary = int(canary)
        self.max_drift = float(max_drift)
        self._rows = jnp.asarray([prompt], jnp.int32)
        self._lock = TracedLock("serving.canary")
        self._probe = _make_drift_probe(cfg)
        # Compile the probe NOW: a rollout is serve-phase, and its
        # zero-compile budget covers the probe too.  Zero trees carry
        # the template's exact avals (uncommitted, like engine params).
        zeros = jax.tree.map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), template)
        float(self._probe(zeros, zeros, self._rows))
        # The last successfully promoted (version, tree) — the
        # rollback source once version 1 has been promoted; before
        # that, canaries roll back to each engine's own live tree.
        self._good: tuple | None = None
        # Versions a rollout rejected: :meth:`poll` quarantines them
        # so a gate-tripped publish is pushed ONCE, not re-pushed on
        # every tick until the trainer publishes something newer.
        self._rejected: set[int] = set()

    # ------------------------------------------------------------ gate

    def _drift(self, new_tree, old_tree) -> float:
        new_j = jax.tree.map(jnp.asarray, new_tree)
        old_j = jax.tree.map(jnp.asarray, old_tree)
        drift = float(self._probe(new_j, old_j, self._rows))
        obs.observe("canary.drift", drift)
        return drift

    # --------------------------------------------------------- rollout

    def rollout(self, version: int, tree) -> dict:
        """Run one full push of ``tree`` as ``version``: canary swap →
        drift + SLO gate → promote fleet-wide or roll the canaries
        back.  Returns the rollout record
        ``{"action", "version", "drift", "canaries", "promoted"}``.

        Atomic from the fleet's point of view: on ANY failure —
        gate trip, a mid-swap exception, a chaos fault at the
        ``canary.promote`` probe — every replica that saw version
        ``version`` is rolled back to what it served before, and the
        epoch is bumped so routing state never straddles the attempt.
        """
        version = int(version)
        with self._lock:
            return self._rollout_locked(version, tree)

    def _rollout_locked(self, version: int, tree) -> dict:
        snap = self.router.fleet_snapshot()
        handles = self.router.replica_handles()
        eligible = sorted(
            n for n, r in snap["replicas"].items()
            if r["up"] and not r["draining"]
            and hasattr(handles[n], "swap_params"))
        if not eligible:
            raise ValueError(
                "no eligible replicas: a rollout needs >= 1 up, "
                "non-draining replica wrapping a hot_swap=True engine")
        canaries = eligible[:self.canary]
        rest = eligible[self.canary:]
        old = self._good[1] if self._good is not None else None
        obs.event("canary.push", version=version,
                  canaries=len(canaries), fleet=len(eligible))
        # ---- canary swap (stash each replica's incumbent for the
        # rollback path; reading it through the handle keeps version N
        # alive however this attempt ends).
        swapped: list = []
        try:
            for n in canaries:
                incumbent = (old if old is not None
                             else handles[n].engine.params)
                from_v = handles[n].param_version()
                handles[n].swap_params(tree, version)
                swapped.append((n, incumbent, from_v))
            drift = self._drift(tree, swapped[0][1])
            post = self.router.fleet_snapshot()
            degraded = [n for n in canaries
                        if post["replicas"][n]["degraded"]
                        or not post["replicas"][n]["up"]]
            # Non-finite drift ALWAYS trips — ``inf <= inf`` would
            # otherwise wave a NaN push through the default budget.
            tripped = (not math.isfinite(drift)
                       or drift > self.max_drift or bool(degraded))
            if tripped:
                return self._rollback(
                    version, swapped, drift,
                    reason=("slo_degraded" if degraded
                            else "drift"))
            # ---- promote: the canaries passed; the rest of the
            # fleet follows, then the epoch commits the new version
            # set.  A fault injected at the probe site lands AFTER
            # the gate but BEFORE any non-canary swap — the rollback
            # below must leave the whole fleet on the incumbent.
            chaos.probe("canary.promote", step=version)
            for n in rest:
                handles[n].swap_params(tree, version)
        except Exception:
            self._rollback(version, swapped, None, reason="error")
            raise
        self.router.bump_epoch(f"canary promote v{version}")
        self._good = (version, tree)
        if self.reader is not None:
            self.reader.adopt(version)
        obs.count("canary.promotions")
        obs.event("canary.rollout", action="promote", version=version,
                  drift=drift, canaries=len(canaries),
                  promoted=len(eligible))
        return {"action": "promote", "version": version,
                "drift": drift, "canaries": list(canaries),
                "promoted": len(eligible)}

    def _rollback(self, version: int, swapped, drift,
                  reason: str) -> dict:
        for n, incumbent, from_v in swapped:
            # allow_downgrade: THE legitimate monotonicity exception.
            n_handle_swap_ok = True
            try:
                # Re-fetch nothing: the handle in ``swapped`` is the
                # one we pushed through; an engine that died between
                # push and rollback surfaces here, not silently.
                self.router.replica_handles()[n].swap_params(
                    incumbent, from_v, allow_downgrade=True)
            except Exception as e:  # noqa: BLE001 — best-effort per
                # replica: one dead canary must not strand the rest
                # on the rejected version.
                n_handle_swap_ok = False
                obs.event("canary.rollback_failed", replica=n,
                          error=f"{type(e).__name__}: {e}"[:200])
            if n_handle_swap_ok:
                obs.event("canary.replica_rollback", replica=n,
                          to_version=from_v)
        self._rejected.add(version)
        self.router.bump_epoch(
            f"canary rollback v{version} ({reason})")
        obs.count("canary.rollbacks")
        obs.event("canary.rollout", action="rollback", version=version,
                  drift=drift, reason=reason, canaries=len(swapped),
                  promoted=0)
        return {"action": "rollback", "version": version,
                "drift": drift, "reason": reason,
                "canaries": [n for n, _, _ in swapped], "promoted": 0}

    # ------------------------------------------------------------ poll

    def poll(self) -> dict | None:
        """One train→serve tick: surface the newest fully-verified
        snapshot strictly above the adopted version and roll it out.
        Returns the rollout record, an ``{"action": "abort"}`` record
        when the newest publish is torn/corrupt (engines keep serving
        the current version — the ``train_kill_push`` contract), or
        None when there is nothing new."""
        if self.reader is None:
            raise ValueError(
                "poll() needs a SnapshotReader (reader=); feed "
                "rollout() directly otherwise")
        latest = self.reader.latest_version()
        if latest is not None and int(latest) in self._rejected:
            return None
        try:
            nxt = self.reader.poll(self.template)
        except SnapshotCorrupt as e:
            obs.count("canary.aborts")
            obs.event("canary.abort", reason=f"{e}"[:200])
            return {"action": "abort", "error": str(e)}
        if nxt is None:
            return None
        version, tree = nxt
        return self.rollout(version, tree)
