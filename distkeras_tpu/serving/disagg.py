"""Disaggregated prefill/decode: the block-shipping wire format.

Round 17 splits the fleet by phase: a prefill-specialized replica
builds a prompt's KV as paged BLOCKS on the round-12 slab and SHIPS
them to a decode-specialized replica, which adopts them by page-table
splice (:meth:`~distkeras_tpu.serving.paged.PagedBatcher.import_blocks`
pins the run through the ordinary :class:`PinnedStems` refcount path).
The content-hashed block run is already the transferable unit — a
shipped block carries the same chain digest the residency telemetry
advertises, so the decode side hash-hits blocks it already holds and
the router skips transfers for warm stems entirely.

This module is the WIRE half and is deliberately jax-free (the router
imports it, and the router runs on hosts that never import jax —
source lint ``jax-free`` rule): a :class:`BlockShipment` is plain
numpy + metadata, and :func:`encode_shipment` / :func:`decode_shipment`
are the stdlib byte codec the ``/blocks`` and ``/prefill`` endpoint
routes speak.

Wire format (version 1)::

    [4-byte LE header length][JSON header][raw leaf payload]

The JSON header carries the block size, the chain digests (hex — the
same spelling ``/residency`` serves), and one (dtype, shape) spec per
slab leaf; the payload is the blocks' leaf buffers concatenated
blocks-major, leaves-minor, in ``jax.tree.leaves`` order of the
exporter's slab.  Both ends run the same model config, so leaf order
and avals agree by construction — the importer still validates every
buffer against ITS slab before any device write.  int8 (``kv_int8``)
blocks ride as-is: quantized values and their scale leaves are just
more leaves, never dequantized in transit.
"""

from __future__ import annotations

import dataclasses
import json
import struct

import numpy as np

_MAGIC = "dkt-blocks"
_VERSION = 1


def _np_dtype(name: str) -> np.dtype:
    """dtype by NAME (``.str`` spells bfloat16 as raw ``V2``, losing
    its identity).  Extension dtypes resolve once ml_dtypes has
    registered them — import it lazily so plain-float shipments stay
    dependency-free."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 names with numpy
        return np.dtype(getattr(ml_dtypes, name))


@dataclasses.dataclass(frozen=True)
class BlockShipment:
    """A host-side run of exported KV blocks, ready to ship.

    ``block``: positions per block (must match the importer's slab).
    ``hashes``: the chain digest of each block — position-dependent
    content identity, in stem order (block k's digest covers tokens
    ``[0, (k+1)*block)``).  ``blocks[k]`` is block k's slab content:
    one numpy array per slab leaf (``jax.tree.leaves`` order), each
    shaped like the leaf with the block axis sliced to 1.
    """

    block: int
    hashes: tuple
    blocks: tuple

    def __post_init__(self):
        if len(self.hashes) != len(self.blocks):
            raise ValueError(
                f"shipment carries {len(self.hashes)} digests but "
                f"{len(self.blocks)} block payloads")

    def __len__(self) -> int:
        return len(self.blocks)

    @property
    def span(self) -> int:
        """Token positions the shipment covers (always full blocks)."""
        return len(self.blocks) * self.block

    @property
    def nbytes(self) -> int:
        """Payload bytes (the transfer-budget number the obs counters
        report — header overhead excluded on purpose: it is O(leaves),
        not O(tokens))."""
        return sum(a.nbytes for leaves in self.blocks for a in leaves)

    def hexes(self) -> list:
        """Digests in the JSON-safe hex spelling the router's affinity
        table stores."""
        return [h.hex() for h in self.hashes]


def encode_shipment(shipment: BlockShipment) -> bytes:
    """Serialize a shipment for the ``/blocks`` POST body."""
    if not shipment.blocks:
        raise ValueError("refusing to encode an empty shipment")
    leaves0 = shipment.blocks[0]
    header = {
        "magic": _MAGIC,
        "version": _VERSION,
        "block": int(shipment.block),
        "hashes": shipment.hexes(),
        "leaves": [{"dtype": a.dtype.name, "shape": list(a.shape)}
                   for a in leaves0],
    }
    payload = []
    for leaves in shipment.blocks:
        if len(leaves) != len(leaves0):
            raise ValueError("ragged shipment: blocks disagree on "
                             "leaf count")
        for a, spec in zip(leaves, leaves0):
            if a.shape != spec.shape or a.dtype != spec.dtype:
                raise ValueError("ragged shipment: blocks disagree "
                                 "on leaf avals")
            payload.append(np.ascontiguousarray(a).tobytes())
    hb = json.dumps(header).encode()
    return struct.pack("<I", len(hb)) + hb + b"".join(payload)


def decode_shipment(data: bytes) -> BlockShipment:
    """Parse :func:`encode_shipment` output back into a
    :class:`BlockShipment`.  Raises ``ValueError`` on anything
    malformed — truncation, bad magic, payload/spec size mismatch —
    so a torn transfer can never half-adopt."""
    if len(data) < 4:
        raise ValueError("shipment truncated before header length")
    (hlen,) = struct.unpack_from("<I", data)
    if len(data) < 4 + hlen:
        raise ValueError("shipment truncated inside header")
    try:
        header = json.loads(data[4:4 + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"shipment header is not JSON: {e}") from e
    if header.get("magic") != _MAGIC:
        raise ValueError("not a block shipment (bad magic)")
    if header.get("version") != _VERSION:
        raise ValueError(
            f"unsupported shipment version {header.get('version')!r}")
    specs = [(_np_dtype(s["dtype"]), tuple(s["shape"]))
             for s in header["leaves"]]
    hashes = tuple(bytes.fromhex(h) for h in header["hashes"])
    per_block = sum(dt.itemsize * int(np.prod(shape, dtype=np.int64))
                    for dt, shape in specs)
    off = 4 + hlen
    if len(data) - off != per_block * len(hashes):
        raise ValueError(
            f"shipment payload is {len(data) - off} bytes; header "
            f"promises {per_block * len(hashes)}")
    blocks = []
    for _ in hashes:
        leaves = []
        for dt, shape in specs:
            n = dt.itemsize * int(np.prod(shape, dtype=np.int64))
            leaves.append(np.frombuffer(data[off:off + n], dtype=dt)
                          .reshape(shape))
            off += n
        blocks.append(tuple(leaves))
    return BlockShipment(block=int(header["block"]), hashes=hashes,
                         blocks=tuple(blocks))


__all__ = ["BlockShipment", "encode_shipment", "decode_shipment"]
