"""Fleet serving: a cache-aware router over N engine replicas.

One ``ContinuousBatcher``/``PagedBatcher`` in one process was the
whole serving plane through round 12; this module is the layer ABOVE
it (ROADMAP item 1): a host-side :class:`Router` that fronts N engine
replicas — in-process objects or cross-host endpoints discovered over
the ``DKT_CLUSTER_*`` substrate — behind the familiar
``submit``/``enqueue``/``poll``/``drain``/``shutdown`` surface.  Four
pillars:

- **Cache-aware routing.**  The router keeps a per-replica affinity
  table of resident paged stem digests and prefix-pool ids, built
  from the replicas' residency digests (``engine.residency()`` /
  the ``/residency`` endpoint — ground truth) plus optimistic inserts
  from routed request history.  A request whose warm-prompt stems are
  resident on replica k routes to k (the same locality trick
  production LLM gateways use: a stem hit refcounts blocks instead of
  re-prefilling them); everything else falls back to least-loaded by
  the live queue-depth/lanes-busy signals, with ``slo.breach``
  subscriber callbacks demoting a breaching replica for a cooldown.
- **Health-gated membership.**  Replicas join and leave off health
  probes (``/healthz``, heartbeat freshness, or any injected
  callable).  A replica that stops answering is marked DOWN within
  one health interval and takes no new routes; when it answers again
  it rejoins under a new route epoch with a fresh affinity entry (its
  cache died with it).  ``QueueFull`` from one replica spills to the
  next candidate — the caller sees QueueFull only when every live
  replica is saturated.
- **Drain-and-reroute.**  A dead or draining replica's un-finished
  ACCEPTED requests are re-admitted elsewhere, idempotently by
  request id: the router polls only a request's CURRENT assignment,
  stamps every route with the route epoch (the same
  generation-counter idea as ``resilience/cluster.py``'s
  :class:`~distkeras_tpu.resilience.cluster.EpochStore`), and records
  only the first terminal result — so a replica kill costs latency
  (the re-prefill on the new replica), never a caller-visible loss.
- **Trace propagation.**  The router assigns fleet-wide request ids
  and emits ``router.submit`` / ``router.route`` /
  ``router.reroute`` / ``router.finish`` events carrying them; each
  route event also records the replica-local request id, so
  ``scripts/obs_report.py --request`` stitches the full cross-process
  waterfall — routing decision, re-route hop, and the engine-side
  admit/emit/finish stages — from the merged traces.
- **Disaggregated prefill/decode** (round 17).  Replica handles
  carry a ``role=`` label: a ``"prefill"``-specialized replica takes
  no decode routes; instead, a long-prompt request becomes a 2-stage
  hop — the router asks a prefill replica to build the warm prompt's
  KV blocks (``export_blocks`` / ``POST /prefill``), ships them to
  the chosen decode replica (``import_blocks`` / ``POST /blocks`` —
  the :mod:`~distkeras_tpu.serving.disagg` wire format), and admits
  the request there, where the admission hash-hits the adopted pinned
  run (zero re-prefill).  Residency digests gate the transfer: a
  decode replica already holding the stems skips the hop entirely.
  The shipped pin is released when the request goes terminal (the
  refcount story chaos leans on); ANY prefill-hop failure — death
  mid-transfer, allocator backpressure, geometry mismatch — falls
  back to plain routing, never a caller-visible error.
- **Token streaming.**  :meth:`Router.stream` relays the serving
  replica's live transcript (``partial()`` / ``GET /stream``)
  incrementally — first token long before the terminal result, the
  thing that makes a 2-stage request usable — and is reroute-safe
  because decode is deterministic: a rerouted request's regenerated
  transcript extends the already-streamed prefix bit-exactly.

Guaranteed jax-free (source lint ``jax-free`` ledger): routing is
host bookkeeping and HTTP; a router process never compiles a program
(the ``serving_router`` session in ``scripts/check_compile_counts.py``
pins a zero-compile route-and-serve phase over in-process replicas).

Thread safety: one ``serving.router`` :class:`TracedRLock` guards the
router's tables; replica engine locks nest INSIDE it (the router is
the outermost lock in the serving plane — docs/concurrency.md).
``enqueue``/``poll``/``take`` are safe from any thread; one thread
drives ``step()``/``pump()``.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.resilience.admission import (EngineClosed, QueueFull,
                                                 RequestResult)
from distkeras_tpu.serving.disagg import (BlockShipment, decode_shipment,
                                          encode_shipment)
from distkeras_tpu.serving.residency import stem_hexes
from distkeras_tpu.utils.locks import TracedRLock

# Replica-local request-id bases: the router gives each attached
# in-process replica a disjoint id range (base, base + span) so one
# merged trace never holds two engines' colliding ids — what makes the
# cross-replica waterfall unambiguous.  Router-level ids stay below
# the first base.
RID_SPAN = 1_000_000


class ReplicaUnreachable(RuntimeError):
    """A remote replica stopped answering (connection refused/reset or
    timeout) — the router treats it as a death signal, not an error
    surfaced to callers."""


def _check_role(role):
    """Replica role labels (round 17): ``None`` = generalist (serves
    everything), ``"decode"`` = decode-specialized (a generalist to
    the routing rules, named for topology clarity), ``"prefill"`` =
    prefill-specialized (takes NO decode routes; serves the
    block-build half of disaggregated requests only)."""
    if role is not None and role not in ("prefill", "decode"):
        raise ValueError(
            f'role must be None, "prefill", or "decode", got {role!r}')
    return role


# ----------------------------------------------------------- replicas


class InProcessReplica:
    """A replica handle over an engine object in THIS process.

    ``engine`` is any serving engine exposing the admission surface
    (``enqueue``/``poll``/``step``/``residency``/``queued``/
    ``running``/``closed``) — the router never imports the engine
    classes, so this module stays jax-free.  A pod-SHARDED engine
    (``plan=``/``mesh=``, round 14) is ONE replica handle like any
    other: its residency digests are host-side content hashes, so the
    affinity table never sees the mesh — a replica behind the router
    can be a whole pod.  ``health`` overrides the
    default liveness check (engine not closed) — e.g. a heartbeat-
    freshness callable for replicas whose process publishes beats.

    ``rid_base``: the replica-local request-id floor; assigned by
    :meth:`Router.add_replica` when None (disjoint ranges per replica,
    see module docstring).  ``start()`` optionally runs the decode
    loop on a daemon thread (the deployment shape where each replica
    steps itself — what the cross-host endpoint does in its own
    process); without it the router's ``step()`` drives the engine.
    """

    remote = False

    def __init__(self, name: str, engine, health=None,
                 rid_base: int | None = None, role: str | None = None):
        self.name = str(name)
        self.engine = engine
        self.role = _check_role(role)
        self._health = health
        self._failed = None
        if rid_base is not None:
            self.set_rid_base(rid_base)
        self._stop = threading.Event()
        self._thread = None

    def set_rid_base(self, base: int) -> None:
        if self.engine._next_id < base:
            self.engine._next_id = base

    # ----------------------------------------------- admission surface

    def enqueue(self, prompt, max_new_tokens: int, **kw) -> int:
        return self.engine.enqueue(prompt, max_new_tokens, **kw)

    def poll(self, request_id: int):
        return self.engine.poll(request_id)

    def partial(self, request_id: int):
        """Live transcript snapshot (the engines' ``partial()``) — the
        streaming relay's read."""
        return self.engine.partial(request_id)

    def step(self) -> None:
        self.engine.step()

    # ------------------------------------------------- block transfer

    def prefill_blocks(self, prompt) -> BlockShipment:
        """Build + export ``prompt``'s full-block KV run (paged
        engines only — the prefill half of a disaggregated hop)."""
        return self.engine.export_blocks(prompt)

    def import_blocks(self, shipment: BlockShipment):
        """Adopt a shipped run; the engine's
        ``{"prefix_id", ...}`` dict, or None under allocator
        backpressure."""
        return self.engine.import_blocks(shipment)

    def unpin(self, prefix_id: int) -> None:
        self.engine.unpin_prefix(prefix_id)

    # ------------------------------------------------- routing signals

    def healthy(self) -> bool:
        if self._failed is not None:
            return False
        if self._health is not None:
            return bool(self._health())
        return not self.engine.closed

    def residency(self) -> dict:
        return self.engine.residency()

    def load(self) -> tuple[int, int, int]:
        """``(queue_depth, lanes_busy, lanes)`` read live off the
        engine (cheap host counters)."""
        return (self.engine.queued, len(self.engine.running()),
                self.engine.lanes)

    def param_version(self) -> int:
        """The engine's live weight version (0 until the first
        ``swap_params``; engines without hot-swap report 0 forever)."""
        return int(getattr(self.engine, "param_version", 0))

    def swap_params(self, tree, version: int,
                    allow_downgrade: bool = False) -> int:
        """Live weight push passthrough (round 20) — hot-swap engines
        only; the canary controller drives this."""
        return self.engine.swap_params(
            tree, version, allow_downgrade=allow_downgrade)

    # -------------------------------------------------- self-stepping

    def start(self, idle_s: float = 0.005) -> "InProcessReplica":
        """Run the decode loop on a daemon thread: step whenever work
        exists, nap ``idle_s`` when idle.  The per-replica step thread
        is what lets N in-process replicas decode CONCURRENTLY (XLA
        releases the GIL during execution) — the bench rows' fleet
        shape."""
        if self._thread is not None:
            raise RuntimeError(f"replica {self.name} already started")
        self._stop.clear()
        self._failed = None

        def run():
            while not self._stop.is_set():
                try:
                    if self.engine.running() or self.engine.queued:
                        self.engine.step()
                    else:
                        self._stop.wait(idle_s)
                except Exception as e:  # noqa: BLE001 — a dead step
                    # thread must flip healthy() so the router
                    # reroutes, not hang its requests forever.
                    self._failed = e
                    return

        self._thread = threading.Thread(
            target=run, name=f"dkt-replica-{self.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class HttpReplica:
    """A replica handle over a cross-host :class:`EngineEndpoint`.

    ``addr`` is ``host:port`` (the endpoint publishes it into
    ``<coord_dir>/serve/host<N>.addr`` under the ``DKT_CLUSTER_*``
    substrate — see :func:`discover_replicas`).  Admission maps HTTP
    status to the engine contract: 429 -> :class:`QueueFull`, 503 ->
    :class:`EngineClosed`, connection failure ->
    :class:`ReplicaUnreachable` (a death signal the router turns into
    drain-and-reroute, never a caller-visible error).  Load/residency
    ride the ``/residency`` document and are cached between refreshes
    so routing decisions never block on the network.
    """

    remote = True

    def __init__(self, name: str, addr: str, timeout: float = 2.0,
                 role: str | None = None,
                 transfer_timeout: float = 30.0):
        self.name = str(name)
        self.addr = addr
        self.timeout = timeout
        self.role = _check_role(role)
        # Block transfers move O(prompt) cache bytes and the prefill
        # hop runs real compute — give them their own, longer budget
        # than the control-plane timeout.
        self.transfer_timeout = transfer_timeout
        self._cached: dict = {}

    def _url(self, path: str) -> str:
        return f"http://{self.addr}{path}"

    def _get(self, path: str) -> tuple[int, bytes]:
        try:
            with urllib.request.urlopen(self._url(path),
                                        timeout=self.timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except Exception as e:  # noqa: BLE001 — refused/reset/timeout
            raise ReplicaUnreachable(
                f"replica {self.name} at {self.addr}: {e}") from e

    def enqueue(self, prompt, max_new_tokens: int, **kw) -> int:
        body = {"prompt": np.asarray(prompt, np.int32).tolist(),
                "max_new_tokens": int(max_new_tokens), **kw}
        req = urllib.request.Request(
            self._url("/enqueue"), data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return int(json.loads(resp.read())["request_id"])
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")
            if e.code == 429:
                raise QueueFull(detail) from e
            if e.code == 503:
                raise EngineClosed(detail) from e
            raise ValueError(detail) from e
        except (QueueFull, EngineClosed):
            raise
        except Exception as e:  # noqa: BLE001 — refused/reset/timeout
            raise ReplicaUnreachable(
                f"replica {self.name} at {self.addr}: {e}") from e

    def poll(self, request_id: int):
        code, body = self._get(f"/poll?id={int(request_id)}")
        if code == 404:
            return None
        if code != 200:
            # A 5xx means the endpoint is up but erroring — treat it
            # like a death signal (drain-and-reroute is idempotent),
            # never let an error document parse as a result.
            raise ReplicaUnreachable(
                f"replica {self.name} at {self.addr}: poll returned "
                f"HTTP {code}: {body[:200]!r}")
        rec = json.loads(body)
        return RequestResult(
            request_id=int(rec["request_id"]),
            tokens=np.asarray(rec["tokens"], np.int32),
            status=rec["status"], prompt_len=int(rec["prompt_len"]),
            error=rec.get("error"))

    def partial(self, request_id: int):
        """Live transcript snapshot off ``GET /stream`` — terminal
        results included (same doc shape as ``/poll``), None for
        unknown ids."""
        code, body = self._get(f"/stream?id={int(request_id)}")
        if code == 404:
            return None
        if code != 200:
            raise ReplicaUnreachable(
                f"replica {self.name} at {self.addr}: stream returned "
                f"HTTP {code}: {body[:200]!r}")
        rec = json.loads(body)
        return RequestResult(
            request_id=int(rec["request_id"]),
            tokens=np.asarray(rec["tokens"], np.int32),
            status=rec["status"], prompt_len=int(rec["prompt_len"]),
            error=rec.get("error"))

    def step(self) -> None:
        """No-op: a remote replica's endpoint steps its own engine."""

    # ------------------------------------------------- block transfer

    def _post(self, path: str, data: bytes, content_type: str,
              timeout: float) -> bytes:
        req = urllib.request.Request(
            self._url(path), data=data,
            headers={"Content-Type": content_type}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")
            if e.code == 429:
                raise QueueFull(detail) from e
            if e.code == 503:
                raise EngineClosed(detail) from e
            raise ValueError(detail) from e
        except (QueueFull, EngineClosed):
            raise
        except Exception as e:  # noqa: BLE001 — refused/reset/timeout
            raise ReplicaUnreachable(
                f"replica {self.name} at {self.addr}: {e}") from e

    def prefill_blocks(self, prompt) -> BlockShipment:
        """``POST /prefill``: build + export the prompt's full-block
        KV run on the remote replica; returns the decoded shipment.
        429 -> QueueFull (allocator backpressure), connection death ->
        ReplicaUnreachable — both fall back to plain routing."""
        body = {"prompt": np.asarray(prompt, np.int32).tolist()}
        data = self._post("/prefill", json.dumps(body).encode(),
                          "application/json", self.transfer_timeout)
        return decode_shipment(data)

    def import_blocks(self, shipment: BlockShipment):
        """``POST /blocks``: ship the run to the remote replica for
        adoption.  Mirrors the engine contract: the import dict on
        success, None under allocator backpressure (HTTP 429)."""
        try:
            body = self._post("/blocks", encode_shipment(shipment),
                              "application/octet-stream",
                              self.transfer_timeout)
        except QueueFull:
            return None
        return json.loads(body)

    def unpin(self, prefix_id: int) -> None:
        self._post("/unpin",
                   json.dumps({"prefix_id": int(prefix_id)}).encode(),
                   "application/json", self.timeout)

    def healthy(self) -> bool:
        try:
            code, _ = self._get("/healthz")
        except ReplicaUnreachable:
            return False
        return code == 200

    def residency(self) -> dict:
        _, body = self._get("/residency")
        self._cached = json.loads(body)
        return self._cached

    def load(self) -> tuple[int, int, int]:
        c = self._cached
        return (int(c.get("queue_depth", 0)),
                int(c.get("lanes_busy", 0)), int(c.get("lanes", 1)))

    def param_version(self) -> int:
        """Weight version from the last ``/residency`` poll (0 until
        one lands — same staleness contract as :meth:`load`)."""
        return int(self._cached.get("param_version", 0))


def discover_replicas(coord_dir: str, timeout: float = 2.0
                      ) -> list[HttpReplica]:
    """Build :class:`HttpReplica` handles from the ``serve/`` address
    ledger an :class:`EngineEndpoint` publishes under the cluster
    coordination directory (the same atomic-file pattern as the
    telemetry federation's ``telemetry/`` ledger)."""
    import os

    d = os.path.join(coord_dir, "serve")
    out = []
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not (name.startswith("host") and name.endswith(".addr")):
            continue
        try:
            with open(os.path.join(d, name), encoding="utf-8") as f:
                rec = json.load(f)
            out.append(HttpReplica(f"host{int(rec['host'])}",
                                   rec["addr"], timeout=timeout,
                                   role=rec.get("role")))
        except (OSError, ValueError, KeyError):
            continue  # torn publish mid-replace: skip this pass
    return out


# ------------------------------------------------------------- router


@dataclasses.dataclass
class _Member:
    replica: object
    up: bool = True
    draining: bool = False
    degraded_until: float = 0.0
    last_health: float = 0.0
    inflight: int = 0


@dataclasses.dataclass
class _Routed:
    request_id: int
    prompt: np.ndarray
    max_new: int
    kw: dict
    deadline: float | None
    born: float
    prefix_id: object
    replica: str | None = None
    replica_rid: int | None = None
    epoch: int = 0
    hops: int = 0
    # Disagg import pin held on the decode side: (replica_name,
    # prefix_id), released (queued to the pump's unpin drain) when the
    # request goes terminal or its holder dies — the refcount story.
    pin: tuple | None = None
    # Warm-prompt stem digests per block size, computed lazily (one
    # request may be scored against replicas with different blocks).
    stems: dict = dataclasses.field(default_factory=dict)

    def stems_at(self, block: int) -> list[str]:
        if block not in self.stems:
            self.stems[block] = stem_hexes(self.prompt[:-1], block)
        return self.stems[block]


class Router:
    """Cache-aware request router over N engine replicas (module
    docstring has the full story).

    ``replicas``: initial handles (:class:`InProcessReplica` /
    :class:`HttpReplica` / any object with the same surface); more
    join via :meth:`add_replica`.  ``policy``: ``"affinity"`` (stem/
    prefix residency first, least-loaded fallback — the default),
    ``"least_loaded"`` (residency ignored), or ``"round_robin"`` (the
    bench baseline).  ``health_interval`` / ``residency_interval``:
    probe cadences (seconds on ``clock``, injectable for tests).

    The admission surface mirrors the engines': :meth:`enqueue`
    returns a fleet-wide request id immediately (``QueueFull`` only
    when EVERY live replica is saturated; ``EngineClosed`` after
    :meth:`begin_shutdown` — and EngineClosed wins the race, same
    contract as the engines); results arrive via :meth:`poll` /
    :meth:`take` / :meth:`results`; :meth:`drain` blocks for one
    request; :meth:`shutdown` drains everything.  :meth:`step` drives
    in-process replicas one decode step and pumps; self-stepping
    replicas (``InProcessReplica.start()`` / remote endpoints) only
    need :meth:`pump`.
    """

    def __init__(self, replicas=(), *, policy: str = "affinity",
                 clock=None, health_interval: float = 0.5,
                 residency_interval: float = 2.0,
                 degrade_cooldown: float = 5.0,
                 poll_s: float = 0.005):
        if policy not in ("affinity", "least_loaded", "round_robin"):
            raise ValueError(
                f"policy must be affinity|least_loaded|round_robin, "
                f"got {policy!r}")
        self.policy = policy
        self._clock = clock if clock is not None else time.monotonic
        self.health_interval = health_interval
        self.residency_interval = residency_interval
        self.degrade_cooldown = degrade_cooldown
        self.poll_s = poll_s
        # Outermost serving-plane lock: replica engine admission locks
        # nest INSIDE it (docs/concurrency.md lock inventory).
        self._lock = TracedRLock("serving.router")
        self._members: dict[str, _Member] = {}
        self._affinity: dict[str, dict] = {}
        self._requests: dict[int, _Routed] = {}
        self._completed: dict[int, RequestResult] = {}
        self._pending: list[int] = []   # accepted but currently unrouted
        # Import pins awaiting release (network I/O — drained OUTSIDE
        # the router lock at the end of each pump round).
        self._unpins: list[tuple] = []
        self._next_id = 0
        # Router-assigned in-process bases start HIGH so they can
        # never collide with EngineEndpoint's host-id-derived bases
        # ((host_id + 1) * RID_SPAN) in a mixed fleet — the waterfall
        # leans on fleet-wide id disjointness.
        self._next_base = 1000 * RID_SPAN
        self._rr = 0
        self._closed = False
        self.epoch = 0
        self._last_residency = -float("inf")
        for r in replicas:
            self.add_replica(r)

    # ------------------------------------------------------ membership

    def add_replica(self, replica) -> None:
        """Join a replica.  In-process replicas get a disjoint
        request-id range; the affinity table seeds from the replica's
        residency digest (best effort — a dead-on-arrival replica
        joins DOWN and is retried by health probing)."""
        with self._lock:
            name = replica.name
            if name in self._members:
                raise ValueError(f"replica {name!r} already attached")
            if not getattr(replica, "remote", False):
                replica.set_rid_base(self._next_base)
            self._next_base += RID_SPAN
            self._members[name] = _Member(replica,
                                          last_health=self._clock())
            self.epoch += 1
        ok = self._refresh_one(name)
        with self._lock:
            if name in self._members:
                self._members[name].up = ok
        obs.event("router.replica_join", replica=name, up=ok,
                  epoch=self.epoch)

    def remove_replica(self, name: str):
        """Leave: reroute the replica's unfinished requests, then drop
        it from membership.  Returns the detached replica handle —
        the autoscaler pools a retired (still-warm) handle for later
        re-admission; other callers may ignore it."""
        with self._lock:
            if name not in self._members:
                raise KeyError(f"unknown replica {name!r}")
            self._members[name].draining = True
            self.epoch += 1
            self._reroute_from_locked(name, why="removed")
            handle = self._members.pop(name).replica
            self._affinity.pop(name, None)
        obs.event("router.replica_leave", replica=name,
                  epoch=self.epoch)
        return handle

    def drain_replica(self, name: str) -> None:
        """Graceful drain: stop routing to the replica and re-admit
        its unfinished accepted requests elsewhere.  The replica
        object itself is untouched (its owner decides shutdown)."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                raise KeyError(f"unknown replica {name!r}")
            m.draining = True
            self.epoch += 1
            self._reroute_from_locked(name, why="draining")
        obs.event("router.replica_drain", replica=name,
                  epoch=self.epoch)

    def replicas_up(self) -> list[str]:
        with self._lock:
            return sorted(n for n, m in self._members.items()
                          if m.up and not m.draining)

    def mark_degraded(self, name: str,
                      cooldown: float | None = None) -> None:
        """Demote a replica in the least-loaded ordering for
        ``cooldown`` seconds — the `slo.breach` hook (see
        :meth:`breach_demoter`)."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                return
            m.degraded_until = self._clock() + (
                self.degrade_cooldown if cooldown is None else cooldown)
        obs.event("router.replica_degraded", replica=name)

    def slo_rules(self, *templates) -> list:
        """Stamp ``SloRule`` templates per attached replica (round
        14): one ``replica=``-labeled copy of each template for every
        replica currently attached, in name order.  Pass the result to
        ``obs.session(slo_rules=...)`` and subscribe
        :meth:`breach_demoter` ONCE — a breach then demotes the
        replica its rule is scoped to, no hand-built closure per
        replica.  (Rules are snapshots: re-derive after membership
        changes if new replicas need coverage.)"""
        import dataclasses as _dc

        with self._lock:
            names = sorted(self._members)
        return [_dc.replace(t, replica=n)
                for n in names for t in templates]

    def breach_demoter(self, name: str | None = None):
        """A subscriber for ``obs.SloRule`` breach callbacks
        (``fn(rule, value)``).

        With ``name``: any breach demotes that fixed replica — the
        shape for cross-host fleets where each replica process runs
        its own rules and the operator maps streams to names.  With
        no argument (round 14): the subscriber reads the RULE's own
        ``replica=`` label (see :meth:`slo_rules`) and demotes that
        replica — attach it once for the whole fleet; breaches from
        unlabeled rules are ignored."""
        def on_breach(rule, value):
            del value
            target = name if name is not None \
                else getattr(rule, "replica", None)
            if target is not None:
                self.mark_degraded(target)
        return on_breach

    # ------------------------------------------------------- admission

    def enqueue(self, prompt, max_new_tokens: int, ttl=None,
                deadline=None, **submit_kw) -> int:
        """Route and admit one request; returns the fleet-wide request
        id.  ``QueueFull`` spills to the next candidate replica and
        reaches the caller only when every live replica is saturated;
        an expired-on-arrival deadline records a structured timeout
        (engine contract).  ``submit_kw`` forwards to the replica's
        ``enqueue`` (per-request keys / sampling overrides /
        ``prefix_id``)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if ttl is not None and deadline is not None:
            raise ValueError("pass ttl (relative) OR deadline "
                             "(absolute), not both")
        with self._lock:
            if self._closed:
                raise EngineClosed(
                    "router is shutting down (begin_shutdown was "
                    "called); no new requests are admitted during "
                    "drain")
            now = self._clock()
            dl = now + ttl if ttl is not None else deadline
            rid = self._next_id
            self._next_id += 1
            obs.event("router.submit", request_id=rid,
                      prompt_len=int(prompt.size),
                      max_new=int(max_new_tokens))
            req = _Routed(request_id=rid, prompt=prompt,
                          max_new=int(max_new_tokens), kw=submit_kw,
                          deadline=dl, born=now,
                          prefix_id=submit_kw.get("prefix_id"))
            if dl is not None and dl <= now:
                self._finish_locked(req, prompt, "timeout",
                                    prompt.size)
                return rid
            self._requests[rid] = req
            plan = self._disagg_plan_locked(req)
        try:
            # The 2-stage hop (prefill + block transfer) is network/
            # compute I/O and runs OUTSIDE the router lock; any
            # failure inside it falls back to plain routing.
            routed = (plan is not None
                      and self._disagg_enqueue(req, plan))
            if not routed:
                with self._lock:
                    self._route_locked(req)
        except BaseException:
            # Not accepted (QueueFull everywhere / no live
            # replica / validation): the id must not linger as an
            # accepted request for shutdown to "cancel" — and an
            # import pin taken for it must be handed back.
            with self._lock:
                dropped = self._requests.pop(rid, None)
                if dropped is not None and dropped.pin is not None:
                    self._unpins.append(dropped.pin)
                    dropped.pin = None
            self._drain_unpins()
            raise
        return rid

    # submit() is enqueue() here on purpose: a fleet has no stable
    # lane ids to hand back, so the id-keyed surface IS the surface
    # (the same argument as the elastic engine's enqueue-only rule).
    submit = enqueue

    def poll(self, request_id: int):
        """The request's :class:`RequestResult` (re-keyed to the
        fleet-wide id), or None while it decodes."""
        with self._lock:
            return self._completed.get(request_id)

    def take(self, request_id: int):
        with self._lock:
            return self._completed.pop(request_id)

    def results(self) -> dict:
        with self._lock:
            out = self._completed
            self._completed = {}
            return out

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def queued(self) -> int:
        """Accepted requests currently awaiting a replica slot (the
        router-level backlog; replica-level queues are on top)."""
        with self._lock:
            return len(self._pending)

    # ----------------------------------------------------- fleet state

    def fleet_snapshot(self) -> dict:
        """One CONSISTENT read of the whole fleet under a single lock
        acquisition: ``{"epoch", "pending", "closed", "replicas":
        {name: {...}}}`` with per-replica up/draining/degraded flags,
        live load (``queue_depth``/``lanes_busy``/``lanes`` plus the
        router's ``inflight`` debit and the combined ``load`` scoring
        key), role, and the affinity view (``prefix_ids`` /
        ``stems`` / ``block``).

        This is THE fleet-state read: the route scorer, the disagg
        planner, and the autoscaler all consume it (round 19), so a
        membership flip can never be observed torn against the load
        fields it changes — the ad-hoc per-field reads those
        consumers used to make individually are gone."""
        with self._lock:
            return self._fleet_snapshot_locked()

    def replica_handles(self) -> dict:
        """``{name: replica}`` — the live member handles under one
        lock acquisition.  The canary controller's swap surface
        (round 20): it needs the handles themselves (to call
        ``swap_params``), which the dict-of-dicts snapshot above
        deliberately does not carry."""
        with self._lock:
            return {n: m.replica for n, m in self._members.items()}

    def bump_epoch(self, reason: str) -> int:
        """Advance the route epoch without a membership change — the
        canary controller's promote/rollback commit point (round 20):
        a weight push changes what the fleet SERVES, so in-flight
        routing state made under the old version set is re-stamped
        the same way a drain re-stamps it.  Event emitted after the
        lock is released (the drain-path convention)."""
        with self._lock:
            self.epoch += 1
            epoch = self.epoch
        obs.event("router.epoch_bump", reason=str(reason), epoch=epoch)
        return epoch

    def _fleet_snapshot_locked(self) -> dict:
        now = self._clock()
        reps = {}
        for n, m in self._members.items():
            q, busy, lanes = m.replica.load()
            load = (busy + q) / max(lanes, 1) + m.inflight
            obs.gauge("router.replica_load", load, replica=n)
            tab = self._affinity.get(n, {})
            reps[n] = {
                "up": m.up, "draining": m.draining,
                "degraded": m.degraded_until > now,
                "inflight": m.inflight,
                "role": getattr(m.replica, "role", None),
                "queue_depth": q, "lanes_busy": busy, "lanes": lanes,
                "load": load,
                "prefix_ids": frozenset(tab.get("prefix_ids", ())),
                "stems": len(tab.get("stem_hashes", ())),
                "block": tab.get("block"),
                # Round 20: the live weight version (0 = never
                # swapped).  The canary controller and the request
                # waterfalls read it; the autoscaler ignores it (its
                # policies key on the named load fields above —
                # regression-tested in tests/test_autoscale.py).
                "param_version": (m.replica.param_version()
                                  if hasattr(m.replica,
                                             "param_version") else 0),
            }
        return {"epoch": self.epoch, "pending": len(self._pending),
                "closed": self._closed, "replicas": reps}

    # --------------------------------------------------------- routing

    def _candidates_locked(self, req: _Routed, exclude):
        now = self._clock()
        # Prefill-specialized replicas take no decode routes: they
        # serve the block-build half of disaggregated requests only.
        cands = [m for n, m in self._members.items()
                 if m.up and not m.draining and n not in exclude
                 and getattr(m.replica, "role", None) != "prefill"]
        if req.prefix_id is not None:
            have = [m for m in cands
                    if req.prefix_id in self._affinity.get(
                        m.replica.name, {}).get("prefix_ids", ())]
            if not have:
                raise ValueError(
                    f"prefix_id {req.prefix_id} is not resident on "
                    "any live replica (pool entries are replica-"
                    "local; pin it somewhere first)")
            cands = have
        return cands, now

    def _affinity_score(self, req: _Routed, name: str) -> int:
        tab = self._affinity.get(name)
        if not tab:
            return 0
        score = 0
        block = tab.get("block")
        if block:
            resident = tab.get("stem_hashes", ())
            for h in req.stems_at(block):
                if h in resident:
                    score += block
                else:
                    break
        if req.prefix_id is not None and \
                req.prefix_id in tab.get("prefix_ids", ()):
            score += 1
        return score

    def _route_locked(self, req: _Routed, exclude=frozenset(),
                      rerouting: bool = False,
                      prefer: str | None = None) -> bool:
        """Pick a replica and admit ``req`` on it.  Returns True on
        acceptance; parks the request in the router backlog (False)
        when every candidate is saturated AND the request was already
        accepted (a reroute must never surface QueueFull to a caller
        who holds an id); raises QueueFull for a fresh enqueue.
        ``prefer`` front-runs one replica in the candidate order (the
        disagg hop's decode target, which now holds the shipped
        blocks) without bypassing spillover."""
        if req.request_id in self._completed:
            return True  # finished while its enqueue ran unlocked
        try:
            cands, now = self._candidates_locked(req, exclude)
        except ValueError:
            if not rerouting:
                raise
            # Pool entries are replica-local: a prefix_id request
            # whose only advertising replica died cannot be served
            # anywhere — terminal structured error, never an
            # exception out of the pump round.
            self._finish_locked(
                req, req.prompt, "error", req.prompt.size,
                error=f"prefix_id {req.prefix_id} is no longer "
                      "resident on any live replica (its replica "
                      "died or drained)")
            return True
        if not cands and not rerouting:
            raise RuntimeError("router has no live replicas")
        del now
        # ONE consistent fleet read scores every candidate: the
        # degraded flag and the load key come from the same snapshot
        # (round 19 — the scorer can never see them torn).
        fleet = self._fleet_snapshot_locked()["replicas"]
        scored = []
        for m in cands:
            s = (self._affinity_score(req, m.replica.name)
                 if self.policy == "affinity" else 0)
            degraded = 1 if fleet[m.replica.name]["degraded"] else 0
            scored.append((m, s, degraded))
        if self.policy == "round_robin":
            order = sorted(scored, key=lambda t: t[2])
            order = order[self._rr % len(order):] \
                + order[:self._rr % len(order)] if order else order
            self._rr += 1
        else:
            order = sorted(
                scored,
                key=lambda t: (-t[1], t[2],
                               fleet[t[0].replica.name]["load"],
                               t[0].replica.name))
        if prefer is not None:
            # Stable re-sort: the preferred replica front-runs, the
            # rest keep their relative order (spillover path intact).
            order.sort(key=lambda t: t[0].replica.name != prefer)
        saw_full = False
        for i, (m, score, _deg) in enumerate(order):
            name = m.replica.name
            kw = dict(req.kw)
            if req.deadline is not None:
                remaining = req.deadline - self._clock()
                if remaining <= 0:
                    self._finish_locked(req, req.prompt, "timeout",
                                        req.prompt.size)
                    return True
                kw["ttl"] = remaining
            try:
                rrid = m.replica.enqueue(req.prompt, req.max_new, **kw)
            except QueueFull:
                saw_full = True
                continue
            except (EngineClosed, ReplicaUnreachable):
                # Racing its own shutdown/death: health probing will
                # flip it down; skip it for this route.
                continue
            reason = ("reroute" if rerouting
                      else "spillover" if i > 0
                      else "affinity" if score > 0
                      else self.policy if self.policy != "affinity"
                      else "least_loaded")
            req.replica, req.replica_rid = name, rrid
            req.epoch = self.epoch
            m.inflight += 1
            obs.count("router.requests", replica=name, reason=reason)
            if reason == "affinity":
                obs.count("router.affinity_hits")
            obs.event("router.route", request_id=req.request_id,
                      replica=name, replica_request_id=rrid,
                      reason=reason, hop=req.hops)
            # Optimistic history insert: the stems this request
            # prefills become resident on that replica.
            tab = self._affinity.setdefault(
                name, {"stem_hashes": set(), "prefix_ids": set(),
                       "block": None})
            if tab.get("block"):
                tab["stem_hashes"].update(
                    req.stems_at(tab["block"]))
            return True
        if rerouting:
            # Accepted request, fleet momentarily saturated: park in
            # the router backlog; pump() retries.
            req.replica, req.replica_rid = None, None
            if req.request_id not in self._pending:
                self._pending.append(req.request_id)
            obs.gauge("router.pending", len(self._pending))
            return False
        if saw_full:
            raise QueueFull(
                f"all {len(cands)} live replica(s) are saturated "
                "(every admission queue full); shed load or add "
                "replicas")
        raise RuntimeError(
            "no live replica accepted the request (all closed or "
            "unreachable)")

    def _reroute_from_locked(self, name: str, why: str) -> None:
        for req in list(self._requests.values()):
            if req.replica != name or req.request_id \
                    in self._completed:
                continue
            req.hops += 1
            if req.pin is not None:
                # The new replica re-prefills from scratch; the old
                # pin buys nothing there — queue its release (a dead
                # holder's pin is simply dropped by the drain).
                self._unpins.append(req.pin)
                req.pin = None
            obs.count("router.reroutes")
            obs.event("router.reroute", request_id=req.request_id,
                      src=name, why=why, hop=req.hops)
            self._route_locked(req, exclude={name}, rerouting=True)
        m = self._members.get(name)
        if m is not None:
            m.inflight = 0

    # ------------------------------------------- disaggregated 2-stage

    def _disagg_plan_locked(self, req: _Routed) -> str | None:
        """Decide whether ``req`` takes the 2-stage prefill/decode hop;
        returns the chosen prefill replica's name, or None for plain
        routing.  Plain routing wins when: no up prefill replica; the
        request rides a prefix-pool pin (warm by construction); the
        warm prompt spans less than one full block (nothing to ship);
        or some decode candidate's affinity table already covers every
        stem — the residency gate: shipping blocks the fleet already
        holds is pure waste, route to the warm replica instead."""
        if req.prefix_id is not None:
            return None
        pre = [(n, m) for n, m in self._members.items()
               if m.up and not m.draining
               and getattr(m.replica, "role", None) == "prefill"]
        if not pre:
            return None
        # Prefill + decode replicas run the same slab geometry; read
        # the block size off any affinity entry that advertises one.
        block = None
        for tab in self._affinity.values():
            if tab.get("block"):
                block = tab["block"]
                break
        if block is None:
            return None
        stems = req.stems_at(block)
        if not stems:
            return None  # warm prompt under one block
        for n, m in self._members.items():
            if not m.up or m.draining \
                    or getattr(m.replica, "role", None) == "prefill":
                continue
            resident = self._affinity.get(n, {}).get("stem_hashes", ())
            if all(h in resident for h in stems):
                obs.count("router.disagg_warm_skips")
                return None
        fleet = self._fleet_snapshot_locked()["replicas"]
        name, _m = min(pre, key=lambda t: (fleet[t[0]]["load"], t[0]))
        return name

    def _disagg_enqueue(self, req: _Routed, prefill_name: str) -> bool:
        """The 2-stage hop: build ``req``'s KV blocks on the prefill
        replica, ship them to the best decode candidate, and admit the
        request there — where admission hash-hits the adopted pinned
        run (zero re-prefill).  Runs OUTSIDE the router lock (the hop
        is prefill compute plus block-transfer network I/O).  Returns
        True once the request is admitted; returns False on ANY hop
        failure — prefill death mid-transfer, allocator backpressure,
        geometry mismatch — so ``enqueue`` falls back to plain
        routing, never a caller-visible error."""
        rid = req.request_id
        with self._lock:
            m = self._members.get(prefill_name)
            if m is None or not m.up or m.draining:
                return False
            prefill = m.replica
        try:
            with obs.span("router.prefill", request_id=rid,
                          replica=prefill_name):
                ship = prefill.prefill_blocks(req.prompt)
        except Exception as e:  # noqa: BLE001 — any failure: fall back
            obs.count("router.disagg_fallbacks", stage="prefill")
            obs.event("router.disagg_fallback", request_id=rid,
                      stage="prefill", replica=prefill_name,
                      error=f"{type(e).__name__}: {e}")
            return False
        # Pick the decode target exactly the way _route_locked would
        # (affinity first, degraded demoted, least-loaded tiebreak) so
        # the blocks ship to where admission will land.
        with self._lock:
            try:
                cands, now = self._candidates_locked(req, frozenset())
            except ValueError:
                return False
            if not cands:
                return False
            del now
            fleet = self._fleet_snapshot_locked()["replicas"]
            scored = [(m2,
                       self._affinity_score(req, m2.replica.name)
                       if self.policy == "affinity" else 0,
                       1 if fleet[m2.replica.name]["degraded"] else 0)
                      for m2 in cands]
            order = sorted(scored,
                           key=lambda t: (-t[1], t[2],
                                          fleet[t[0].replica.name]
                                          ["load"],
                                          t[0].replica.name))
            target = order[0][0].replica
            tname = target.name
            resident = set(self._affinity.get(tname, {})
                           .get("stem_hashes", ()))
        hexes = ship.hexes()
        imported = None
        if not all(h in resident for h in hexes):
            try:
                with obs.span("router.transfer", request_id=rid,
                              src=prefill_name, dst=tname):
                    imported = target.import_blocks(ship)
            except Exception as e:  # noqa: BLE001 — fall back
                obs.count("router.disagg_fallbacks", stage="transfer")
                obs.event("router.disagg_fallback", request_id=rid,
                          stage="transfer", replica=tname,
                          error=f"{type(e).__name__}: {e}")
                return False
            if imported is None:  # allocator backpressure on target
                obs.count("router.disagg_fallbacks", stage="adopt")
                obs.event("router.disagg_fallback", request_id=rid,
                          stage="adopt", replica=tname,
                          error="no free block on decode target")
                return False
            obs.count("router.transfer_bytes", float(ship.nbytes))
            obs.event("router.block_transfer", request_id=rid,
                      src=prefill_name, dst=tname,
                      bytes=int(ship.nbytes), blocks=len(ship),
                      hits=int(imported.get("hits", 0)))
        else:
            # The target grew the stems while the prefill ran (another
            # request's optimistic insert): skip the transfer.
            obs.count("router.disagg_warm_skips")
        with self._lock:
            if rid not in self._requests:
                # Finished/cancelled while the hop ran (shutdown or
                # deadline race): nothing left to route, but an
                # imported pin must still be handed back.
                if imported is not None:
                    self._unpins.append(
                        (tname, int(imported["prefix_id"])))
                return True
            if imported is not None:
                req.pin = (tname, int(imported["prefix_id"]))
                # Ground truth, not optimism: the shipment IS resident
                # on the target now — score it so admission routes
                # there as an affinity hit.
                tab = self._affinity.setdefault(
                    tname, {"stem_hashes": set(), "prefix_ids": set(),
                            "block": None})
                if not tab.get("block"):
                    tab["block"] = ship.block
                tab["stem_hashes"].update(hexes)
            self._route_locked(req, prefer=tname)
            if req.pin is not None and req.replica != tname:
                # Spilled past the warm target (its queue filled
                # during the hop): the pin buys nothing — hand it
                # back rather than hold blocks hostage.
                self._unpins.append(req.pin)
                req.pin = None
        obs.count("router.disagg_requests")
        self._drain_unpins()
        return True

    def _drain_unpins(self) -> None:
        """Release queued import pins (best effort — network I/O, runs
        OUTSIDE the router lock at the end of each pump round).  A pin
        whose holder died or left membership is dropped: its blocks
        died with that cache, there is nothing to release."""
        with self._lock:
            if not self._unpins:
                return
            pins, self._unpins = self._unpins, []
            handles = {n: m.replica for n, m in self._members.items()}
        for name, pid in pins:
            r = handles.get(name)
            if r is None:
                continue
            try:
                r.unpin(pid)
            except Exception:  # noqa: BLE001 — dead/racing replica:
                pass           # the pin died with its cache

    # ---------------------------------------------------- result pump

    def _finish_locked(self, req: _Routed, tokens, status: str,
                       prompt_len: int, error=None) -> None:
        self._completed[req.request_id] = RequestResult(
            request_id=req.request_id,
            tokens=np.asarray(tokens, np.int32), status=status,
            prompt_len=prompt_len, error=error)
        self._requests.pop(req.request_id, None)
        if req.pin is not None:
            # Terminal: hand the shipped blocks back (refcount story —
            # the drain runs outside the lock at the next pump round).
            self._unpins.append(req.pin)
            req.pin = None
        obs.count("router.finished", status=status)
        obs.event("router.finish", request_id=req.request_id,
                  status=status, replica=req.replica,
                  hops=req.hops)
        if obs.active() is not None:
            obs.observe("router.request_s", self._clock() - req.born,
                        status=status)

    def _refresh_one(self, name: str) -> bool:
        """Pull one replica's residency digest into the affinity
        table (network I/O for remote replicas — runs OUTSIDE the
        router lock).  Returns reachability."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                return False
            replica = m.replica
        try:
            res = replica.residency()
        except Exception:  # noqa: BLE001 — unreachable OR a malformed
            return False   # doc: either way, not a usable table yet
        tab = {"stem_hashes": set(res.get("stem_hashes", ())),
               "prefix_ids": set(res.get("prefix_ids", ())),
               "block": res.get("block")}
        with self._lock:
            if name in self._members:
                self._affinity[name] = tab
        return True

    def refresh_residency(self) -> None:
        """Rebuild the affinity table from every up replica's
        residency digest (ground truth replaces the optimistic
        history)."""
        with self._lock:
            names = [n for n, m in self._members.items() if m.up]
            self._last_residency = self._clock()
        for n in names:
            self._refresh_one(n)

    def pump(self) -> list[int]:
        """One router bookkeeping round: poll every routed request's
        CURRENT replica, collect results, health-gate membership
        (down replicas trigger drain-and-reroute, recovered ones
        rejoin under a new epoch), retry the parked backlog, and
        refresh residency on cadence.  Returns newly completed
        request ids.  Poll and health network I/O run OUTSIDE the
        router lock; re-admission to a replica (the reroute/backlog
        ``enqueue``) runs under it — route-and-record must be atomic
        — so with remote replicas that leg can hold the lock for up
        to the replica timeout per candidate (the bounded stall the
        lock inventory documents)."""
        with self._lock:
            now = self._clock()
            todo = [(req.request_id, req.replica, req.replica_rid,
                     req.epoch)
                    for req in self._requests.values()
                    if req.replica is not None]
            due = [(n, m.replica) for n, m in self._members.items()
                   if now - m.last_health >= self.health_interval]
            replicas = {n: m.replica for n, m in self._members.items()}
            residency_due = (now - self._last_residency
                             >= self.residency_interval)

        polled: dict[int, object] = {}
        assignment = {rid: (name, rrid, ep)
                      for rid, name, rrid, ep in todo}
        dead: set[str] = set()
        for rid, name, rrid, _ep in todo:
            if name in dead:
                continue
            try:
                polled[rid] = replicas[name].poll(rrid)
            except ReplicaUnreachable:
                dead.add(name)
        health: dict[str, bool] = {}
        for n, replica in due:
            if n in dead:
                health[n] = False
                continue
            try:
                health[n] = replica.healthy()
            except Exception:  # noqa: BLE001 — a broken probe is down
                health[n] = False

        completed = []
        with self._lock:
            now = self._clock()
            # Results FIRST, membership second: a request its replica
            # finished just before dying must be recorded, not
            # rerouted (and the inflight accounting must hit the
            # replica that actually served it).
            for rid, res in polled.items():
                req = self._requests.get(rid)
                if req is None or res is None:
                    continue
                name, rrid, _ep = assignment[rid]
                if (req.replica != name or req.replica_rid != rrid
                        or rid in self._completed):
                    # Rerouted/finished while the poll was in flight:
                    # the result belongs to a STALE hop — drop it
                    # (the epoch-stamped-assignment check; recording
                    # it would also debit the new replica's inflight
                    # for work it is still doing).
                    continue
                m = self._members.get(name)
                if m is not None and m.inflight > 0:
                    m.inflight -= 1
                self._finish_locked(req, res.tokens, res.status,
                                    res.prompt_len, error=res.error)
                completed.append(rid)
            for n, ok in health.items():
                m = self._members.get(n)
                if m is None:
                    continue
                m.last_health = now
                if m.up and not ok:
                    m.up = False
                    self.epoch += 1
                    obs.event("router.replica_down", replica=n,
                              epoch=self.epoch)
                    self._reroute_from_locked(n, why="health")
                elif not m.up and ok:
                    m.up = True
                    self.epoch += 1
                    # Its cache died with it: a fresh affinity entry,
                    # refilled from residency on the next refresh.
                    self._affinity.pop(n, None)
                    obs.event("router.replica_up", replica=n,
                              epoch=self.epoch)
            for n in dead:
                m = self._members.get(n)
                if m is not None and m.up:
                    m.up = False
                    self.epoch += 1
                    obs.event("router.replica_down", replica=n,
                              epoch=self.epoch)
                    self._reroute_from_locked(n, why="unreachable")
            # Parked backlog: a replica may have freed capacity.
            still = []
            for rid in self._pending:
                req = self._requests.get(rid)
                if req is None:
                    continue
                if not self._route_locked(req, rerouting=True):
                    still.append(rid)
            self._pending = still
            obs.gauge("router.pending", len(self._pending))
        # Release import pins freed by the finishes/reroutes above —
        # outside the lock (remote unpins are network I/O).
        self._drain_unpins()
        if residency_due:
            self.refresh_residency()
        return completed

    def step(self) -> list[int]:
        """Drive one decode step on every up in-process replica, then
        :meth:`pump`.  Replica engine locks are taken OUTSIDE the
        router lock here (step is long; holding the router lock
        across it would stall concurrent enqueues)."""
        with self._lock:
            reps = [m.replica for m in self._members.values()
                    if m.up and not getattr(m.replica, "remote", False)]
        for r in reps:
            try:
                r.step()
            except Exception:  # noqa: BLE001 — a dying replica's step
                pass           # failure is health probing's to report
        return self.pump()

    # -------------------------------------------------------- lifecycle

    def drain(self, request_id: int, max_steps: int = 100_000):
        """Block until ``request_id`` finishes (driving
        :meth:`step`); returns its result."""
        for _ in range(max_steps):
            with self._lock:
                res = self._completed.get(request_id)
                known = request_id in self._requests
            if res is not None:
                return res
            if not known:
                raise KeyError(f"unknown request {request_id}")
            self.step()
            if self._all_remote():
                time.sleep(self.poll_s)
        raise TimeoutError(
            f"request {request_id} did not finish in {max_steps} "
            "steps")

    def stream(self, request_id: int, max_steps: int = 100_000):
        """Incremental token relay for one request: a generator that
        yields each newly generated token (ints; prompt excluded) as
        the serving replica emits it, ending when the request goes
        terminal — the caller holds the first token long before the
        terminal result, which is what makes a 2-stage disaggregated
        request USABLE.  Reads the replica's live transcript
        (``partial()`` in-process, ``GET /stream`` remote) and drives
        :meth:`step` between reads (same loop shape as :meth:`drain`).
        Reroute-safe because decode is deterministic: a rerouted
        request's regenerated transcript extends the already-streamed
        prefix bit-exactly, so the cursor never rewinds and nothing is
        double-yielded.  Raises ``KeyError`` for unknown ids and
        ``TimeoutError`` past ``max_steps``."""
        emitted = 0
        for _ in range(max_steps):
            with self._lock:
                res = self._completed.get(request_id)
                req = self._requests.get(request_id)
                replica = rrid = None
                if res is None and req is not None \
                        and req.replica is not None:
                    m = self._members.get(req.replica)
                    if m is not None:
                        replica, rrid = m.replica, req.replica_rid
            if res is None and req is None:
                raise KeyError(f"unknown request {request_id}")
            snap = res
            if snap is None and replica is not None \
                    and rrid is not None:
                part = getattr(replica, "partial", None)
                if part is not None:
                    try:
                        snap = part(rrid)
                    except ReplicaUnreachable:
                        snap = None  # pump's reroute will re-home it
            if snap is not None:
                toks = np.asarray(snap.tokens)
                cut = int(snap.prompt_len) + emitted
                if toks.size > cut:
                    for t in toks[cut:]:
                        emitted += 1
                        yield int(t)
                    obs.event("router.stream", request_id=request_id,
                              tokens=emitted)
            if res is not None:
                return
            self.step()
            if self._all_remote():
                time.sleep(self.poll_s)
        raise TimeoutError(
            f"request {request_id} did not finish in {max_steps} "
            "steps of streaming")

    def _all_remote(self) -> bool:
        with self._lock:
            return all(getattr(m.replica, "remote", False)
                       for m in self._members.values()) \
                and bool(self._members)

    def begin_shutdown(self) -> None:
        """Stop admission (enqueue raises :class:`EngineClosed`;
        EngineClosed wins the race with an in-flight enqueue — the
        engine contract, one level up)."""
        with self._lock:
            self._closed = True

    def shutdown(self, max_steps: int | None = None) -> dict:
        """Drain-then-shutdown: stop admission, pump until every
        accepted request is terminal (or ``max_steps`` trips —
        stragglers get structured ``"cancelled"`` results), and return
        all results.  Replica objects are left open: the router does
        not own their lifecycle."""
        self.begin_shutdown()
        steps = 0
        while True:
            with self._lock:
                live = bool(self._requests)
            if not live:
                break
            if max_steps is not None and steps >= max_steps:
                break
            self.step()
            steps += 1
            if self._all_remote():
                time.sleep(self.poll_s)
        with self._lock:
            for req in list(self._requests.values()):
                self._finish_locked(req, req.prompt, "cancelled",
                                    req.prompt.size)
            self._pending = []
        self._drain_unpins()
        return self.results()


# ------------------------------------------------------- the endpoint


class EngineEndpoint:
    """Serve one engine's admission surface over HTTP — the remote
    half of :class:`HttpReplica` (stdlib ``ThreadingHTTPServer``; the
    handlers call the engine's thread-safe admission surface, so this
    module stays jax-free and an endpoint thread can never compile a
    program).

    ================  ====================================================
    route             serves
    ================  ====================================================
    ``POST /enqueue``  ``{"prompt": [...], "max_new_tokens": n, ...}``
                       -> ``{"request_id": id}``; 429 = QueueFull
                       (backpressure), 503 = EngineClosed, 400 =
                       validation error
    ``GET /poll?id=``  the terminal ``RequestResult`` as JSON, or 404
                       while the request decodes
    ``GET /stream?id=`` the LIVE transcript snapshot (``partial()`` —
                       non-terminal ``queued``/``decoding`` statuses
                       included), 404 for unknown ids — the streaming
                       relay's read
    ``POST /prefill``  ``{"prompt": [...]}`` -> the prompt's full-block
                       KV run as a binary block shipment
                       (:func:`~distkeras_tpu.serving.disagg.encode_shipment`);
                       429 = allocator backpressure, 400 = not a paged
                       engine / bad prompt
    ``POST /blocks``   a binary block shipment -> the adoption dict
                       (``{"prefix_id", "blocks", "hits", "bytes"}``);
                       429 = no free block (caller falls back), 400 =
                       malformed/geometry mismatch
    ``POST /unpin``    ``{"prefix_id": id}`` releases a shipped pin;
                       404 = unknown pin
    ``GET /residency`` the engine's residency digest (stem hashes,
                       prefix ids, block, live load — plus the
                       endpoint's ``role`` label) — the router's
                       affinity/load source
    ``GET /healthz``   200 while the engine admits, 503 once closed
    ================  ====================================================

    ``start(step=True)`` also runs the decode loop on a daemon thread
    (the replica-process deployment shape).  When the ``DKT_CLUSTER_*``
    env contract is present (or ``coord_dir=`` is given), the bound
    address publishes to ``<coord_dir>/serve/host<N>.addr`` for
    :func:`discover_replicas` — the same ledger pattern as telemetry
    federation.  ``role=`` labels the replica for the router's
    disaggregated topology (published in the address record, so
    discovery builds role-labeled handles).
    """

    def __init__(self, engine, *, port: int = 0,
                 bind: str = "127.0.0.1", coord_dir: str | None = None,
                 host_id: int | None = None, rid_base: int | None = None,
                 role: str | None = None):
        import os

        self.engine = engine
        self.role = _check_role(role)
        self._want_port = port
        self._bind = bind
        env = os.environ
        if coord_dir is None and "DKT_CLUSTER_DIR" in env:
            coord_dir = env["DKT_CLUSTER_DIR"]
        if host_id is None:
            host_id = int(env.get("DKT_CLUSTER_HOST", "0"))
        self.coord_dir = coord_dir
        self.host_id = host_id
        if rid_base is None:
            rid_base = (host_id + 1) * RID_SPAN
        if engine._next_id < rid_base:
            engine._next_id = rid_base
        self.port = None
        self._httpd = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # ---------------------------------------------------------- serve

    def start(self, step: bool = True,
              idle_s: float = 0.005) -> "EngineEndpoint":
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        from urllib.parse import parse_qs, urlparse

        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            # The engine wire protocol — every route, query param, and
            # status code here is censused by the contract lint and
            # pinned in scripts/obs_schema.json; protocol changes must
            # re-record via `graph_lint.py --contracts --update-budgets`.
            server_version = "dkt-engine/1.0"

            def log_message(self, *a):  # pragma: no cover — quiet
                pass

            def _send_raw(self, code, data, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send(self, code, obj):
                self._send_raw(
                    code, json.dumps(obj, default=_jsonable).encode(),
                    "application/json")

            def do_GET(self):  # noqa: N802 — http.server API
                url = urlparse(self.path)
                try:
                    if url.path == "/poll":
                        q = parse_qs(url.query)
                        rid = int(q.get("id", ["-1"])[0])
                        res = endpoint.engine.poll(rid)
                        if res is None:
                            self._send(404, {"pending": rid})
                        else:
                            self._send(200, _result_doc(res))
                    elif url.path == "/stream":
                        q = parse_qs(url.query)
                        rid = int(q.get("id", ["-1"])[0])
                        res = endpoint.engine.partial(rid)
                        if res is None:
                            self._send(404, {"unknown": rid})
                        else:
                            self._send(200, _result_doc(res))
                    elif url.path == "/residency":
                        doc = dict(endpoint.engine.residency())
                        if endpoint.role is not None:
                            doc["role"] = endpoint.role
                        self._send(200, doc)
                    elif url.path == "/healthz":
                        ok = not endpoint.engine.closed
                        self._send(200 if ok else 503, {"ok": ok})
                    else:
                        self._send(404, {"error": f"unknown "
                                         f"{url.path}"})
                except BrokenPipeError:  # pragma: no cover
                    pass
                except Exception as e:  # noqa: BLE001 — keep serving
                    try:
                        self._send(500,
                                   {"error": f"{type(e).__name__}: "
                                             f"{e}"})
                    except Exception:  # pragma: no cover
                        pass

            def _post_enqueue(self, raw):
                body = json.loads(raw or b"{}")
                prompt = np.asarray(body.pop("prompt"), np.int32)
                max_new = int(body.pop("max_new_tokens"))
                try:
                    rid = endpoint.engine.enqueue(prompt, max_new,
                                                  **body)
                except QueueFull as e:
                    self._send(429, {"error": str(e)})
                    return
                except EngineClosed as e:
                    self._send(503, {"error": str(e)})
                    return
                except (ValueError, KeyError) as e:
                    self._send(400, {"error": str(e)})
                    return
                self._send(200, {"request_id": rid})

            def _post_prefill(self, raw):
                body = json.loads(raw or b"{}")
                prompt = np.asarray(body["prompt"], np.int32)
                try:
                    ship = endpoint.engine.export_blocks(prompt)
                except EngineClosed as e:
                    self._send(503, {"error": str(e)})
                    return
                except RuntimeError as e:  # allocator full
                    self._send(429, {"error": str(e)})
                    return
                except (ValueError, KeyError, AttributeError) as e:
                    # Not a paged engine / bad prompt geometry.
                    self._send(400, {"error": str(e)})
                    return
                self._send_raw(200, encode_shipment(ship),
                               "application/octet-stream")

            def _post_blocks(self, raw):
                try:
                    out = endpoint.engine.import_blocks(
                        decode_shipment(raw))
                except EngineClosed as e:
                    self._send(503, {"error": str(e)})
                    return
                except (ValueError, AttributeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                if out is None:
                    self._send(429, {"error": "allocator "
                                     "backpressure: no free block "
                                     "for adoption"})
                    return
                self._send(200, out)

            def _post_unpin(self, raw):
                body = json.loads(raw or b"{}")
                try:
                    endpoint.engine.unpin_prefix(
                        int(body["prefix_id"]))
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                    return
                self._send(200, {"ok": True})

            def do_POST(self):  # noqa: N802 — http.server API
                url = urlparse(self.path)
                routes = {"/enqueue": self._post_enqueue,
                          "/prefill": self._post_prefill,
                          "/blocks": self._post_blocks,
                          "/unpin": self._post_unpin}
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    raw = self.rfile.read(n)
                    handler = routes.get(url.path)
                    if handler is None:
                        self._send(404, {"error": f"unknown "
                                         f"{url.path}"})
                        return
                    handler(raw)
                except BrokenPipeError:  # pragma: no cover
                    pass
                except Exception as e:  # noqa: BLE001 — keep serving
                    try:
                        self._send(500,
                                   {"error": f"{type(e).__name__}: "
                                             f"{e}"})
                    except Exception:  # pragma: no cover
                        pass

        if self._httpd is not None:
            raise RuntimeError("endpoint already started")
        self._httpd = ThreadingHTTPServer((self._bind, self._want_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             kwargs={"poll_interval": 0.2},
                             name="dkt-engine-endpoint", daemon=True)
        t.start()
        self._threads.append(t)
        if step:
            s = threading.Thread(target=self._step_loop,
                                 args=(idle_s,),
                                 name="dkt-engine-step", daemon=True)
            s.start()
            self._threads.append(s)
        self._publish_addr()
        return self

    def _step_loop(self, idle_s: float) -> None:
        while not self._stop.is_set():
            eng = self.engine
            if eng.running() or eng.queued:
                try:
                    eng.step()
                except Exception:  # noqa: BLE001 — a step crash must
                    self._stop.wait(idle_s)  # not spin the thread hot
            else:
                self._stop.wait(idle_s)

    @property
    def addr(self) -> str:
        return f"{self._bind}:{self.port}"

    def _publish_addr(self) -> None:
        import os

        if self.coord_dir is None:
            return
        d = os.path.join(self.coord_dir, "serve")
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".addr.{self.host_id}.{os.getpid()}.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"host": self.host_id, "addr": self.addr,
                       "pid": os.getpid(), "role": self.role}, f)
        os.replace(tmp, os.path.join(d, f"host{self.host_id}.addr"))

    def _unpublish_addr(self) -> None:
        import os

        if self.coord_dir is None:
            return
        try:
            os.remove(os.path.join(self.coord_dir, "serve",
                                   f"host{self.host_id}.addr"))
        except OSError:
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        self._unpublish_addr()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def _result_doc(res: RequestResult) -> dict:
    return {"request_id": int(res.request_id),
            "tokens": np.asarray(res.tokens, np.int32).tolist(),
            "status": res.status,
            "prompt_len": int(res.prompt_len), "error": res.error}


def _jsonable(o):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    return str(o)


__all__ = ["Router", "InProcessReplica", "HttpReplica",
           "EngineEndpoint", "ReplicaUnreachable", "discover_replicas",
           "RID_SPAN"]
