"""Elastic lane tiers: load-driven resizing over pre-compiled programs.

Two mixins: :class:`_ElasticMixin` is the engine-level hysteresis
bookkeeping every :class:`~distkeras_tpu.serving.engine._LaneEngine`
carries (inert unless ``lane_tiers`` is set) — sustained ``enqueue``
overflow steps the lane count up one tier, sustained idle steps it
back down, and a resize compacts occupied lanes through a
pre-compiled gather.  :class:`_ElasticLanesMixin` is
:class:`~distkeras_tpu.serving.lanes.ContinuousBatcher`'s device half:
the dummy-state warmup that compiles EVERY tier's programs (decode
windows, admission buckets — chunked-prefill continuations and
prefix-pool gathers included — and the inter-tier resize gathers) at
construction, so no request ever pays a recompile
(``scripts/check_compile_counts.py``'s ``serving_elastic`` session
asserts the serve phase compiles ZERO and pins the budget).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs


class _ElasticMixin:
    """Host-side tier hysteresis; inert when ``lane_tiers`` is None."""

    def _try_scale_up(self) -> bool:
        """One overflow strike; step the lane tier up once the
        backpressure is *sustained* (``scale_up_after`` consecutive
        overflowing enqueues).  Returns whether a resize happened —
        False means the caller raises QueueFull (non-elastic engine,
        top tier reached, or not sustained yet)."""
        if self.lane_tiers is None:
            return False
        i = self.lane_tiers.index(self.lanes)
        if i + 1 >= len(self.lane_tiers):
            return False
        self._bp_strikes += 1
        if self._bp_strikes < self.scale_up_after:
            return False
        self._resize_to(self.lane_tiers[i + 1])
        return True

    def _maybe_scale_down(self) -> None:
        """Hysteresis mirror of :meth:`_try_scale_up`: after
        ``scale_down_after`` consecutive steps with the queue empty and
        occupancy at or under the next tier down, shrink to it (free
        lanes burn a row of decode compute each step — the whole point
        of stepping back down).  Runs under the admission lock: the
        resize compacts ``_lane_state``, which a concurrent
        ``enqueue`` (the documented thread-safe surface) must never
        observe mid-move."""
        if self.lane_tiers is None:
            return
        with self._admission_lock:
            i = self.lane_tiers.index(self.lanes)
            if i == 0:
                return
            lower = self.lane_tiers[i - 1]
            busy = sum(1 for s in self._lane_state if s is not None)
            if busy <= lower and not self._pending:
                self._idle_strikes += 1
            else:
                self._idle_strikes = 0
                return
            if self._idle_strikes >= self.scale_down_after:
                self._resize_to(lower)

    def _resize_to(self, tier: int) -> None:
        """Move the engine to ``tier`` lanes through the pre-compiled
        resize program: occupied lanes compact into the low indices
        (their device rows gathered, their host records remapped —
        the chunked-admission queue included), new lanes arrive free
        (stale rows — masked until admission overwrites them, the same
        contract as lane reuse).  Strictly host-plus-precompiled work:
        no compile, ever (pinned by ``scripts/check_compile_counts.py``'s
        elastic session)."""
        old = self.lanes
        keep = [i for i, s in enumerate(self._lane_state)
                if s is not None]
        assert len(keep) <= tier, "resize below occupancy"
        idx = keep + [0] * (tier - len(keep))
        # numpy, not jnp.asarray(list): the latter jit-compiles a
        # convert_element_type per target length — a recompile the
        # elastic session's zero-compile assertion would catch.
        self._resize_state(np.asarray(idx, np.int32))
        state: list = [None] * tier
        new_of = {}
        for j, i in enumerate(keep):
            state[j] = self._lane_state[i]
            new_of[i] = j
        self._lane_state = state
        # Parked (chunk-admitting) lanes moved with the compaction;
        # their queue entries follow, order preserved.
        self._admitting = collections.deque(
            new_of[l] for l in self._admitting)
        self.lanes = tier
        self.tier_epoch += 1
        self._bp_strikes = self._idle_strikes = 0
        obs.gauge("serving.lanes_tier", tier)
        obs.count("serving.resizes",
                  direction="up" if tier > old else "down")
        obs.event("serving.resize", from_lanes=old, to_lanes=tier,
                  tier_epoch=self.tier_epoch)

    def _resize_state(self, idx) -> None:  # pragma: no cover
        raise NotImplementedError(
            "this engine does not support lane_tiers")


class _ElasticLanesMixin:
    """ContinuousBatcher's device half of elasticity: per-tier dummy
    states, the construction-time warmup, and the resize gather."""

    def _make_resize(self):
        """Build the jitted inter-tier resize program.  The default
        gathers lanes ``idx[j] -> j`` across the WHOLE device state —
        cache (lane axis 1) plus row metadata (axis 0); jit
        specializes one program per (from, to) tier pair, all warmed
        by :meth:`_compile_tiers`.  Sharded engines re-pin the
        gathered cache with the plan's KV constraint so the output
        placement matches the live slab exactly (placement is part of
        the jit cache key — a drifting layout would surface as a
        serve-phase recompile, which the elastic compile sessions
        assert never happens).  The paged engine overrides this with a
        rows-only gather: its slab is lane-independent."""
        constrain = self._kv_constraint

        def resize(cache, cur, pos, keys, temps, tps, mps, idx):
            cache = jax.tree.map(
                lambda a: jnp.take(a, idx, axis=1), cache)
            if constrain is not None:
                cache = constrain(cache)
            g = lambda a: jnp.take(a, idx, axis=0)
            return (cache, g(cur), g(pos), g(keys), g(temps),
                    g(tps), g(mps))

        # No donation: the gathered output has a different lane
        # count, so nothing could be reused in place anyway (and
        # XLA would warn on every tier pair).
        return jax.jit(resize)

    def _tier_state(self, tier: int):
        """A dummy device state at ``tier`` lanes with EXACTLY the live
        state's avals — the warmup vehicle that populates the jit
        caches every tier will hit.  Returned in step-argument order
        ``(cache, cur, pos, keys, temps, tps, mps)`` — the cache comes
        from the engine's ``_fresh_cache`` layout hook, so the paged
        engine's warmup dummies are block slabs like its live state."""
        cache = self._fresh_cache(tier)
        cur = jnp.zeros((tier,), jnp.int32)
        pos = jnp.zeros((tier,), jnp.int32)
        keys = (jnp.stack([jax.random.key(0)] * tier) if self._keyed
                else jnp.zeros((tier,), jnp.int32))
        if self.per_request_sampling:
            temps = jnp.full((tier,), float(self.temperature),
                             jnp.float32)
            tps = jnp.full((tier,), float(self.top_p or 1.0),
                           jnp.float32)
            mps = jnp.full((tier,), float(self.min_p or 0.0),
                           jnp.float32)
        else:
            temps = tps = mps = jnp.zeros((tier,), jnp.float32)
        # Sharded engines commit rows replicated (lanes.py
        # _place_rows): dummy and live placement must agree or the
        # warm-up misses the live state's jit cache entries.
        cur, pos, keys, temps, tps, mps = self._place_rows(
            cur, pos, keys, temps, tps, mps)
        return cache, cur, pos, keys, temps, tps, mps

    def _warm_tier(self, tier: int) -> None:
        """Compile one tier's worth of programs against dummy state:
        every declared step window, every admission bucket (seeded —
        prefix-pool gather included — and, under chunked prefill, the
        continuation program per bucket), the prefix reseed, and the
        tiny host-scatter programs ``submit`` touches.  Split into the
        three stages below (round 12) so the paged engine can swap
        the step/admission halves — its programs take page tables —
        while the shell and the host-scatter warmers stay shared."""
        self._warm_steps(tier)
        self._warm_admission(tier)
        self._warm_host_writes(tier)

    def _warm_steps(self, tier: int) -> None:
        for n in self._step_windows:
            if n not in self._steps:
                self._steps[n] = self._make_step(n)
        for n in self._step_windows:
            # The step donates its cache: a fresh dummy per window.
            # Hot-swap engines pass the LIVE params (committed arrays
            # — their shardings are part of the jit cache key, so the
            # warm entry is exactly the one swap_params' replacements
            # will hit).
            self._steps[n](*self._pargs(), *self._tier_state(tier))

    def _warm_admission(self, tier: int) -> None:
        pool = self._prefix_pool
        for width in self._buckets:
            rows = jnp.zeros((1, width), jnp.int32)
            cache = self._tier_state(tier)[0]
            if pool is not None:
                self._admit(cache, rows, jnp.int32(0), jnp.int32(0),
                            pool.slab, jnp.int32(-1))
            else:
                self._admit(*self._pargs(), cache, rows, jnp.int32(0),
                            jnp.int32(self._off))
            if self._admit_cont is not None:
                self._admit_cont(*self._pargs(),
                                 self._tier_state(tier)[0], rows,
                                 jnp.int32(0), jnp.int32(0))
        if self._prefix_lane is not None:
            self._reseed(self._tier_state(tier)[0], jnp.int32(0))
        if pool is not None:
            self._reseed_pool(self._tier_state(tier)[0], jnp.int32(0),
                              pool.slab, jnp.int32(0))

    def _warm_host_writes(self, tier: int) -> None:
        # submit()'s host bookkeeping (lane-slot writes) specializes
        # per tier too — tiny scatters, but a compile is a compile.
        # Placed like the live rows (sharded engines commit them
        # replicated), or the live scatter would miss this warm entry.
        ints = self._place_replicated(jnp.zeros((tier,), jnp.int32))
        ints.at[0].set(0)
        if self._keyed:
            self._place_replicated(
                jnp.stack([jax.random.key(0)] * tier)).at[0].set(
                jax.random.key(0))
        if self.per_request_sampling:
            self._place_replicated(
                jnp.zeros((tier,), jnp.float32)).at[0].set(0.0)

    def _compile_tiers(self) -> None:
        """Compile EVERY tier's programs up front, plus the resize
        gathers between adjacent tiers (both directions).  After this,
        the elastic engine's whole lifetime — admissions, decode
        windows, tier moves — runs on warm jit caches; the
        ``serving_elastic`` budget in scripts/compile_budget.json pins
        exactly that."""
        with obs.span("serving.compile_tiers", tiers=self.lane_tiers):
            for tier in self.lane_tiers:
                self._warm_tier(tier)
            for a, b in zip(self.lane_tiers, self.lane_tiers[1:]):
                for frm, to in ((a, b), (b, a)):
                    self._warm_resize(frm, to)

    def _warm_resize(self, frm: int, to: int) -> None:
        """Trace+compile the ``frm -> to`` resize gather against dummy
        state (one jit specialization per tier pair).  Split out of
        :meth:`_compile_tiers` so the paged engine can warm its
        rows-only variant with the same loop."""
        cache, cur, pos, keys, temps, tps, mps = self._tier_state(frm)
        self._resize(cache, cur, pos, keys, temps, tps, mps,
                     jnp.zeros((to,), jnp.int32))

    def _resize_state(self, idx) -> None:
        (self.cache, self.cur, self.pos, self.keys, self.temps,
         self.tps, self.mps) = self._resize(
            self.cache, self.cur, self.pos, self.keys, self.temps,
            self.tps, self.mps, idx)


__all__ = ["_ElasticMixin", "_ElasticLanesMixin"]
