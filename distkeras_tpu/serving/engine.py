"""Shared lane machinery for the serving engines.

``_LaneEngine`` is the host-side core both engines build on: the lane
table (free/running/drain), the per-step emission loop, the chunked-
prefill scheduler, and — via the mixins it composes — admission
control (:mod:`distkeras_tpu.serving.admission`) and elastic lane
tiers (:mod:`distkeras_tpu.serving.elastic`).  The compiled-program
factories for single-lane admission live here too, shared by
:class:`~distkeras_tpu.serving.lanes.ContinuousBatcher` and
:class:`~distkeras_tpu.serving.speculative.SpeculativeBatcher`.

Everything in this module is host bookkeeping or a jit factory; the
decode-step programs themselves are each engine's own.
"""

from __future__ import annotations

import collections
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.models.generate import _decode_chunk
from distkeras_tpu.serving.admission import _AdmissionMixin
from distkeras_tpu.serving.elastic import _ElasticMixin


@dataclasses.dataclass
class _Lane:
    request_id: int
    prompt_len: int
    max_new: int
    key: object          # per-request PRNG key (None for greedy)
    tokens: list         # host-side transcript, prompt included
    done: bool = False
    eos: object = None   # per-request eos token (engine default)
    deadline: float | None = None  # absolute clock() time; None = none
    managed: bool = False  # admitted via enqueue(): auto-collected
    born: float | None = None  # clock() at admission (obs latency)
    # Chunked prefill (round-10): remaining (start, rows) admission
    # chunks; non-None means the lane is still ADMITTING — parked out
    # of the emission loop until the last chunk lands.
    chunks: list | None = None
    # Shared-prefix bookkeeping: the request's prefix length (0 =
    # none) and its PrefixPool id (refcount released at vacation).
    off: int = 0
    prefix_id: int | None = None
    # Engine-clock time of the lane's previous emission (TTFT/TPOT
    # telemetry; None until the first token lands).
    last_emit: float | None = None


def _make_lane_admit(model_params, model_cfg, prefix_lane=None,
                     pooled: bool = False, seed: bool = True,
                     constrain=None, take_params: bool = False):
    """ONE-lane admission program factory shared by both engines:
    prefill ``rows`` (bucket-padded) into a single lane's cache slice
    at traced start position ``off``, seeded from the engine's static
    ``prefix_lane``, from a :class:`PrefixPool` slab gather
    (``pooled=True`` — the program takes ``(slab, slot)``; ``slot < 0``
    means "no prefix", seeding zeros), or from zeros — a fresh
    occupant must never see the previous request's K/V beyond its own
    positions.  ``seed=False`` builds the CONTINUATION program for
    chunked prefill: the chunk lands on the lane's existing cache
    (earlier chunks) untouched.

    ``off`` is traced, so one program per bucket-padded ``rows`` shape
    serves every prefix length and every chunk offset.

    ``constrain``: sharding-constraint hook (pod-sharded engines pass
    the KV-slab constraint so GSPMD pins the cache layout inside the
    compiled program instead of inferring it per call).

    ``take_params=True`` builds the hot-swap spelling (round 20): the
    program takes the param tree as its FIRST argument instead of
    closing over it, so a live weight push is a plain argument change
    on a warm jit cache — same avals + same committed shardings = the
    exact cache entry, zero recompiles (the ``serving_weight_push``
    compile session pins it).  The cache is still the donated buffer
    (argnums shifts to 1); params are never donated — version N must
    survive the swap for rollback.
    """
    def _admit(params, cache, rows, lane, off, *pool):
        if constrain is not None:
            cache = constrain(cache)
        lane_cache = jax.tree.map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, lane, 1, axis=1),
            cache)
        if seed:
            if pooled:
                slab, slot = pool
                # Gather the segment; slot < 0 selects the zero seed
                # (the gather still runs — admission is off the decode
                # hot path and a branch would compile both sides
                # anyway).
                seg = jax.tree.map(
                    lambda a: jnp.take(a, jnp.maximum(slot, 0), axis=0),
                    slab)
                lane_cache = jax.tree.map(
                    lambda z, pre: jnp.where(slot >= 0,
                                             pre.astype(z.dtype),
                                             jnp.zeros_like(z)),
                    lane_cache, seg)
            elif prefix_lane is not None:
                # prefill() returns a full-max_len cache with the
                # prefix slots filled and the rest zero — exactly the
                # fresh-lane seed we need.
                lane_cache = jax.tree.map(
                    lambda z, pre: pre.astype(z.dtype),
                    lane_cache, prefix_lane)
            else:
                lane_cache = jax.tree.map(jnp.zeros_like, lane_cache)
        _, lane_cache = _decode_chunk(
            params, lane_cache, rows,
            jnp.reshape(off, (1,)).astype(jnp.int32), model_cfg,
            uniform_pos=True)
        out = jax.tree.map(
            lambda a, u: jax.lax.dynamic_update_slice_in_dim(
                a, u, lane, axis=1), cache, lane_cache)
        return constrain(out) if constrain is not None else out

    if take_params:
        return jax.jit(_admit, donate_argnums=1)

    def admit(cache, rows, lane, off, *pool):
        return _admit(model_params, cache, rows, lane, off, *pool)
    return jax.jit(admit, donate_argnums=0)


def _make_lane_reseed(prefix_lane=None, pooled: bool = False,
                      constrain=None):
    """Prefix copy into one lane WITHOUT an admission chunk (1-token
    prompts skip the chunk but still need the prefix K/V)."""
    def reseed(cache, lane, *pool):
        if pooled:
            slab, slot = pool
            pre = jax.tree.map(lambda a: jnp.take(a, slot, axis=0), slab)
        else:
            pre = prefix_lane
        out = jax.tree.map(
            lambda a, p: jax.lax.dynamic_update_slice_in_dim(
                a, p.astype(a.dtype), lane, axis=1), cache, pre)
        return constrain(out) if constrain is not None else out
    return jax.jit(reseed, donate_argnums=0)


class _LaneEngine(_AdmissionMixin, _ElasticMixin):
    """Host-side lane machinery shared by the serving engines: the
    lane table, free/running/drain, the per-step emission loop (append
    to the transcript, stop at budget or the lane's eos), and the
    chunked-prefill scheduler.

    Also composes the admission-control layer (resilience subsystem —
    deadlines/TTLs, the bounded FIFO queue with :class:`QueueFull`
    backpressure, structured :class:`RequestResult` reporting, the
    drain-then-shutdown lifecycle) and the elastic-tier bookkeeping.
    All of it is host bookkeeping — the compiled decode programs and
    their exact-parity contract are untouched (an evicted lane just
    stops being read; its rows keep burning compute until admission
    reseeds them, same as any done lane)."""

    # Engines without a pool leave this None; ContinuousBatcher /
    # SpeculativeBatcher set it from their ``prefix_pool=`` argument.
    _prefix_pool = None

    # Pod-sharded serving (round 14): ``mesh``/``_kv_axis`` are set by
    # ContinuousBatcher(plan=..., mesh=...); every other engine runs
    # single-placement and these defaults keep the helpers no-ops.
    mesh = None
    plan = None
    _kv_axis = None

    # Live weight push (round 20): engines built with
    # ``hot_swap=True`` compile their decode/admission programs to
    # take the param tree as an ARGUMENT (see ``_make_lane_admit``'s
    # ``take_params``), so :meth:`swap_params` is a warm-cache
    # argument change.  ``param_version`` is 0 until the first swap —
    # every engine carries it (the router's fleet snapshot reads it
    # unconditionally).
    _hot_swap = False
    param_version = 0

    def _pargs(self) -> tuple:
        """The params-argument prefix of every compiled-program call:
        ``(params,)`` on a hot-swap engine, ``()`` otherwise — ONE
        spelling at every dispatch/warm-up site, so the two engine
        modes cannot drift."""
        return (self.params,) if self._hot_swap else ()

    def swap_params(self, new_params, version: int,
                    allow_downgrade: bool = False) -> int:
        """Replace the engine's weights BETWEEN steps (round 20): the
        new tree is placed with the live params' exact shardings, so
        every warm program is a jit cache hit — zero recompiles (the
        ``serving_weight_push`` session pins it).  In-flight requests
        continue mid-stream on the new weights over their existing
        K/V (the documented mixed-cache contract: tokens emitted
        under version N are bit-deterministic functions of version N).

        ``version`` must be strictly greater than ``param_version``
        unless ``allow_downgrade=True`` — the canary controller's
        rollback is the one legitimate downgrade.  Geometry is
        validated leaf-for-leaf; a mismatched tree raises and the
        engine keeps serving its current version.  Returns the new
        ``param_version``."""
        if not self._hot_swap:
            raise ValueError(
                "engine was built without hot_swap=True: its programs "
                "closed over the weights at compile time, so a swap "
                "would recompile everything — rebuild with "
                "hot_swap=True for live weight push")
        version = int(version)
        with self._admission_lock:
            if version <= self.param_version and not allow_downgrade:
                raise ValueError(
                    f"swap_params(version={version}) ≤ live version "
                    f"{self.param_version}: versions are monotone "
                    "(rollback passes allow_downgrade=True)")
            old_leaves, old_def = jax.tree_util.tree_flatten(
                self.params)
            new_leaves, new_def = jax.tree_util.tree_flatten(
                new_params)
            if old_def != new_def:
                raise ValueError(
                    f"swap_params: param tree structure changed "
                    f"({new_def} vs live {old_def}) — a push must "
                    "carry the exact geometry the engine compiled "
                    "for")
            for i, (o, nw) in enumerate(zip(old_leaves, new_leaves)):
                if (tuple(np.shape(nw)) != tuple(o.shape)
                        or jnp.asarray(nw).dtype != o.dtype):
                    raise ValueError(
                        f"swap_params: leaf {i} is "
                        f"[{np.shape(nw)} {jnp.asarray(nw).dtype}], "
                        f"engine compiled for [{tuple(o.shape)} "
                        f"{o.dtype}]")
            # Placement must REPRODUCE the live tree's exactly — avals
            # plus committed-ness are the jit cache key, so the swap
            # is invisible to the compiler.  Unsharded engines placed
            # via asarray (uncommitted, like every other engine; a
            # committed replacement would re-key every warm program);
            # pod-sharded engines re-commit to the live shardings.
            if self.mesh is None:
                self.params = jax.tree.map(jnp.asarray, new_params)
            else:
                self.params = jax.device_put(
                    new_params,
                    jax.tree.map(lambda l: l.sharding, self.params))
            old = self.param_version
            self.param_version = version
            obs.count("serving.param_swaps")
            obs.event("serving.param_swap", version=version,
                      from_version=old, engine=type(self).__name__)
            return version

    # ----------------------------------------- sharded-placement hooks

    def _place_replicated(self, x):
        """Commit a host/device array REPLICATED over the serving mesh
        (no-op unsharded).  Row metadata and page tables go through
        here: placement is part of the jit cache key for committed
        arrays, so warm-up dummies and live state must agree or the
        serve phase pays a recompile."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(x, NamedSharding(self.mesh,
                                               PartitionSpec()))

    def _put_host(self, arr):
        """Host numpy -> device array: plain ``device_put`` unsharded,
        replicated over the serving mesh when sharded (page tables and
        table rows ride this — their placement must be identical
        between warm-up and live pushes)."""
        if self.mesh is None:
            return jax.device_put(arr)
        return self._place_replicated(arr)

    def _kv_shardings(self, tree):
        """NamedShardings placing a KV cache/slab tree under the
        engine's plan: kv-heads dimension over the derived axis,
        everything else replicated (``parallel/rules.py``)."""
        from distkeras_tpu.parallel.rules import kv_slab_shardings

        return kv_slab_shardings(self.mesh, tree, self._kv_axis)

    def _place_kv(self, tree):
        """Commit a KV cache/slab with the plan-derived sharding
        (no-op unsharded)."""
        if self.mesh is None:
            return tree
        return jax.device_put(tree, self._kv_shardings(tree))

    def _constrain_kv(self, tree):
        """``with_sharding_constraint`` pinning the KV layout inside a
        compiled program, or None when the engine is unsharded — the
        program factories pass this straight to their ``constrain=``
        hooks, so GSPMD places the per-token collectives against a
        DECLARED slab layout instead of one inferred per call."""
        return jax.lax.with_sharding_constraint(
            tree, self._kv_shardings(tree))

    @property
    def _kv_constraint(self):
        return self._constrain_kv if self.mesh is not None else None

    def memory_footprint(self) -> dict:
        """Param and KV bytes, total and per device (max over
        addressable devices) — read from the LIVE arrays' addressable
        shards, the same ground-truth accounting ``zero=3`` uses for
        its per-device claim.  Replicated leaves count fully on every
        device; sharded leaves count 1/n — so the per-device figures
        ARE the claim ``plan=`` makes (bench rows and
        tests/test_serving_sharded.py assert from here)."""
        def account(tree):
            total, per_dev = 0, {}
            for leaf in jax.tree.leaves(tree):
                total += leaf.nbytes
                for sh in leaf.addressable_shards:
                    key = repr(sh.device)
                    per_dev[key] = per_dev.get(key, 0) \
                        + sh.data.nbytes
            return total, max(per_dev.values())

        p_total, p_dev = account(self.params)
        kv_total, kv_dev = account(self.cache)
        return {"param_bytes": p_total,
                "param_bytes_per_device": p_dev,
                "kv_bytes": kv_total,
                "kv_bytes_per_device": kv_dev}

    def free_lanes(self):
        return [i for i, s in enumerate(self._lane_state) if s is None]

    def running(self):
        return [i for i, s in enumerate(self._lane_state)
                if s is not None and not s.done]

    def drain(self, lane):
        """Return the finished lane's [prompt + generation] tokens and
        free the lane; raises if the lane is still running."""
        st = self._lane_state[lane]
        if st is None:
            raise ValueError(f"lane {lane} is empty")
        if not st.done:
            raise ValueError(f"lane {lane} is still decoding")
        self._vacate(lane)
        self._obs_request_done("ok", st.born, rid=st.request_id)
        return np.asarray(st.tokens, np.int32)

    def _vacate(self, lane) -> None:
        """THE one lane-release path (drain, reap, eviction, shutdown
        cancellation): frees the lane slot, drops it from the chunked-
        admission queue, releases its prefix-pool pin, and hands the
        lane's storage back through :meth:`_release_lane_storage`."""
        st = self._lane_state[lane]
        self._lane_state[lane] = None
        if st is None:
            return
        if st.chunks is not None:
            try:
                self._admitting.remove(lane)
            except ValueError:  # pragma: no cover — defensive
                pass
        if st.prefix_id is not None and self._prefix_pool is not None:
            self._prefix_pool.release(st.prefix_id)
        self._release_lane_storage(lane, st)

    def _release_lane_storage(self, lane, st) -> None:
        """Storage-layout hook of :meth:`_vacate`: monolithic engines
        own a fixed cache row per lane (nothing to release); the paged
        engine drops the lane's block references here — the ONE place,
        so no eviction path can leak a block."""

    def residency(self) -> dict:
        """The engine's residency digest (round 13): what a cache-
        aware router needs to route on — resident prefix-pool ids,
        resident paged stem hashes (the paged engine overrides to
        fill them), and the live load signals.  Ground truth, cheap
        (host counters + id lists, no device work), JSON-safe; served
        live by the ``/residency`` telemetry endpoint and consumed by
        :class:`~distkeras_tpu.serving.router.Router`.

        Mesh-agnostic by construction: the digests are host-side chain
        hashes of token content (serving/residency.py), so a
        pod-SHARDED engine publishes exactly the digests its solo twin
        would — to the router, one sharded engine is ONE replica
        handle whose mesh is an implementation detail
        (``model_shards`` is surfaced for operators only, never
        scored)."""
        with self._admission_lock:
            return {
                "engine": type(self).__name__,
                "lanes": self.lanes,
                "model_shards": (int(self.mesh.shape[self._kv_axis])
                                 if self._kv_axis is not None else 1),
                "lanes_busy": len(self.running()),
                "queue_depth": len(self._pending),
                "block": None,
                "prefix_ids": (self._prefix_pool.ids()
                               if self._prefix_pool is not None
                               else []),
                "stem_hashes": [],
                "param_version": int(self.param_version),
            }

    def _validate_request_args(self, prompt, max_new_tokens: int):
        """The prompt/budget checks every engine's submit() runs —
        one definition (ContinuousBatcher and SpeculativeBatcher must
        not drift); returns the canonicalized 1-D int32 prompt."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        return prompt

    def _emit(self, lane_tokens):
        """Feed each live lane's new tokens (``lane_tokens(lane)``)
        through the transcript/budget/eos bookkeeping; returns the
        ``{lane: [emitted...]}`` step result.  The ONE site that
        counts emitted tokens (``serving.tokens``) — every step path
        funnels through here, so the throughput metric is
        structurally complete, and so are the per-request latency
        signals it derives: ``serving.ttft_s`` (born -> first token,
        queue wait included for managed requests) and
        ``serving.tpot_s`` (inter-token gap per emitted token), plus
        one ``serving.emit`` trace event per emitting lane carrying
        its ``request_id`` — the decode leg of the request waterfall
        (``scripts/obs_report.py --request``).  Lanes still ADMITTING
        (pending prefill chunks) are parked: their decode rows are
        burnt compute, never emission."""
        out = {}
        active = obs.active() is not None
        now = self._clock() if active else None
        for lane, st in enumerate(self._lane_state):
            if st is None or st.done or st.chunks is not None:
                continue
            emitted = []
            for tok in lane_tokens(lane):
                st.tokens.append(int(tok))
                emitted.append(int(tok))
                budget = len(st.tokens) - st.prompt_len >= st.max_new
                if budget or (st.eos is not None and tok == st.eos):
                    st.done = True
                    break
            out[lane] = emitted
            if active and emitted:
                first = (len(st.tokens) - st.prompt_len
                         == len(emitted))
                if first and st.born is not None:
                    obs.observe("serving.ttft_s", now - st.born)
                elif st.last_emit is not None:
                    obs.observe("serving.tpot_s",
                                (now - st.last_emit) / len(emitted))
                st.last_emit = now
                obs.event("serving.emit", request_id=st.request_id,
                          lane=lane, n=len(emitted), first=first)
        if active:
            obs.count("serving.tokens",
                      sum(len(v) for v in out.values()))
        return out

    # --------------------------------------------- chunked admission

    def _run_pending_chunk(self) -> None:
        """Execute ONE pending admission chunk (FIFO across admitting
        lanes) — called at the top of every ``step()``, so a long
        prompt's prefill interleaves with decode at one chunk per step
        and the other lanes' inter-token gap stays bounded by one
        chunk.  Completing the last chunk un-parks the lane: its
        position/current-token are set and it joins THIS step's decode
        (the same "admission then the next step processes the final
        prompt token" convention as monolithic admission)."""
        if not self._admitting:
            return
        lane = self._admitting[0]
        st = self._lane_state[lane]
        start, rows = st.chunks.pop(0)
        with obs.span("serving.admit_chunk", bucket=rows.shape[1],
                      remaining=len(st.chunks),
                      request_id=st.request_id):
            self._exec_chunk(lane, start, rows)
        if not st.chunks:
            self._admitting.popleft()
            st.chunks = None
            self._finish_admission(lane, st)

    def _exec_chunk(self, lane, start, rows):  # pragma: no cover
        raise NotImplementedError(
            "this engine does not support chunked prefill")

    def _finish_admission(self, lane, st):  # pragma: no cover
        raise NotImplementedError(
            "this engine does not support chunked prefill")


__all__ = ["_Lane", "_LaneEngine", "_make_lane_admit",
           "_make_lane_reseed"]
