"""SLO-driven autoscaling control plane over the fleet router.

ROADMAP item 2: every signal and actuator this loop needs has existed
since rounds 8–17 — ``slo.breach`` subscriber callbacks and windowed
percentiles, per-replica queue/busy/residency state, membership
epochs, lossless drain-and-reroute — but nothing *closed* the loop.
This module is the policy layer that does, and it is deliberately
dumb: a :class:`Autoscaler` reads ONE consistent
:meth:`~distkeras_tpu.serving.router.Router.fleet_snapshot` per
decision tick, applies a thresholds-plus-hysteresis policy
(:class:`AutoscalePolicy`), and actuates through the router's
existing membership surface.  Three load-bearing contracts:

- **Warm-pool joins are zero-compile by construction.**  Scale-up
  never builds an engine: it admits a handle from a :class:`WarmPool`
  of replicas whose programs were compiled BEFORE they were pooled.
  A candidate is health-gated before ``add_replica`` and verified
  live after it — a replica that died in the pool (or mid-join) is
  discarded without ever holding a route-table entry, and the next
  pool candidate is tried.  The ``serving_autoscale`` session in
  ``scripts/check_compile_counts.py`` pins the zero-compile claim.
- **Scale-down is the existing lossless drain-and-reroute** under a
  bumped membership epoch (``Router.remove_replica``): unfinished
  accepted requests re-admit elsewhere idempotently by request id,
  so a retire costs latency, never a caller-visible loss.  The
  retired handle returns to the warm pool still warm (its compiled
  programs survive), unless a ``release=`` hook takes ownership.
- **A replica that is the last holder of pinned prefix state is
  never retired.**  Pool entries and shipped disagg blocks are
  replica-local: draining the only replica advertising a
  ``prefix_id`` would drop pinned state callers still reference
  (pinned admissions to it would become structured errors).  The
  retire path skips such victims; when no safe victim exists the
  scale-down is *deferred* (``autoscale.retire_deferred``) until an
  unpin makes one — the refusal the regression test pins.

Determinism: the policy is a pure function of its tick inputs.
:meth:`Autoscaler.tick` is driven externally (the bench harness calls
it once per virtual-clock tick; a deployment can call it from any
timer), hysteresis and cooldown count *ticks*, not wall seconds, and
every decision appends to an audit trail (``autoscale.decision``
events + :attr:`Autoscaler.decisions`) — two same-seed harness runs
over a :class:`~distkeras_tpu.serving.traffic.TraceReplay` produce
identical decision timelines.  SLO breaches enter the loop through
:meth:`Autoscaler.on_breach` (a ``SloEngine.subscribe`` target): a
breach votes scale-up for ``policy.breach_ticks`` subsequent ticks.

Guaranteed jax-free (source lint ledger): scaling is host
bookkeeping; the control plane must never compile a program.
"""

from __future__ import annotations

import dataclasses

from distkeras_tpu import obs
from distkeras_tpu.resilience import chaos
from distkeras_tpu.utils.locks import TracedLock


class WarmPool:
    """Pre-compiled replica handles awaiting admission.

    A FIFO of router-attachable handles (``InProcessReplica`` /
    ``HttpReplica`` / anything with the replica surface) whose
    engines compiled their programs BEFORE pooling — the warm-pool
    contract that makes a scale-up join zero-compile.  The pool does
    not health-check: the autoscaler gates health at admission time
    (a handle can die while pooled).  Thread-safe; retired replicas
    return here still warm.
    """

    def __init__(self, replicas=()):
        self._lock = TracedLock("serving.warm_pool")
        self._ready = list(replicas)

    def put(self, replica) -> None:
        with self._lock:
            self._ready.append(replica)

    def take(self):
        """Pop the oldest pooled handle, or None when empty."""
        with self._lock:
            return self._ready.pop(0) if self._ready else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ready)

    def names(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(r.name for r in self._ready)


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The policy knobs (docs/serving_guide.md "Autoscaling" table).

    Utilization is fleet-wide ``(lanes_busy + queue_depth) /
    lanes`` over serving (non-prefill, up, non-draining) replicas;
    router-level backlog (requests parked because every replica was
    saturated) always votes scale-up.  ``up_after``/``down_after``
    are consecutive-tick streak requirements (hysteresis — a single
    noisy tick moves nothing, and down_after > up_after biases the
    loop toward latency over cost); ``cooldown_ticks`` is the
    minimum tick gap between ANY two membership changes (flap
    damping); ``min_replicas``/``max_replicas`` is the envelope.
    ``breach_ticks`` is how long one SLO breach keeps voting
    scale-up after :meth:`Autoscaler.on_breach` records it."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_threshold: float = 0.9
    down_threshold: float = 0.3
    up_after: int = 1
    down_after: int = 3
    cooldown_ticks: int = 2
    breach_ticks: int = 3

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"({self.min_replicas}, {self.max_replicas})")
        if not 0.0 <= self.down_threshold < self.up_threshold:
            raise ValueError(
                "need 0 <= down_threshold < up_threshold, got "
                f"({self.down_threshold}, {self.up_threshold})")
        if self.up_after < 1 or self.down_after < 1:
            raise ValueError("up_after and down_after must be >= 1")
        if self.cooldown_ticks < 0 or self.breach_ticks < 0:
            raise ValueError(
                "cooldown_ticks and breach_ticks must be >= 0")


class Autoscaler:
    """The policy engine (module docstring has the full story).

    ``router``: the fleet :class:`~distkeras_tpu.serving.router.
    Router` (actuator + snapshot source).  ``pool``: the
    :class:`WarmPool` scale-up admits from.  ``release``: optional
    hook called with a retired handle instead of pooling it (the
    owner takes shutdown responsibility).  Drive :meth:`tick` once
    per decision interval from one thread; :meth:`on_breach` may
    race it from the SLO ticker thread (it only records a vote).
    """

    def __init__(self, router, pool: WarmPool, *,
                 policy: AutoscalePolicy | None = None, release=None):
        self.router = router
        self.pool = pool
        self.policy = policy if policy is not None else AutoscalePolicy()
        self._release = release
        # Guards the cross-thread vote state only; never held across
        # router calls (no nesting with the serving.router lock).
        self._lock = TracedLock("serving.autoscale")
        self._breach_until = -1
        self._tick = -1
        self._hi_streak = 0
        self._lo_streak = 0
        self._last_change = None
        self.decisions: list[dict] = []

    # ----------------------------------------------------------- inputs

    def on_breach(self, rule, value) -> None:
        """``SloEngine.subscribe`` target: one breach votes scale-up
        for ``policy.breach_ticks`` subsequent ticks (edge-triggered
        breaches re-arm the vote; the engine fires this with its own
        lock released)."""
        del rule, value
        with self._lock:
            self._breach_until = self._tick + 1 + self.policy.breach_ticks

    @staticmethod
    def _serving(snap: dict) -> dict:
        """The snapshot's serving members: up, not draining, and not
        prefill-specialized (prefill replicas take no decode routes,
        so they are outside the decode-capacity envelope)."""
        return {n: r for n, r in snap["replicas"].items()
                if r["up"] and not r["draining"]
                and r["role"] != "prefill"}

    # --------------------------------------------------------- decision

    def tick(self) -> dict:
        """One decision pass.  Reads one consistent fleet snapshot,
        updates the hysteresis streaks, and actuates at most ONE
        membership change.  Returns the decision record (also
        appended to :attr:`decisions` and emitted as an
        ``autoscale.decision`` event for actions other than hold)."""
        with self._lock:
            self._tick += 1
            tick = self._tick
            breach = tick < self._breach_until
        p = self.policy
        snap = self.router.fleet_snapshot()
        serving = self._serving(snap)
        n = len(serving)
        lanes = sum(r["lanes"] for r in serving.values())
        busy = sum(r["lanes_busy"] + r["queue_depth"]
                   for r in serving.values())
        backlog = snap["pending"]
        util = (busy / lanes) if lanes else float(busy + backlog > 0)
        obs.gauge("autoscale.utilization", util)
        hot = util > p.up_threshold or backlog > 0 or breach
        cold = util < p.down_threshold and backlog == 0 and not breach
        self._hi_streak = self._hi_streak + 1 if hot else 0
        self._lo_streak = self._lo_streak + 1 if cold else 0
        cooling = (self._last_change is not None
                   and tick - self._last_change < p.cooldown_ticks)
        action, replica, reason = "hold", None, "steady"
        if cooling:
            reason = "cooldown"
        elif self._hi_streak >= p.up_after and n < p.max_replicas:
            action, replica, reason = self._scale_up(
                "breach" if breach else
                "backlog" if backlog > 0 else "utilization")
        elif self._lo_streak >= p.down_after and n > p.min_replicas:
            action, replica, reason = self._scale_down(snap, serving)
        if action in ("up", "down"):
            self._last_change = tick
            self._hi_streak = self._lo_streak = 0
        snap_after = self.router.fleet_snapshot()
        n_after = len(self._serving(snap_after))
        obs.gauge("autoscale.replicas", float(n_after))
        record = {"tick": tick, "action": action, "replica": replica,
                  "reason": reason, "replicas": n_after,
                  "epoch": snap_after["epoch"]}
        self.decisions.append(record)
        if action != "hold":
            obs.event("autoscale.decision", tick=tick, action=action,
                      replica=replica, reason=reason,
                      replicas=n_after, epoch=snap_after["epoch"])
        return record

    # --------------------------------------------------------- actuators

    def _scale_up(self, reason: str) -> tuple:
        """Admit the first live warm-pool candidate.  Health-gated
        before ``add_replica`` and verified up after it: a candidate
        that died in the pool or mid-join is discarded — never a
        route-table entry for a dead replica — and the next
        candidate is tried.  Empty (or fully dead) pool: the
        scale-up is recorded as exhausted and retried next time the
        streak rebuilds."""
        while True:
            cand = self.pool.take()
            if cand is None:
                obs.count("autoscale.pool_exhausted")
                return "exhausted", None, reason
            name = cand.name
            try:
                chaos.probe("autoscale.join")
                alive = bool(cand.healthy())
            except Exception:  # noqa: BLE001 — a dead probe is dead
                alive = False
            if alive:
                try:
                    self.router.add_replica(cand)
                except Exception:  # noqa: BLE001 — join raced death
                    alive = False
                else:
                    if name not in self.router.replicas_up():
                        # Died between the gate and the join: the
                        # membership entry is DOWN — drop it so a
                        # dead replica never lingers in the table.
                        self.router.remove_replica(name)
                        alive = False
            if alive:
                obs.count("autoscale.scale_ups")
                return "up", name, reason
            obs.count("autoscale.join_aborts")
            obs.event("autoscale.decision", tick=self._tick,
                      action="abort", replica=name,
                      reason="join-health-gate",
                      replicas=len(self.router.replicas_up()),
                      epoch=self.router.epoch)

    def _scale_down(self, snap: dict, serving: dict) -> tuple:
        """Retire the least-loaded serving replica that is SAFE to
        drop: one whose advertised pinned ``prefix_id``\\ s are all
        resident on some other serving replica (pool entries and
        disagg pins are replica-local, so in practice: no live
        pins).  No safe victim -> defer until an unpin."""
        others_ok = []
        for name in sorted(serving,
                           key=lambda n: (serving[n]["load"], n)):
            mine = set(serving[name]["prefix_ids"])
            elsewhere = set()
            for n2, r2 in serving.items():
                if n2 != name:
                    elsewhere |= set(r2["prefix_ids"])
            if mine <= elsewhere:
                others_ok.append(name)
        if not others_ok:
            obs.count("autoscale.retire_deferred")
            return "defer", None, "pinned-last-holder"
        victim = others_ok[0]
        handle = self.router.remove_replica(victim)
        if handle is not None:
            if self._release is not None:
                self._release(handle)
            else:
                self.pool.put(handle)
        obs.count("autoscale.scale_downs")
        del snap
        return "down", victim, "idle"


__all__ = ["Autoscaler", "AutoscalePolicy", "WarmPool"]
