"""distkeras_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of dist-keras (reference:
cbonnett/dist-keras): data-parallel distributed optimization of Keras
models — but designed TPU-first.  Where the reference runs Spark
executors that exchange pickled weight deltas with a socket-based
parameter server (reference: distkeras/parameter_servers.py,
distkeras/networking.py), this framework compiles Keras 3 models to XLA
via the JAX backend and combines gradients with XLA collectives over the
TPU ICI mesh (``jax.sharding`` + ``jit``/``shard_map``).  The Spark
DataFrame data plane is replaced by a host-sharded, device-prefetching
column Dataset.

Public surface (mirrors the reference's — see SURVEY.md §2):

* Trainers (reference: distkeras/trainers.py): :class:`SingleTrainer`,
  :class:`ADAG`, :class:`DOWNPOUR`, :class:`AEASGD`, :class:`EAMSGD`,
  :class:`DynSGD`, :class:`AveragingTrainer`, :class:`EnsembleTrainer`.
* Predictors (reference: distkeras/predictors.py): :class:`ModelPredictor`.
* Transformers (reference: distkeras/transformers.py):
  :class:`OneHotTransformer`, :class:`LabelIndexTransformer`,
  :class:`MinMaxTransformer`, :class:`StandardScaleTransformer`,
  :class:`ReshapeTransformer`,
  :class:`DenseTransformer`.
* Evaluators (reference: distkeras/evaluators.py): :class:`AccuracyEvaluator`.
* Serialization (reference: distkeras/utils.py):
  :func:`serialize_keras_model`, :func:`deserialize_keras_model`.

The Keras backend is forced to JAX at import time: every compute path in
this package goes through XLA.
"""

import os as _os
import sys as _sys

# The framework requires the JAX backend of Keras 3; TensorFlow is the
# default otherwise.  Must happen before `keras` is imported anywhere.
_os.environ.setdefault("KERAS_BACKEND", "jax")
if _os.environ.get("KERAS_BACKEND") != "jax":  # pragma: no cover
    raise ImportError(
        "distkeras_tpu requires KERAS_BACKEND=jax; found %r. "
        "Unset KERAS_BACKEND or set it to 'jax' before importing." %
        _os.environ.get("KERAS_BACKEND"))
if "keras" in _sys.modules:  # keras imported before us — check its backend
    import keras as _keras

    if _keras.backend.backend() != "jax":  # pragma: no cover
        raise ImportError(
            "keras was imported with the %r backend before distkeras_tpu "
            "could select JAX. Either `import distkeras_tpu` before keras, "
            "or set KERAS_BACKEND=jax in the environment." %
            _keras.backend.backend())

from distkeras_tpu.version import __version__

from distkeras_tpu.utils.serialization import (
    serialize_keras_model,
    deserialize_keras_model,
    save_lm,
    load_lm,
)
from distkeras_tpu import obs
from distkeras_tpu.models.adapter import ModelAdapter, TrainState
from distkeras_tpu.parallel import collectives, exchange, rules
from distkeras_tpu.parallel.collectives import zero1_optimizer
from distkeras_tpu.parallel.exchange import (ExchangeConfig,
                                              exchange_optimizer)
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.rules import match_partition_rules
from distkeras_tpu.parallel.sharding import (ServingPlan, ShardingPlan,
                                              dp_plan, fsdp_plan,
                                              serving_plan, tp_plan,
                                              zero1_plan, zero3_plan)
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.packing import pack_documents, packing_efficiency
from distkeras_tpu.data.tokenizer import BPETokenizer
from distkeras_tpu.data.transformers import (
    Transformer,
    OneHotTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    StandardScaleTransformer,
    ReshapeTransformer,
    DenseTransformer,
)
from distkeras_tpu.checkpoint import CheckpointManager
from distkeras_tpu.resilience import (ClusterMember, ClusterSupervisor,
                                       EngineClosed, FaultPlan, Preempted,
                                       QueueFull, RequestResult,
                                       Supervisor)
from distkeras_tpu.serving import (ContinuousBatcher, PagedBatcher,
                                   PrefixPool, SpeculativeBatcher)
from distkeras_tpu.evaluators import (Evaluator, AccuracyEvaluator,
                                       PerplexityEvaluator)
from distkeras_tpu.predictors import Predictor, ModelPredictor
from distkeras_tpu.trainers import (
    Trainer,
    SingleTrainer,
    ADAG,
    AsyncDP,
    DOWNPOUR,
    AEASGD,
    EAMSGD,
    DynSGD,
    AveragingTrainer,
    EnsembleTrainer,
    LMTrainer,
    LoRATrainer,
)

__all__ = [
    "__version__",
    "serialize_keras_model",
    "deserialize_keras_model",
    "save_lm",
    "load_lm",
    "ModelAdapter",
    "TrainState",
    "MeshSpec",
    "make_mesh",
    "ShardingPlan",
    "dp_plan",
    "fsdp_plan",
    "tp_plan",
    "zero1_plan",
    "zero3_plan",
    "ServingPlan",
    "serving_plan",
    "zero1_optimizer",
    "match_partition_rules",
    "collectives",
    "exchange",
    "rules",
    "ExchangeConfig",
    "exchange_optimizer",
    "obs",
    "Dataset",
    "pack_documents",
    "packing_efficiency",
    "BPETokenizer",
    "Transformer",
    "OneHotTransformer",
    "LabelIndexTransformer",
    "MinMaxTransformer",
    "StandardScaleTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
    "CheckpointManager",
    "EngineClosed",
    "ClusterMember",
    "ClusterSupervisor",
    "FaultPlan",
    "Preempted",
    "QueueFull",
    "RequestResult",
    "Supervisor",
    "Evaluator",
    "AccuracyEvaluator",
    "PerplexityEvaluator",
    "Predictor",
    "ModelPredictor",
    "Trainer",
    "SingleTrainer",
    "ADAG",
    "AsyncDP",
    "DOWNPOUR",
    "AEASGD",
    "EAMSGD",
    "DynSGD",
    "AveragingTrainer",
    "EnsembleTrainer",
    "LMTrainer",
    "ContinuousBatcher",
    "PagedBatcher",
    "SpeculativeBatcher",
    "PrefixPool",
    "LoRATrainer",
]
