"""Contract lint: the static witness for the fleet's coordination contracts.

The comm lint pins collective traffic (``comm_budget.json``), the shard
lint pins compiled placements (``shard_budget.json``), and the thread
lint pins the lock order — but until this module nothing pinned the
THREE contracts the serving fleet and the upcoming autoscaler actually
close their loops on:

* **Telemetry schema** — every ``obs.count/gauge/observe/event/span``
  emission site's name, instrument kind, and label-key set, censused by
  AST walk over ``distkeras_tpu/`` and pinned exactly in
  ``scripts/obs_schema.json``.  A renamed metric, a changed label set,
  or a name claimed by two instrument kinds silently blinds every
  consumer (``obs/report.py``, the SLO engine, the chaos suite, the
  serving bench) — here each becomes a lint error at the emitting line.
* **Wire protocol** — the route census of every HTTP server
  (``EngineEndpoint`` ``do_GET``/``do_POST``, ``TelemetryServer``'s
  handler) cross-checked both directions against every client
  (``HttpReplica``, the federation scraper, chaos-suite probes): path,
  method, query params, and status codes, pinned in the same schema
  file.
* **Resource pairing** — per-function control-flow proof over
  ``serving/`` that every acquire (``alloc``/``share_by_hash``/
  ``acquire``/``pin_prefix``/``import_blocks``) reaches its paired
  release on every path *including exception edges* — the leak class
  the PR-7 post-review pin fixed by hand and ``DKT_ASSERT_IDLE_ALLOC``
  catches only at runtime.

Rules::

    metric-drift        error  emitted-but-unpinned / pinned-but-gone /
                               instrument kind changed vs the schema
    metric-collision    error  one name, two instrument kinds (or two
                               names aliasing one Prometheus family)
    label-drift         error  a site's label-key union != the schema
    dangling-consumer   error  a consumer references a name no producer
                               emits
    undocumented-metric warn   censused name absent from the
                               docs/observability.md instrumentation
                               tables (baselineable)
    route-drift         error  client calls an unserved route / served
                               route has neither a client nor an
                               operator flag / census != schema
    status-drift        warn   a client explicitly checks a status code
                               the server never sends on that route
    unbalanced-resource error  an acquire can escape its function (or
                               die on an exception edge) without its
                               paired release

Dynamic-name emission sites (``obs.gauge(f"train.{k}", ...)``,
``StepTimer``'s ``f"{scope}.{name}"`` spans, the lock sanitizer's
``metric`` variable) cannot be censused literally; the names they are
known to produce are declared in :data:`DYNAMIC_METRICS` and pinned in
the schema's ``dynamic_metrics`` list so consumer references to them
still resolve.  Chaos-suite child scripts emit a few events from inside
generated source strings, invisible to the AST — a raw-regex sweep over
``scripts/*.py`` collects those into the schema's ``scenario_events``.

Everything here is importable without jax/keras (pure ``ast`` + the
PR-3 findings machinery + the PR-8 ``prom_name`` ledger), so the
``scripts/graph_lint.py --contracts`` path stays a sub-second gate.
"""

from __future__ import annotations

import ast
import json
import os
import re

from .findings import Finding, apply_suppressions
from .source_lint import iter_py_files

# --------------------------------------------------------------- census config

#: ``obs`` facade methods -> instrument kind.
FACADE_KINDS = {"count": "counter", "gauge": "gauge",
                "observe": "histogram", "event": "event", "span": "span"}

#: Registry factory methods -> instrument kind (``sess.registry.counter(
#: "name", ...)`` style, chained or assigned to a local).
REGISTRY_KINDS = {"counter": "counter", "gauge": "gauge",
                  "histogram": "histogram"}

#: Instrument-handle methods whose keywords are label keys.
OBSERVER_METHODS = {"inc", "set", "observe"}

#: Per-kind keyword names that are call parameters, not labels.
NON_LABEL_KW = {"counter": {"n"}, "gauge": {"value"},
                "histogram": {"value", "buckets"},
                "event": set(), "span": set()}

#: A metric/event name: at least two dotted lowercase segments.
NAME_RE = re.compile(r"^[a-z0-9_]+(?:\.[a-z0-9_]+)+$")

#: Trailing segments that mark a dotted string as a filename, not a
#: metric name (consumer-reference noise filter).
_FILE_EXT = {"py", "json", "jsonl", "md", "txt", "log", "yaml", "yml",
             "addr", "tmp", "csv", "html"}

#: Names emitted through dynamic-name sites the AST census cannot see:
#: ``StepTimer`` (``f"{scope}.{name}"`` spans / ``_s`` histograms /
#: ``.round`` events, scope defaults to ``train``), the trainer probe
#: gauges (``f"train.{k}"``), and the lock sanitizer's ``_observe``
#: indirection.  Declared here, pinned into the schema, consulted by
#: the dangling-consumer rule.
DYNAMIC_METRICS = {
    "train.step_s": "histogram",
    "train.h2d_s": "histogram",
    "train.step": "span",
    "train.h2d": "span",
    "train.round": "event",
    "lock.held_s": "histogram",
    "lock.wait_s": "histogram",
}

#: Name prefixes dynamic sites can mint beyond :data:`DYNAMIC_METRICS`
#: (trainer probe gauges mint ``train.<probe>`` per probe key).
DYNAMIC_PREFIXES = ("train.",)

#: Files whose metric-name references must resolve to a producer.
CONSUMER_FILES = (
    "distkeras_tpu/obs/report.py",
    "distkeras_tpu/obs/slo.py",
    "scripts/obs_report.py",
    "scripts/chaos_suite.py",
    "scripts/bench_serving.py",
)

#: The instrumentation tables the warn-tier documentation rule reads.
DOC_FILE = "docs/observability.md"

# ------------------------------------------------------------------ wire config

#: HTTP server definitions: file -> protocol family.
WIRE_SERVER_FILES = {
    "distkeras_tpu/serving/router.py": "engine",
    "distkeras_tpu/obs/live.py": "telemetry",
}

#: HTTP client call sites: file -> protocol family the calls target.
WIRE_CLIENT_FILES = {
    "distkeras_tpu/serving/router.py": "engine",
    "distkeras_tpu/obs/live.py": "telemetry",
    "scripts/chaos_suite.py": "telemetry",
}

#: Server routes consumed by operators/external scrapers rather than
#: in-repo code — exempt from the served-but-never-called check and
#: flagged ``"operator": true`` in the schema.
OPERATOR_ROUTES = {
    ("telemetry", "GET /snapshot.json"),
    ("telemetry", "GET /trace/tail"),
    ("telemetry", "GET /residency"),
}

#: Methods that make a call a client-side HTTP request.
_CLIENT_CALLEES = {"_get", "_post", "urlopen", "Request"}

# -------------------------------------------------------------- resource config

#: Acquire method name -> resource family.
ACQUIRE_FAMILY = {
    "alloc": "block",
    "share_by_hash": "block",
    "acquire": "prefix",
    "pin_prefix": "pin",
    "import_blocks": "pin",
}

#: Resource family -> release method names.
RELEASE_FAMILY = {
    "block": {"free"},
    "prefix": {"release"},
    "pin": {"unpin_prefix", "unpin", "pop"},
}

#: Calls that transfer ownership of a handle passed to them: container
#: inserts (the caller's cleanup path now walks the container) and the
#: HTTP response writers (the remote peer owns the pin it was sent).
_COLLECT_METHODS = {"append", "add", "extend", "insert", "put",
                    "appendleft", "_send", "send"}

#: Calls that cannot raise mid-protocol (or whose failure modes we
#: accept): pure builtins, the obs facade (never raises by contract),
#: lock/event primitives, and pure container/string reads.
_SAFE_BUILTINS = {
    "int", "float", "str", "bool", "len", "min", "max", "abs", "sorted",
    "list", "tuple", "dict", "set", "range", "enumerate", "zip", "sum",
    "any", "all", "isinstance", "getattr", "hasattr", "repr", "format",
    "round", "id", "hex", "type", "print",
}
_SAFE_METHODS = {"get", "items", "keys", "values", "tolist", "copy",
                 "join", "split", "startswith", "endswith", "encode",
                 "decode", "format", "hexdigest", "setdefault",
                 "monotonic", "time", "perf_counter"}
_SAFE_ROOTS = {"obs", "time", "math", "os", "logging"}
_LOCKISH = ("lock", "cond", "sem", "event", "mutex", "cv")


# ----------------------------------------------------------------- AST helpers

def _attr_chain(node) -> list[str]:
    """``a.b.c`` -> ``["a", "b", "c"]``; non-chains -> ``[]``."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _str_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _first_arg_str(call: ast.Call) -> str | None:
    return _str_const(call.args[0]) if call.args else None


def _callee(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return ""


def _is_metric_name(s: str) -> bool:
    return bool(NAME_RE.match(s)) and s.rsplit(".", 1)[-1] not in _FILE_EXT


def _rel(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace("\\", "/")


# ============================================================ telemetry census

class EmitSite:
    """One emission site: name, instrument kind, label-key set."""

    __slots__ = ("name", "kind", "labels", "path", "line")

    def __init__(self, name, kind, labels, path, line):
        self.name, self.kind = name, kind
        self.labels = frozenset(labels)
        self.path, self.line = path, line


def _labels_of(call: ast.Call, kind: str) -> set[str]:
    """Label keys a call contributes: keyword names minus per-kind call
    parameters; ``**labels`` contributes the ``"*"`` marker."""
    out = set()
    for kw in call.keywords:
        if kw.arg is None:
            out.add("*")
        elif kw.arg not in NON_LABEL_KW[kind]:
            out.add(kw.arg)
    return out


def census_emits(source: str, path: str = "<string>") -> list[EmitSite]:
    """Every literal-name emission site in one module.

    Covers the ``obs`` facade, chained registry instruments
    (``...registry.counter("x", "h").inc(**labels)``), registry
    instruments assigned to a local and observed later in the same
    function, and the SLO engine's ``self._emit("name", ...)`` event
    hook.  Dynamic-name sites (f-strings, variables) are skipped — see
    :data:`DYNAMIC_METRICS`.
    """
    tree = ast.parse(source, filename=path)
    sites: list[EmitSite] = []
    chained_inner: set[ast.Call] = set()

    # Chained registry form first, so the inner factory call is not
    # double-counted by the assigned-form scan.
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in OBSERVER_METHODS
                and isinstance(node.func.value, ast.Call)):
            continue
        inner = node.func.value
        if not isinstance(inner.func, ast.Attribute):
            continue
        kind = REGISTRY_KINDS.get(inner.func.attr)
        name = _first_arg_str(inner)
        chain = _attr_chain(inner.func)
        if kind is None or name is None or "registry" not in chain[:-1]:
            continue
        chained_inner.add(inner)
        sites.append(EmitSite(name, kind, _labels_of(node, kind),
                              path, node.lineno))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            recv, attr = node.func.value, node.func.attr
            name = _first_arg_str(node)
            if (isinstance(recv, ast.Name) and recv.id == "obs"
                    and attr in FACADE_KINDS and name is not None):
                kind = FACADE_KINDS[attr]
                sites.append(EmitSite(name, kind, _labels_of(node, kind),
                                      path, node.lineno))
            elif (attr == "_emit" and isinstance(recv, ast.Name)
                    and recv.id == "self" and name is not None
                    and _is_metric_name(name)):
                sites.append(EmitSite(name, "event",
                                      _labels_of(node, "event"),
                                      path, node.lineno))

    # Assigned registry form: ``g = ...registry.gauge("x", "h")`` then
    # ``g.set(v, metric=..., q=...)`` later in the same function.
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        handles: dict[str, tuple[str, str, int]] = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value not in chained_inner):
                kind = REGISTRY_KINDS.get(node.value.func.attr)
                name = _first_arg_str(node.value)
                chain = _attr_chain(node.value.func)
                if (kind is not None and name is not None
                        and "registry" in chain[:-1]):
                    handles[node.targets[0].id] = (name, kind,
                                                   node.lineno)
        for var, (name, kind, line) in handles.items():
            labels: set[str] = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in OBSERVER_METHODS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == var):
                    labels |= _labels_of(node, kind)
            sites.append(EmitSite(name, kind, labels, path, line))

    return sites


_SCENARIO_RE = re.compile(
    r"""obs\.(count|gauge|observe|event|span)\(\s*["']([a-z0-9_.]+)["']""")


def scenario_emits(source: str) -> set[str]:
    """Names emitted by script code, including emissions embedded in
    generated-child source strings (chaos scenarios) the AST cannot
    reach — a raw-regex sweep, names only."""
    return {m.group(2) for m in _SCENARIO_RE.finditer(source)
            if _is_metric_name(m.group(2))}


def merge_census(sites) -> tuple[dict, list[Finding]]:
    """Fold sites into ``{name: {"kind", "labels"}}``; kind conflicts
    (and Prometheus-family aliasing via the PR-8 ``prom_name`` ledger)
    become ``metric-collision`` errors."""
    from distkeras_tpu.obs.metrics import prom_name

    census: dict[str, dict] = {}
    findings: list[Finding] = []
    first: dict[str, EmitSite] = {}
    for s in sites:
        if s.name not in census:
            census[s.name] = {"kind": s.kind, "labels": set(s.labels)}
            first[s.name] = s
            continue
        ent = census[s.name]
        if ent["kind"] != s.kind:
            findings.append(Finding(
                "metric-collision", "error", s.path, s.line,
                f"'{s.name}' emitted as {s.kind} here but as "
                f"{ent['kind']} at {first[s.name].path}:"
                f"{first[s.name].line}",
                hint="one name must map to one instrument kind — "
                     "rename one of the two"))
        else:
            ent["labels"] |= s.labels
    prom: dict[str, str] = {}
    for name, ent in sorted(census.items()):
        if ent["kind"] not in REGISTRY_KINDS.values():
            continue
        p = prom_name(name)
        if p in prom and prom[p] != name:
            s = first[name]
            findings.append(Finding(
                "metric-collision", "error", s.path, s.line,
                f"'{name}' and '{prom[p]}' both render as Prometheus "
                f"family '{p}'",
                hint="pick names that stay distinct under prom_name()"))
        prom.setdefault(p, name)
    return census, findings


# ---------------------------------------------------------- consumer references

def _is_name_lookup(expr) -> bool:
    """Does ``expr`` read a record's ``name`` field (``e["name"]`` /
    ``r.get("name")``)?  The anchor that separates metric-name string
    comparisons from ordinary string code."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Subscript):
            if _str_const(node.slice) == "name":
                return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and _first_arg_str(node) == "name"):
            return True
    return False


def _str_elts(node):
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            s = _str_const(e)
            if s is not None:
                yield e, s
    else:
        s = _str_const(node)
        if s is not None:
            yield node, s


def consumer_refs(source: str, path: str,
                  vocab: set[str]) -> list[tuple[str, int, str]]:
    """Metric-name references a consumer module makes, as
    ``(name, line, mode)`` with mode ``"exact"`` or ``"prefix"``.

    ``vocab`` is the first-segment vocabulary of known producer names
    (``{"serving", "router", ...}``) — the noise filter that keeps file
    paths and chaos fault-site labels out of the reference set.
    """
    tree = ast.parse(source, filename=path)
    refs: list[tuple[str, int, str]] = []

    def known(s: str) -> bool:
        return _is_metric_name(s) and s.split(".", 1)[0] in vocab

    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if any(_is_name_lookup(s) for s in sides):
                for s in sides:
                    for elt, txt in _str_elts(s):
                        if _is_metric_name(txt):
                            refs.append((txt, elt.lineno, "exact"))
        elif isinstance(node, ast.Call):
            callee = _callee(node)
            if (callee == "startswith"
                    and isinstance(node.func, ast.Attribute)
                    and _is_name_lookup(node.func.value)
                    and node.args):
                for _elt, txt in _str_elts(node.args[0]):
                    refs.append((txt, node.lineno, "prefix"))
            elif callee == "SloRule":
                txt = _first_arg_str(node)
                if txt is not None and _is_metric_name(txt):
                    refs.append((txt, node.lineno, "exact"))
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in {"counter", "gauge",
                                         "histogram", "hist"}):
                txt = _first_arg_str(node)
                if txt is not None and known(txt):
                    refs.append((txt, node.lineno, "exact"))
            elif (callee == "get" and isinstance(node.func,
                                                 ast.Attribute)
                    and node.args):
                txt = _str_const(node.args[0])
                if txt is not None and known(txt):
                    refs.append((txt, node.lineno, "exact"))
        elif isinstance(node, ast.Subscript):
            txt = _str_const(node.slice)
            if txt is not None and known(txt):
                refs.append((txt, node.lineno, "exact"))
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_METRICS")):
            for _elt, txt in _str_elts(node.value):
                if _is_metric_name(txt):
                    refs.append((txt, node.lineno, "exact"))
        elif isinstance(node, (ast.Tuple, ast.List)):
            # Only all-string literals: mixed tuples are structured
            # records (chaos fault-plan events carry site labels like
            # ("cluster.push", 5, "fail") that are NOT metric names).
            if node.elts and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str) for e in node.elts):
                for elt, txt in _str_elts(node):
                    if known(txt):
                        refs.append((txt, elt.lineno, "exact"))
    return refs


def documented_names(doc_text: str) -> set[str]:
    """Dotted names the observability doc mentions (label-set suffixes
    like ``{status}`` stripped first).  A deliberate superset — extra
    dotted tokens in prose only ever make the documentation rule MORE
    permissive."""
    text = re.sub(r"\{[^}]*\}", "", doc_text)
    return set(re.findall(r"[a-z0-9_]+(?:\.[a-z0-9_]+)+", text))


# ================================================================= wire census

_SEND_CALLEES = {"_send", "_send_raw", "send_response", "send_error"}


def _status_codes(body) -> set[int]:
    out: set[int] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and _callee(node) in _SEND_CALLEES and node.args):
                arg = node.args[0]
                arms = (arg.body, arg.orelse) if isinstance(
                    arg, ast.IfExp) else (arg,)
                for a in arms:
                    if (isinstance(a, ast.Constant)
                            and isinstance(a.value, int)):
                        out.add(a.value)
    return out


def _branch_params(body) -> set[str]:
    """Query params a route branch reads: ``q.get("id")`` keys in a
    branch that also calls ``parse_qs``."""
    uses_qs = any(isinstance(n, ast.Call) and _callee(n) == "parse_qs"
                  for stmt in body for n in ast.walk(stmt))
    if not uses_qs:
        return set()
    out = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call) and _callee(node) == "get"
                    and isinstance(node.func, ast.Attribute)
                    and node.args):
                key = _str_const(node.args[0])
                if key is not None:
                    out.add(key)
    return out


def server_routes(source: str, path: str = "<string>") -> dict:
    """Routes one module serves: ``{"GET /poll": {"params": set,
    "status": set}}``.

    GET routes come from ``url.path == "/x"`` comparisons inside any
    ``do_GET``; POST routes from the ``{"/x": self._post_x}`` dispatch
    dict inside ``do_POST``, statuses read from each handler's body.
    """
    tree = ast.parse(source, filename=path)
    routes: dict[str, dict] = {}
    fns = {n.name: n for n in ast.walk(tree)
           if isinstance(n, ast.FunctionDef)}

    for fn in ast.walk(tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name in ("do_GET", "do_POST")):
            continue
        method = fn.name.split("_")[1]
        for node in ast.walk(fn):
            if (method == "GET" and isinstance(node, ast.If)
                    and isinstance(node.test, ast.Compare)
                    and len(node.test.ops) == 1
                    and isinstance(node.test.ops[0], ast.Eq)):
                sides = [node.test.left, node.test.comparators[0]]
                lit = next((s for s in map(_str_const, sides)
                            if s is not None and s.startswith("/")),
                           None)
                anchored = any(
                    isinstance(s, ast.Attribute) and s.attr == "path"
                    for s in sides)
                if lit is not None and anchored:
                    routes[f"GET {lit}"] = {
                        "params": _branch_params(node.body),
                        "status": _status_codes(node.body)}
            elif method == "POST" and isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    lit = _str_const(k)
                    if (lit is None or not lit.startswith("/")
                            or not isinstance(v, ast.Attribute)):
                        continue
                    handler = fns.get(v.attr)
                    routes[f"POST {lit}"] = {
                        "params": set(),
                        "status": _status_codes(handler.body)
                        if handler is not None else set()}
    return routes


def client_calls(source: str, path: str = "<string>") -> list[dict]:
    """Client-side HTTP calls one module makes: ``{"route", "params",
    "expects", "line"}`` per call site.

    Routes come from ``/``-prefixed string constants (including
    f-string constant parts — ``f"/poll?id={rid}"``) in the argument
    subtree of ``_get``/``_post``/``urlopen``/``Request`` calls; status
    expectations from integer comparisons against ``code``/``status``
    names in the enclosing function.
    """
    tree = ast.parse(source, filename=path)
    out: list[dict] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        expects: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                codeish = any(
                    (isinstance(s, ast.Name)
                     and s.id in ("code", "status"))
                    or (isinstance(s, ast.Attribute)
                        and s.attr in ("code", "status"))
                    for s in sides)
                if codeish:
                    expects |= {s.value for s in sides
                                if isinstance(s, ast.Constant)
                                and isinstance(s.value, int)}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and _callee(node) in _CLIENT_CALLEES):
                continue
            method = "POST" if _callee(node) == "_post" else "GET"
            for kw in node.keywords:
                if (kw.arg == "method"
                        and _str_const(kw.value) is not None):
                    method = _str_const(kw.value)
            parts: list[str] = []
            for arg in node.args:
                for sub in ast.walk(arg):
                    s = _str_const(sub)
                    if s is not None:
                        parts.append(s)
                    elif isinstance(sub, ast.JoinedStr):
                        parts.extend(v.value for v in sub.values
                                     if isinstance(v, ast.Constant)
                                     and isinstance(v.value, str))
            for s in parts:
                if not s.startswith("/") or s.startswith("//"):
                    continue
                route_path, sep, query = s.partition("?")
                params = ({p.split("=", 1)[0]
                           for p in query.split("&") if p}
                          if sep else set())
                out.append({"route": f"{method} {route_path}",
                            "params": params, "expects": set(expects),
                            "line": node.lineno})
    return out


def collect_wire(root: str) -> tuple[dict, dict]:
    """Census servers and clients across the configured files.

    Returns ``(servers, clients)``: ``servers[family][route] =
    {"params", "status"}``; ``clients[family][route] = {"params",
    "expects", "sites": [(path, line), ...]}``.
    """
    servers: dict[str, dict] = {}
    clients: dict[str, dict] = {}
    for rel, family in WIRE_SERVER_FILES.items():
        full = os.path.join(root, rel)
        with open(full, encoding="utf-8") as fh:
            routes = server_routes(fh.read(), rel)
        fam = servers.setdefault(family, {})
        for route, ent in routes.items():
            fam[route] = ent
    for rel, family in WIRE_CLIENT_FILES.items():
        full = os.path.join(root, rel)
        with open(full, encoding="utf-8") as fh:
            calls = client_calls(fh.read(), rel)
        fam = clients.setdefault(family, {})
        for c in calls:
            ent = fam.setdefault(c["route"], {"params": set(),
                                              "expects": set(),
                                              "sites": []})
            ent["params"] |= c["params"]
            ent["expects"] |= c["expects"]
            ent["sites"].append((rel, c["line"]))
    return servers, clients


def check_wire(servers: dict, clients: dict, pinned_wire: dict,
               schema_rel: str) -> list[Finding]:
    """Cross-check both directions and against the pinned schema."""
    findings: list[Finding] = []
    for family, fam_clients in sorted(clients.items()):
        fam_servers = servers.get(family, {})
        for route, ent in sorted(fam_clients.items()):
            rel, line = ent["sites"][0]
            if route not in fam_servers:
                findings.append(Finding(
                    "route-drift", "error", rel, line,
                    f"client calls {family} route '{route}' no server "
                    f"handles",
                    hint="add the route to the server dispatch or fix "
                         "the client path"))
                continue
            srv = fam_servers[route]
            unknown = ent["params"] - srv["params"]
            if unknown:
                findings.append(Finding(
                    "route-drift", "error", rel, line,
                    f"client sends params {sorted(unknown)} on "
                    f"'{route}' the {family} server never reads",
                    hint="sync the query-parameter names"))
            phantom = ent["expects"] - srv["status"]
            if phantom:
                findings.append(Finding(
                    "status-drift", "warn", rel, line,
                    f"client checks status {sorted(phantom)} on "
                    f"'{route}' but the {family} server only sends "
                    f"{sorted(srv['status'])}",
                    hint="dead status branch — sync the protocol"))
    for family, fam_servers in sorted(servers.items()):
        fam_clients = clients.get(family, {})
        for route in sorted(fam_servers):
            if (route not in fam_clients
                    and (family, route) not in OPERATOR_ROUTES):
                findings.append(Finding(
                    "route-drift", "error",
                    _server_file_of(family), 1,
                    f"{family} serves '{route}' but no in-repo client "
                    f"calls it and it carries no operator flag",
                    hint="delete the route or add it to "
                         "OPERATOR_ROUTES in contract_lint.py"))
    built = _wire_doc(servers, clients)
    if built != pinned_wire:
        for family in sorted(set(built) | set(pinned_wire)):
            b, p = built.get(family, {}), pinned_wire.get(family, {})
            for route in sorted(set(b) | set(p)):
                if b.get(route) != p.get(route):
                    findings.append(Finding(
                        "route-drift", "error", schema_rel, 1,
                        f"wire census for {family} '{route}' differs "
                        f"from the pinned schema: census="
                        f"{b.get(route)} pinned={p.get(route)}",
                        hint="re-record with --update-budgets and "
                             "review the protocol diff"))
    return findings


def _server_file_of(family: str) -> str:
    for rel, fam in WIRE_SERVER_FILES.items():
        if fam == family:
            return rel
    return "scripts/obs_schema.json"


def _wire_doc(servers: dict, clients: dict) -> dict:
    doc: dict[str, dict] = {}
    for family, fam in servers.items():
        d = doc.setdefault(family, {})
        for route, ent in fam.items():
            cli = clients.get(family, {}).get(route, {})
            d[route] = {
                "params": sorted(ent["params"]),
                "status": sorted(ent["status"]),
                "client_expects": sorted(cli.get("expects", ())),
                "operator": (family, route) in OPERATOR_ROUTES,
            }
    return doc


# ========================================================== resource pairing

class _Handle:
    __slots__ = ("var", "family", "recv", "line", "state",
                 "protected", "fin_depth")

    def __init__(self, var, family, recv, line):
        self.var, self.family, self.recv = var, family, recv
        self.line = line
        self.state = "held"          # held | vacuous | resolved | reported
        self.protected = 0           # depth of protecting try blocks
        self.fin_depth = 0           # of which: finally-releasing tries


def _acquire_of(node) -> tuple[str, list[str]] | None:
    """``(family, receiver_chain)`` when ``node`` is an acquire call."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return None
    fam = ACQUIRE_FAMILY.get(node.func.attr)
    if fam is None:
        return None
    chain = _attr_chain(node.func)
    recv = chain[:-1]
    if recv and any(h in recv[-1].lower() for h in _LOCKISH):
        return None
    return fam, recv


def _contains_name(expr, var: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == var
               for n in ast.walk(expr))


def _safe_call(call: ast.Call, recv: list[str]) -> bool:
    if isinstance(call.func, ast.Name):
        return call.func.id in _SAFE_BUILTINS
    chain = _attr_chain(call.func)
    if not chain:
        return False
    method, owner = chain[-1], chain[:-1]
    if chain[0] in _SAFE_ROOTS:
        return True
    if owner and owner == recv and method not in ACQUIRE_FAMILY:
        return True
    if owner and any(h in owner[-1].lower() for h in _LOCKISH):
        return True
    return method in _SAFE_METHODS or method in _COLLECT_METHODS


def _escape_occurrence(expr, var: str, recv: list[str],
                       parents=None) -> bool:
    """Does ``var`` occur in ``expr`` wrapped only by containers and
    safe conversion calls (so storing/sending ``expr`` transfers the
    handle), rather than swallowed as an argument to a fallible call?"""
    def walk(node, risky: bool) -> bool:
        if isinstance(node, ast.Name) and node.id == var:
            return not risky
        child_risky = risky
        if isinstance(node, ast.Call):
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in _SAFE_BUILTINS):
                child_risky = True
        return any(walk(c, child_risky)
                   for c in ast.iter_child_nodes(node))
    return walk(expr, False)


def _is_release(call: ast.Call, h: _Handle) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in RELEASE_FAMILY[h.family]:
        return False
    if any(_contains_name(a, h.var) for a in call.args):
        return True
    return _attr_chain(call.func)[:-1] == h.recv


def _stmt_resolves(stmt, h: _Handle) -> bool:
    """Release or ownership-transfer of ``h`` in one statement."""
    if isinstance(stmt, (ast.Return, ast.Expr)) and isinstance(
            getattr(stmt, "value", None), ast.Yield):
        stmt = stmt.value  # yield treated like return below
    if isinstance(stmt, ast.Return) and stmt.value is not None:
        if _contains_name(stmt.value, h.var):
            return True
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        value = stmt.value
        if value is not None and any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in targets):
            if _escape_occurrence(value, h.var, h.recv):
                return True
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        if _is_release(node, h):
            return True
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _COLLECT_METHODS
                and any(_escape_occurrence(a, h.var, h.recv)
                        for a in node.args)):
            return True
    return False


def _stmt_risky(stmt, h: _Handle) -> ast.Call | None:
    """First fallible call in ``stmt`` (excluding nested defs)."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call) and not _safe_call(node, h.recv):
            acq = _acquire_of(node)
            if acq is not None and acq[0] == h.family:
                continue  # the acquire itself / sibling acquires
            return node
    return None


def _try_protects(stmt: ast.Try, h: _Handle) -> str | None:
    """``"finally"`` when the finalbody releases the handle's family
    (runs on EVERY exit, so it discharges the obligation outright),
    ``"handler"`` when an except-rollback does (covers exception edges
    only — the normal path must still release), else None."""
    def releases(body) -> bool:
        for s in body:
            for node in ast.walk(s):
                if isinstance(node, ast.Call) and (
                        _is_release(node, h)
                        or (isinstance(node.func, ast.Attribute)
                            and node.func.attr
                            in RELEASE_FAMILY[h.family])):
                    return True
        return False
    if releases(stmt.finalbody):
        return "finally"
    if any(releases(hd.body) for hd in stmt.handlers):
        return "handler"
    return None


def _none_test(test, var: str):
    """``var is None`` -> "none"; ``var is not None`` -> "notnone"."""
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and test.left.id == var
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        if isinstance(test.ops[0], ast.Is):
            return "none"
        if isinstance(test.ops[0], ast.IsNot):
            return "notnone"
    return None


class _ResourceEval:
    """Evaluate one handle's lifetime over the remainder of its
    function — a tiny path-sensitive interpreter over the statement
    tree (If/Try/With/loops), tracking held/vacuous/resolved and the
    exception edges ``try`` protection covers."""

    def __init__(self, h: _Handle, path: str):
        self.h = h
        self.path = path
        self.findings: list[Finding] = []

    def _leak(self, line: int, why: str) -> None:
        if self.h.state != "reported":
            self.findings.append(Finding(
                "unbalanced-resource", "error", self.path, line,
                f"{self.h.family} handle '{self.h.var}' acquired at "
                f"line {self.h.line} {why}",
                hint="release on every path (try/finally or an "
                     "except-rollback), or hand ownership off "
                     "explicitly"))
            self.h.state = "reported"

    # -- statement-sequence walker ------------------------------------

    def _risk_expr(self, expr, line_hint: int) -> None:
        """Flag the first fallible call inside one header expression."""
        h = self.h
        if h.state != "held" or h.protected or expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and not _safe_call(node,
                                                             h.recv):
                self._leak(getattr(node, "lineno", line_hint),
                           "can leak if this call raises "
                           f"('{_callee(node)}' is on the path "
                           "before any release)")
                return

    def run_block(self, stmts, loop_depth: int) -> str:
        """Run statements; returns "fall" | "exit"."""
        h = self.h
        for stmt in stmts:
            if h.state in ("resolved", "reported"):
                return "fall"
            if isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                                 ast.Try)):
                kind = self._compound(stmt, loop_depth)
                if kind == "exit":
                    return "exit"
                continue
            # Rebinding the handle variable loses the only reference.
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == h.var
                    for t in stmt.targets):
                if h.state == "held" and not _stmt_resolves(stmt, h):
                    self._leak(stmt.lineno,
                               "is overwritten before release")
                if h.state != "reported":
                    h.state = "resolved"
                return "fall"
            if h.state == "held":
                if _stmt_resolves(stmt, h):
                    h.state = "resolved"
                    return "fall"
                risky = _stmt_risky(stmt, h)
                if risky is not None and not h.protected:
                    self._leak(risky.lineno,
                               "can leak if this call raises "
                               f"('{_callee(risky)}' is on the path "
                               "before any release)")
            if isinstance(stmt, (ast.Return, ast.Raise)):
                if h.state == "held":
                    if h.fin_depth:
                        h.state = "resolved"  # finally releases on exit
                    else:
                        word = ("return" if isinstance(stmt, ast.Return)
                                else "raise")
                        self._leak(stmt.lineno,
                                   f"never released before {word}")
                return "exit"
            if isinstance(stmt, (ast.Break, ast.Continue)):
                if h.state == "held":
                    if h.fin_depth:
                        h.state = "resolved"
                    elif loop_depth == 0:
                        self._leak(stmt.lineno, "never released "
                                   "before leaving the loop")
                return "exit"
        return "fall"

    @staticmethod
    def _merge(branches) -> tuple[str, str | None]:
        """Join (kind, state) per may-fall-through path."""
        live = [s for k, s in branches if k == "fall"]
        if not live:
            return "exit", None
        for rank in ("reported", "held", "vacuous", "resolved"):
            if rank in live:
                return "fall", rank
        return "fall", live[0]

    def _compound(self, stmt, loop_depth: int) -> str:
        h = self.h
        if isinstance(stmt, ast.If):
            entry = h.state
            mode = _none_test(stmt.test, h.var)
            if mode is None:
                self._risk_expr(stmt.test, stmt.lineno)
            h.state = "vacuous" if (mode == "none"
                                    and entry == "held") else entry
            body_kind = self.run_block(stmt.body, loop_depth)
            body_state = h.state
            h.state = "vacuous" if (mode == "notnone"
                                    and entry == "held") else entry
            else_kind = (self.run_block(stmt.orelse, loop_depth)
                         if stmt.orelse else "fall")
            else_state = h.state
            # A vacuous path that falls through carries no obligation.
            if mode == "none" and entry == "held":
                body_state = ("resolved" if body_state == "vacuous"
                              else body_state)
            if mode == "notnone" and entry == "held":
                else_state = ("resolved" if else_state == "vacuous"
                              else else_state)
            kind, state = self._merge([(body_kind, body_state),
                                       (else_kind, else_state)])
            if kind == "exit":
                return "exit"
            h.state = state
            return "fall"
        if isinstance(stmt, ast.Try):
            prot = (_try_protects(stmt, h)
                    if h.state == "held" else None)
            if prot is not None:
                h.protected += 1
                if prot == "finally":
                    h.fin_depth += 1
            body_kind = self.run_block(stmt.body, loop_depth)
            if prot is not None:
                h.protected -= 1
                if prot == "finally":
                    h.fin_depth -= 1
            if prot == "finally" and h.state == "held":
                # the finalbody's release runs on fall-through too
                h.state = "resolved"
            # Handler bodies are not re-evaluated for this handle: on
            # the exception edge either the try protects (rollback /
            # finally) or the risky statement inside the body was
            # already flagged.
            if stmt.orelse and body_kind == "fall":
                body_kind = self.run_block(stmt.orelse, loop_depth)
            if stmt.finalbody:
                fin_kind = self.run_block(stmt.finalbody, loop_depth)
                if fin_kind == "exit":
                    return "exit"
            return body_kind if not stmt.handlers else "fall"
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._risk_expr(item.context_expr,
                                stmt.lineno)
            return self.run_block(stmt.body, loop_depth)
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self._risk_expr(stmt.iter, stmt.lineno)
            else:
                self._risk_expr(stmt.test, stmt.lineno)
            entry = h.state
            self.run_block(stmt.body, loop_depth + 1)
            # zero-trip loops: resolution inside the body is not
            # guaranteed, so the entry obligation survives the loop
            if h.state != "reported":
                h.state = entry
            self.run_block(stmt.orelse, loop_depth)
            return "fall"
        return "fall"


def _walk_resource_fn(fn, path: str) -> list[Finding]:
    findings: list[Finding] = []

    def scan(stmts, enclosing, loop_depth):
        """Find acquires in ``stmts``; ``enclosing`` is the stack of
        (remaining-statements, loop_depth, try-node-or-None) blocks to
        evaluate after the innermost block falls through."""
        for i, stmt in enumerate(stmts):
            acq = None
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                acq = _acquire_of(stmt.value)
                if acq is not None:
                    fam, recv = acq
                    h = _Handle(stmt.targets[0].id, fam, recv,
                                stmt.lineno)
                    ev = _ResourceEval(h, path)
                    # Protection from try blocks the acquire already
                    # sits inside applies from the first statement.
                    prots = []
                    for _rest, _depth, tnode in enclosing:
                        p = (_try_protects(tnode, h)
                             if tnode is not None else None)
                        prots.append(p)
                        if p is not None:
                            h.protected += 1
                        if p == "finally":
                            h.fin_depth += 1
                    kind = ev.run_block(stmts[i + 1:], loop_depth)
                    depth_now = loop_depth
                    for (rest, depth, tnode), p in zip(
                            reversed(enclosing), reversed(prots)):
                        if kind != "fall" or h.state in ("resolved",
                                                         "reported"):
                            break
                        if p is not None:
                            h.protected -= 1
                            if p == "finally":
                                h.fin_depth -= 1
                                h.state = "resolved"
                                break
                        if depth < depth_now and h.state == "held":
                            # fell off a loop body still holding
                            ev._leak(stmt.lineno,
                                     "is not released before the "
                                     "next loop iteration")
                            break
                        kind = ev.run_block(rest, depth)
                        depth_now = depth
                    if kind == "fall" and h.state == "held":
                        ev._leak(stmt.lineno, "is never released "
                                 "before the function returns")
                    findings.extend(ev.findings)
            elif isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call):
                a = _acquire_of(stmt.value)
                if a is not None:
                    findings.append(Finding(
                        "unbalanced-resource", "error", path,
                        stmt.lineno,
                        f"{a[0]} acquire result discarded — the "
                        "handle can never be released",
                        hint="bind the result and release it, or "
                             "drop the call"))
            # recurse into child blocks
            for body, extra_loop, tnode in _child_blocks(stmt):
                scan(body,
                     enclosing + [(stmts[i + 1:], loop_depth, tnode)],
                     loop_depth + extra_loop)

    scan(fn.body, [], 0)
    return findings


def _child_blocks(stmt):
    """(body, extra_loop_depth, enclosing_try) per child block."""
    if isinstance(stmt, ast.If):
        return [(stmt.body, 0, None), (stmt.orelse, 0, None)]
    if isinstance(stmt, (ast.For, ast.While)):
        return [(stmt.body, 1, None), (stmt.orelse, 0, None)]
    if isinstance(stmt, ast.With):
        return [(stmt.body, 0, None)]
    if isinstance(stmt, ast.Try):
        blocks = [(stmt.body, 0, stmt)]
        blocks += [(h.body, 0, None) for h in stmt.handlers]
        blocks += [(stmt.orelse, 0, None), (stmt.finalbody, 0, None)]
        return blocks
    return []


def lint_resource_source(source: str,
                         path: str = "<string>") -> list[Finding]:
    """The resource-pairing rule over one module's functions."""
    tree = ast.parse(source, filename=path)
    findings: list[Finding] = []
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_walk_resource_fn(fn, path))
    lines = source.splitlines()
    out = []
    for f in findings:
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        out.append(apply_suppressions(f, text))
    return out


def lint_resource_paths(paths) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            findings.extend(lint_resource_source(fh.read(), path=f))
    return findings


# ================================================================ schema + lint

def _producer_files(root: str) -> list[str]:
    return iter_py_files([os.path.join(root, "distkeras_tpu")])


def _script_files(root: str) -> list[str]:
    return iter_py_files([os.path.join(root, "scripts")])


def collect_telemetry(root: str):
    """``(sites, census, collision_findings, scenario_names)`` for the
    whole repo, with suppression comments honoured at emission sites."""
    sites: list[EmitSite] = []
    for f in _producer_files(root):
        with open(f, encoding="utf-8") as fh:
            sites.extend(census_emits(fh.read(), _rel(f, root)))
    scenario: set[str] = set()
    for f in _script_files(root):
        with open(f, encoding="utf-8") as fh:
            scenario |= scenario_emits(fh.read())
    census, collisions = merge_census(sites)
    return sites, census, collisions, scenario


def build_obs_schema(root: str) -> dict:
    """The pinnable contract document (no findings — pure census)."""
    _sites, census, _coll, scenario = collect_telemetry(root)
    servers, clients = collect_wire(root)
    return {
        "metrics": {name: {"kind": ent["kind"],
                           "labels": sorted(ent["labels"])}
                    for name, ent in sorted(census.items())},
        "dynamic_metrics": sorted(DYNAMIC_METRICS),
        "scenario_events": sorted(scenario),
        "wire": _wire_doc(servers, clients),
    }


def save_obs_schema(path: str, schema: dict) -> None:
    doc = {"comment": "Pinned telemetry + wire-protocol contract "
                      "census. Regenerate with scripts/graph_lint.py "
                      "--update-budgets and review the diff like a "
                      "code change.",
           **schema}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def load_obs_schema(path: str) -> dict | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc.pop("comment", None)
    return doc


def check_obs_schema(built: dict, pinned: dict | None,
                     schema_rel: str = "scripts/obs_schema.json",
                     sites: dict | None = None) -> list[Finding]:
    """Census-vs-schema comparison (telemetry half).  ``sites`` maps
    metric name -> (path, line) for error placement at the emitting
    site when available."""
    sites = sites or {}
    findings: list[Finding] = []
    if pinned is None:
        return [Finding(
            "metric-drift", "error", schema_rel, 1,
            "no telemetry schema recorded for this repo",
            hint="run scripts/graph_lint.py --update-budgets to pin "
                 "the contract census")]
    want, got = pinned.get("metrics", {}), built.get("metrics", {})
    for name in sorted(set(got) | set(want)):
        path, line = sites.get(name, (schema_rel, 1))
        if name not in want:
            findings.append(Finding(
                "metric-drift", "error", path, line,
                f"'{name}' is emitted but not pinned in the schema",
                hint="re-record with --update-budgets and review the "
                     "contract diff"))
        elif name not in got:
            findings.append(Finding(
                "metric-drift", "error", schema_rel, 1,
                f"'{name}' is pinned in the schema but no longer "
                f"emitted",
                hint="a consumer may still read it — re-record with "
                     "--update-budgets after checking consumers"))
        elif want[name]["kind"] != got[name]["kind"]:
            findings.append(Finding(
                "metric-drift", "error", path, line,
                f"'{name}' changed instrument kind: "
                f"{want[name]['kind']} -> {got[name]['kind']}",
                hint="consumers bound to the old kind — re-record "
                     "with --update-budgets"))
        elif want[name]["labels"] != got[name]["labels"]:
            findings.append(Finding(
                "label-drift", "error", path, line,
                f"'{name}' label keys drifted: pinned "
                f"{want[name]['labels']} vs emitted "
                f"{got[name]['labels']}",
                hint="label-key changes re-key every aggregation — "
                     "re-record with --update-budgets"))
    for key in ("dynamic_metrics", "scenario_events"):
        if sorted(built.get(key, [])) != sorted(pinned.get(key, [])):
            findings.append(Finding(
                "metric-drift", "error", schema_rel, 1,
                f"schema section '{key}' drifted from the census",
                hint="re-record with --update-budgets"))
    return findings


def lint_repo_contracts(root: str,
                        schema_path: str | None = None) -> list[Finding]:
    """The full contract gate: telemetry census vs schema, consumer
    resolution, documentation coverage, wire-protocol cross-check, and
    the resource-pairing analysis over ``serving/``."""
    if schema_path is None:
        schema_path = os.path.join(root, "scripts", "obs_schema.json")
    schema_rel = _rel(schema_path, root)
    findings: list[Finding] = []

    sites, census, collisions, scenario = collect_telemetry(root)
    findings.extend(collisions)

    pinned = load_obs_schema(schema_path)
    built = {
        "metrics": {n: {"kind": e["kind"], "labels": sorted(e["labels"])}
                    for n, e in census.items()},
        "dynamic_metrics": sorted(DYNAMIC_METRICS),
        "scenario_events": sorted(scenario),
    }
    site_index = {}
    for s in sites:
        site_index.setdefault(s.name, (s.path, s.line))
    findings.extend(check_obs_schema(built, pinned, schema_rel,
                                     site_index))

    # -- consumer resolution ------------------------------------------
    producers = set(census) | set(DYNAMIC_METRICS) | scenario
    vocab = {n.split(".", 1)[0] for n in producers}
    for rel in CONSUMER_FILES:
        full = os.path.join(root, rel)
        if not os.path.exists(full):
            continue
        with open(full, encoding="utf-8") as fh:
            src = fh.read()
        src_lines = src.splitlines()
        for name, line, mode in consumer_refs(src, rel, vocab):
            if mode == "exact":
                ok = (name in producers
                      or any(name.startswith(p)
                             for p in DYNAMIC_PREFIXES))
            else:
                ok = any(p == name or p.startswith(name)
                         for p in producers)
            if not ok:
                f = Finding(
                    "dangling-consumer", "error", rel, line,
                    f"consumer references "
                    f"{'prefix' if mode == 'prefix' else 'name'} "
                    f"'{name}' that no producer emits",
                    hint="rename the reference to a live metric or "
                         "delete the dead consumer path")
                text = (src_lines[line - 1]
                        if 0 < line <= len(src_lines) else "")
                findings.append(apply_suppressions(f, text))

    # -- documentation coverage (warn, baselineable) ------------------
    doc_full = os.path.join(root, DOC_FILE)
    documented: set[str] = set()
    if os.path.exists(doc_full):
        with open(doc_full, encoding="utf-8") as fh:
            documented = documented_names(fh.read())
    for name in sorted(census):
        if name not in documented:
            path, line = site_index.get(name, (schema_rel, 1))
            findings.append(Finding(
                "undocumented-metric", "warn", path, line,
                f"'{name}' is emitted but absent from the "
                f"{DOC_FILE} instrumentation tables",
                hint="add it to the layer table (or baseline with "
                     "--update-baseline while docs catch up)"))

    # -- wire protocol ------------------------------------------------
    servers, clients = collect_wire(root)
    pinned_wire = (pinned or {}).get("wire", {})
    findings.extend(check_wire(servers, clients, pinned_wire,
                               schema_rel))

    # -- resource pairing ---------------------------------------------
    serving_dir = os.path.join(root, "distkeras_tpu", "serving")
    for f in lint_resource_paths([serving_dir]):
        # iter_py_files prefixes every path with ``root`` (absolute or
        # relative), so the findings always re-anchor cleanly.
        findings.append(Finding(f.rule, f.severity, _rel(f.path, root),
                                f.line, f.message, f.hint,
                                f.suppressed, f.baselined))
    return findings


__all__ = [
    "DYNAMIC_METRICS", "OPERATOR_ROUTES", "EmitSite",
    "census_emits", "scenario_emits", "merge_census", "consumer_refs",
    "documented_names", "server_routes", "client_calls", "collect_wire",
    "check_wire", "lint_resource_source", "lint_resource_paths",
    "collect_telemetry", "build_obs_schema", "save_obs_schema",
    "load_obs_schema", "check_obs_schema", "lint_repo_contracts",
]
