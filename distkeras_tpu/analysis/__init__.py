"""Static analysis: machine-check the invariants the docs only claim.

The lint layers share one findings model (``findings.py``):

* :mod:`~distkeras_tpu.analysis.ir_lint` — trace the trainers' and
  serving engines' REAL compiled step functions (each subsystem exposes
  them via ``traced_for_analysis()``) and audit the closed jaxpr plus
  the post-SPMD compiled HLO: per-step collective census against
  ``scripts/comm_budget.json``, dtype policy, donation coverage,
  host callbacks inside jit, PRNG key reuse.
* :mod:`~distkeras_tpu.analysis.source_lint` — an AST rule engine over
  the package source with JAX-specific rules (wall-clock/np.random in
  traced functions, host syncs in hot loops, import-time jnp compute,
  axis-name typos, undonated step jits, ...).
* :mod:`~distkeras_tpu.analysis.thread_lint` — the concurrency gate's
  static half (raw locks, callbacks/blocking under a lock, double
  acquires) over the threaded core.
* :mod:`~distkeras_tpu.analysis.shard_lint` — the partition-plan gate:
  pure-host plan lint (dead/shadowed/duplicate rules, axis
  divisibility, replicated giants) over every shipped rule plan, plus
  the compiled-placement census (per-tensor shardings + per-device
  byte ledger vs ``scripts/shard_budget.json``) and resharding
  attribution over the same trace targets.
* :mod:`~distkeras_tpu.analysis.contract_lint` — the coordination
  contracts: the telemetry-schema census (every emission site's
  name/kind/label-keys vs ``scripts/obs_schema.json``, consumer and
  documentation resolution), the wire-protocol cross-check between
  every HTTP server and its in-repo clients, and the resource-pairing
  control-flow proof over ``serving/``.

All honor the ``# dkt: ignore[rule]`` suppression syntax and are wired
into CI through ``scripts/graph_lint.py`` and the tier-1 tests
(``tests/test_graph_lint.py`` / ``tests/test_shard_lint.py`` /
``tests/test_contract_lint.py`` / ``tests/test_budget_guards.py``);
see docs/graph_lint.md for the rule catalogue and the budget-update
workflow.
"""

from distkeras_tpu.analysis.contract_lint import (build_obs_schema,
                                                  lint_repo_contracts)
from distkeras_tpu.analysis.findings import Finding, format_findings
from distkeras_tpu.analysis.ir_lint import (CollectiveOp, TraceSpec,
                                             comm_census, lint_trace,
                                             trace_target)
from distkeras_tpu.analysis.shard_lint import (lint_plan,
                                               lint_repo_plans,
                                               placement_census)
from distkeras_tpu.analysis.source_lint import lint_paths, lint_source

__all__ = ["Finding", "format_findings", "TraceSpec", "CollectiveOp",
           "comm_census", "lint_trace", "trace_target", "lint_plan",
           "lint_repo_plans", "placement_census", "lint_source",
           "lint_paths", "build_obs_schema", "lint_repo_contracts"]
