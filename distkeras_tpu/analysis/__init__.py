"""Static analysis: machine-check the invariants the docs only claim.

Two layers over one findings model (``findings.py``):

* :mod:`~distkeras_tpu.analysis.ir_lint` — trace the trainers' and
  serving engines' REAL compiled step functions (each subsystem exposes
  them via ``traced_for_analysis()``) and audit the closed jaxpr plus
  the post-SPMD compiled HLO: per-step collective census against
  ``scripts/comm_budget.json``, dtype policy, donation coverage,
  host callbacks inside jit, PRNG key reuse.
* :mod:`~distkeras_tpu.analysis.source_lint` — an AST rule engine over
  the package source with JAX-specific rules (wall-clock/np.random in
  traced functions, host syncs in hot loops, import-time jnp compute,
  axis-name typos, undonated step jits, ...).

Both honor the ``# dkt: ignore[rule]`` suppression syntax and are wired
into CI through ``scripts/graph_lint.py`` and the tier-1 tests
(``tests/test_graph_lint.py`` / ``tests/test_budget_guards.py``); see
docs/graph_lint.md for the rule catalogue and the budget-update
workflow.
"""

from distkeras_tpu.analysis.findings import Finding, format_findings
from distkeras_tpu.analysis.ir_lint import (CollectiveOp, TraceSpec,
                                             comm_census, lint_trace)
from distkeras_tpu.analysis.source_lint import lint_paths, lint_source

__all__ = ["Finding", "format_findings", "TraceSpec", "CollectiveOp",
           "comm_census", "lint_trace", "lint_source", "lint_paths"]
