"""The findings model both lint layers share.

A finding is (rule id, severity, location, message, fix hint).  The
source layer locates findings at ``path:line``; the IR layer locates
them at the trace-target name (there is no one source line for a
compiled program).  Suppression is per-line for source findings —
``# dkt: ignore[rule-a,rule-b]`` (or a bare ``# dkt: ignore`` for every
rule) on the flagged line — and per-target for IR findings (the
``suppress=`` tuple on :class:`~distkeras_tpu.analysis.ir_lint.TraceSpec`).
Suppressed findings are still *returned* (marked) so tooling can count
them; only unsuppressed ones gate CI.
"""

from __future__ import annotations

import dataclasses
import re

# error: a correctness/semantics violation.  warn: a performance or
# hygiene hazard.  info: census/annotation output, never gating.
SEVERITIES = ("error", "warn", "info")

_IGNORE_RE = re.compile(r"#\s*dkt:\s*ignore(?:\[([\w ,\-]*)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str               # file path, or the IR trace-target name
    line: int | None
    message: str
    hint: str = ""
    suppressed: bool = False

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}")

    @property
    def gating(self) -> bool:
        """Does this finding fail CI?  Unsuppressed error/warn only."""
        return not self.suppressed and self.severity != "info"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        sup = " (suppressed)" if self.suppressed else ""
        hint = f" — {self.hint}" if self.hint else ""
        return f"{loc}: {self.severity} [{self.rule}]{sup} {self.message}{hint}"


def suppressed_rules(line_text: str) -> frozenset | None:
    """Rules a ``# dkt: ignore[...]`` comment on this line suppresses.

    Returns None when the line carries no ignore comment, an empty
    frozenset for the bare ``# dkt: ignore`` (suppress every rule), or
    the named rule set.  The scan is textual — a string literal
    containing the marker would also match, which is harmless (the
    syntax is ours) and keeps the check independent of the tokenizer.
    """
    m = _IGNORE_RE.search(line_text)
    if m is None:
        return None
    if m.group(1) is None:
        return frozenset()
    return frozenset(r.strip() for r in m.group(1).split(",") if r.strip())


def apply_suppressions(finding: Finding, line_text: str) -> Finding:
    """Mark ``finding`` suppressed if ``line_text`` carries a matching
    ignore comment (bare ignores match every rule)."""
    rules = suppressed_rules(line_text)
    if rules is None:
        return finding
    if rules and finding.rule not in rules:
        return finding
    return dataclasses.replace(finding, suppressed=True)


def format_findings(findings) -> str:
    lines = [f.format() for f in findings]
    gating = sum(f.gating for f in findings)
    lines.append(f"{len(lines)} finding(s), {gating} gating")
    return "\n".join(lines)


__all__ = ["Finding", "SEVERITIES", "suppressed_rules",
           "apply_suppressions", "format_findings"]
