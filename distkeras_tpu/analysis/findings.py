"""The findings model both lint layers share.

A finding is (rule id, severity, location, message, fix hint).  The
source layer locates findings at ``path:line``; the IR layer locates
them at the trace-target name (there is no one source line for a
compiled program).  Suppression is per-line for source findings —
``# dkt: ignore[rule-a,rule-b]`` (or a bare ``# dkt: ignore`` for every
rule) on the flagged line — and per-target for IR findings (the
``suppress=`` tuple on :class:`~distkeras_tpu.analysis.ir_lint.TraceSpec`).
Suppressed findings are still *returned* (marked) so tooling can count
them; only unsuppressed ones gate CI.
"""

from __future__ import annotations

import dataclasses
import re

# error: a correctness/semantics violation.  warn: a performance or
# hygiene hazard.  info: census/annotation output, never gating.
SEVERITIES = ("error", "warn", "info")

_IGNORE_RE = re.compile(r"#\s*dkt:\s*ignore(?:\[([\w ,\-]*)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str               # file path, or the IR trace-target name
    line: int | None
    message: str
    hint: str = ""
    suppressed: bool = False
    # Covered by the checked-in warn baseline (scripts/lint_baseline
    # .json): known debt that no longer gates but can only RATCHET
    # down — new findings beyond the recorded count still fail.
    baselined: bool = False

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}")

    @property
    def gating(self) -> bool:
        """Does this finding fail CI?  Unsuppressed, unbaselined
        error/warn only."""
        return (not self.suppressed and not self.baselined
                and self.severity != "info")

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        sup = (" (suppressed)" if self.suppressed
               else " (baselined)" if self.baselined else "")
        hint = f" — {self.hint}" if self.hint else ""
        return f"{loc}: {self.severity} [{self.rule}]{sup} {self.message}{hint}"


def suppressed_rules(line_text: str) -> frozenset | None:
    """Rules a ``# dkt: ignore[...]`` comment on this line suppresses.

    Returns None when the line carries no ignore comment, an empty
    frozenset for the bare ``# dkt: ignore`` (suppress every rule), or
    the named rule set.  The scan is textual — a string literal
    containing the marker would also match, which is harmless (the
    syntax is ours) and keeps the check independent of the tokenizer.
    """
    m = _IGNORE_RE.search(line_text)
    if m is None:
        return None
    if m.group(1) is None:
        return frozenset()
    return frozenset(r.strip() for r in m.group(1).split(",") if r.strip())


def apply_suppressions(finding: Finding, line_text: str) -> Finding:
    """Mark ``finding`` suppressed if ``line_text`` carries a matching
    ignore comment (bare ignores match every rule)."""
    rules = suppressed_rules(line_text)
    if rules is None:
        return finding
    if rules and finding.rule not in rules:
        return finding
    return dataclasses.replace(finding, suppressed=True)


def format_findings(findings) -> str:
    lines = [f.format() for f in findings]
    gating = sum(f.gating for f in findings)
    lines.append(f"{len(lines)} finding(s), {gating} gating")
    return "\n".join(lines)


# ------------------------------------------------------ warn baselines
#
# Per-finding baselines let `warn` rules RATCHET: a checked-in file
# records how many warn findings each (rule, path) pair is allowed,
# existing debt stops gating, and any NEW warn — a higher count at a
# recorded key, or any unrecorded key — still fails CI.  Errors are
# never baselineable (they are correctness violations, not debt), and
# re-recording with fewer findings tightens the ledger, so the only
# stable direction is down.

def baseline_key(finding: Finding) -> str:
    """The ledger key: rule + path (no line numbers — they churn on
    every unrelated edit, which would make the baseline useless)."""
    return f"{finding.rule}:{finding.path.replace(chr(92), '/')}"


def warn_counts(findings) -> dict:
    """Current unsuppressed-warn census, keyed by :func:`baseline_key`
    — what ``--update-baseline`` records."""
    counts: dict[str, int] = {}
    for f in findings:
        if f.severity == "warn" and not f.suppressed:
            counts[baseline_key(f)] = counts.get(baseline_key(f), 0) + 1
    return counts


def apply_baseline(findings, baseline: dict) -> list:
    """Mark warn findings covered by ``baseline`` (a
    ``{key: allowed_count}`` dict) as ``baselined``.  At most the
    recorded count per key is covered, in encounter order — the excess
    (and every unrecorded key) keeps gating, which is exactly the
    ratchet: counts can only shrink."""
    remaining = dict(baseline)
    out = []
    for f in findings:
        if f.severity == "warn" and not f.suppressed:
            key = baseline_key(f)
            if remaining.get(key, 0) > 0:
                remaining[key] -= 1
                f = dataclasses.replace(f, baselined=True)
        out.append(f)
    return out


def save_baseline(path: str, findings) -> dict:
    """Write the current warn census to ``path``; returns it."""
    import json

    counts = warn_counts(findings)
    with open(path, "w") as fh:
        json.dump({"comment": "allowed warn findings per rule:path — "
                              "the ratchet ledger; re-record with "
                              "scripts/graph_lint.py --update-baseline "
                              "and review the diff (counts should "
                              "only go DOWN)",
                   "warn_counts": counts}, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return counts


def load_baseline(path: str) -> dict:
    """Read the warn ledger; a missing/empty file is an empty ledger
    (every warn gates — the pre-baseline behavior)."""
    import json
    import os

    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        return dict(json.load(fh).get("warn_counts", {}))


__all__ = ["Finding", "SEVERITIES", "suppressed_rules",
           "apply_suppressions", "format_findings", "baseline_key",
           "warn_counts", "apply_baseline", "save_baseline",
           "load_baseline"]
