"""IR lint: audit the trainers' REAL compiled step programs.

A :class:`TraceSpec` names one jitted function plus example arguments
(shape structs are fine — nothing executes).  :func:`lint_trace` then

* traces it to a closed jaxpr and walks every sub-jaxpr for the
  **dtype policy** (f64 anywhere; silent bf16/f16 -> f32 upcasts),
  **host callbacks** inside the jit region, and **PRNG key reuse**
  (one key consumed by two samplers with no ``split``/``fold_in``
  between, or sampled loop-invariantly inside a scan/while body);
* lowers + compiles it and parses the post-SPMD HLO into a
  **collective census** (:func:`comm_census`) — op kind, payload
  bytes, replica-group size, and ring-model wire bytes per device —
  the number ``scripts/comm_budget.json`` pins in CI;
* checks **donation coverage**: declared-donated buffers that XLA
  could not consume (lower-time warning), and donated inputs that are
  both read and returned (XLA inserts a copy — the donation buys
  nothing).

Census canonicalization.  XLA's CPU pipeline lacks the
reduce-scatter-creator pass GPU/TPU partitioners run, so a GSPMD
reduce-scatter compiles on the test mesh as ``all-reduce`` followed by
each device slicing its own 1/n chunk.  When every consumer of an
all-reduce provably uses at most a 1/n slice (the consumer is a
``dynamic-slice``, or a fusion whose body slices, with output bytes
<= payload/n), the census records the op with ``canonical:
"reduce-scatter"`` and charges reduce-scatter wire volume — the bytes
any production partitioner (and the wire) would actually move.  The
raw opcode is kept alongside, so the budget diff shows both.

Wire model (ring algorithms, group size n): all-reduce moves
``2(n-1)/n * payload`` per device, reduce-scatter and all-gather
``(n-1)/n * payload``, collective-permute ``payload``.  This is what
makes the ZeRO-1 claim checkable: RS(G) + AG(G) == AR(G) exactly.
"""

from __future__ import annotations

import dataclasses
import json
import re
import warnings
from typing import Any, Callable, Sequence

import jax
import numpy as np

from distkeras_tpu.analysis.findings import Finding

# ------------------------------------------------------------------ specs


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """One jitted function the IR lint should reach.

    ``fn`` must be the *real* jitted callable the subsystem executes
    (the ``traced_for_analysis()`` hooks hand these out), so the lint
    sees production donation/sharding flags, not a reimplementation.
    ``args`` may mix concrete arrays, ``ShapeDtypeStruct``s and None.
    ``suppress`` is the IR layer's ignore syntax: rule ids waived for
    this target (the per-line ``# dkt: ignore[...]`` form has no
    single line to attach to in a compiled program).
    """

    name: str
    fn: Callable
    args: tuple
    # The donate_argnums the hook passed to jax.jit — carried
    # explicitly (jit wrappers do not expose them portably).
    donate_argnums: tuple = ()
    suppress: tuple = ()
    # Total parameter bytes of the model this step trains (the hooks
    # fill it in) — the zero parity check's reference volume P.
    params_bytes: int | None = None
    # The DP partner target whose gradient all-reduce this target's
    # declared RS+AG exchange must replace at equal volume.
    zero1_parity_with: str | None = None
    # Which ZeRO stage's declared scopes to measure (1: the post-scan
    # RS + explicit AG; 2: the in-scan accumulator RS + update AG; 3:
    # the gather-on-use AG + backward grad RS).
    zero_stage: int = 1


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in the compiled program (aggregated by kind).

    ``dtype`` is the HLO element type of the payload ("f32", "s8",
    "bf16", ... — "+"-joined when a tuple-shaped collective mixes
    types).  Payload bytes were always computed from the compiled
    shapes, so compressed exchanges were never *miscounted*; recording
    the dtype makes the budget PROVE the wire carries int8, not f32 —
    a census that only showed byte totals could silently pass an
    exchange that decompressed before the wire.
    """

    op: str               # HLO opcode as compiled
    canonical: str        # opcode after AR+slice canonicalization
    payload_bytes: int
    group_size: int
    count: int = 1
    dtype: str = "f32"

    @property
    def wire_bytes(self) -> float:
        """Ring-model per-device wire bytes for ``count`` ops."""
        n = max(self.group_size, 1)
        per = {
            "all-reduce": 2 * (n - 1) / n * self.payload_bytes,
            "reduce-scatter": (n - 1) / n * self.payload_bytes,
            "all-gather": (n - 1) / n * self.payload_bytes,
            "all-to-all": (n - 1) / n * self.payload_bytes,
            "collective-permute": float(self.payload_bytes),
        }.get(self.canonical, float(self.payload_bytes))
        return per * self.count

    def as_json(self) -> dict:
        return {"op": self.op, "canonical": self.canonical,
                "dtype": self.dtype,
                "payload_bytes": self.payload_bytes,
                "group_size": self.group_size, "count": self.count,
                "wire_bytes": round(self.wire_bytes, 1)}


# ------------------------------------------------------------ HLO parsing

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "all-to-all", "collective-permute")

_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<rhs>.*)$")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%(?P<name>[\w.\-]+)\s+\(.*\)\s+->")


def _shape_bytes(segment: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(segment):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _shape_dtypes(segment: str) -> str:
    """Element type(s) of a shape segment: "f32", "s8", ... — ordered,
    de-duplicated, "+"-joined for tuple shapes mixing types ("?" when
    no shape parses).  The census field that distinguishes an int8
    compressed payload from the f32 it replaced."""
    seen = []
    for dtype, _ in _SHAPE_RE.findall(segment):
        if dtype in _DTYPE_BYTES and dtype not in seen:
            seen.append(dtype)
    return "+".join(seen) or "?"


def _group_size(line: str, default: int) -> int:
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)
    if m:
        return int(m.group(2))
    m = re.search(r"replica_groups=\{\{([^}]*)\}", line)
    if m:
        return m.group(1).count(",") + 1
    return default


@dataclasses.dataclass
class _Instr:
    name: str
    opcode: str
    result_seg: str
    operand_refs: tuple
    calls: str | None
    line: str
    computation: str


def _parse_instrs(hlo: str) -> tuple[dict, dict]:
    """HLO text -> ({instr name: _Instr}, {computation name: body text}).

    Text-level, deliberately: the census needs opcodes, shapes,
    operand references and fusion bodies — all stable in HLO dumps —
    and must not depend on XLA python bindings.
    """
    instrs: dict[str, _Instr] = {}
    comps: dict[str, list] = {}
    current = "main"
    for raw in hlo.splitlines():
        cm = _COMP_RE.match(raw.strip())
        if cm and raw.rstrip().endswith("{"):
            current = cm.group("name")
            comps[current] = []
            continue
        comps.setdefault(current, []).append(raw)
        m = _INSTR_RE.match(raw)
        if not m:
            continue
        rhs = m.group("rhs")
        om = re.search(r"(?:^|\)\s|\}\s|\]\s|\s)([a-z][a-z0-9\-]*)\(", rhs)
        if not om:
            continue
        opcode = om.group(1)
        result_seg = rhs[:om.start(1)]
        # Data operands: the first balanced paren group after the
        # opcode.  Attribute refs (calls=%c, to_apply=%r) come later.
        depth, start, end = 0, om.end(1), None
        for i in range(om.end(1), len(rhs)):
            if rhs[i] == "(":
                depth += 1
            elif rhs[i] == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        operands = rhs[om.end(1) + 1:end] if end else ""
        refs = tuple(re.findall(r"%([\w.\-]+)", operands))
        calls = re.search(r"calls=%([\w.\-]+)", rhs)
        instrs[m.group("name")] = _Instr(
            name=m.group("name"), opcode=opcode, result_seg=result_seg,
            operand_refs=refs, calls=calls.group(1) if calls else None,
            line=raw, computation=current)
    return instrs, {k: "\n".join(v) for k, v in comps.items()}


def _consumes_sliced(instr: _Instr, comps: dict) -> bool:
    """Does ``instr`` read only a slice of its operand?  True for a
    dynamic-slice, or a fusion whose body dynamic-slices."""
    if instr.opcode == "dynamic-slice":
        return True
    if instr.opcode == "fusion" and instr.calls:
        return "dynamic-slice(" in comps.get(instr.calls, "")
    return False


def comm_census(hlo: str, default_group: int | None = None
                ) -> list[CollectiveOp]:
    """Collective census of one compiled HLO module, aggregated by
    (canonical op, payload, group).  See the module docstring for the
    AR -> reduce-scatter canonicalization rule."""
    if default_group is None:
        default_group = jax.device_count()
    instrs, comps = _parse_instrs(hlo)
    raw: list[CollectiveOp] = []
    for ins in instrs.values():
        op = ins.opcode
        if op.endswith("-start"):
            op = op[:-len("-start")]
        if op not in _COLLECTIVES:
            continue
        n = _group_size(ins.line, default_group)
        if op == "reduce-scatter":
            # Payload = the full pre-scatter operand (what the ring
            # carries), not the 1/n result.
            payload = _operand_bytes(ins)
        else:
            payload = _shape_bytes(ins.result_seg)
        dtype = _shape_dtypes(ins.result_seg)
        canonical = op
        if op == "all-reduce" and n > 1:
            consumers = [c for c in instrs.values()
                         if ins.name in c.operand_refs
                         and c.computation == ins.computation]
            if consumers and all(
                    _consumes_sliced(c, comps)
                    and _shape_bytes(c.result_seg) * n <= payload
                    for c in consumers):
                canonical = "reduce-scatter"
        raw.append(CollectiveOp(op=op, canonical=canonical,
                                payload_bytes=payload, group_size=n,
                                dtype=dtype))
    # Aggregate identical ops so the census is order-stable.
    agg: dict[tuple, int] = {}
    for c in raw:
        key = (c.op, c.canonical, c.payload_bytes, c.group_size, c.dtype)
        agg[key] = agg.get(key, 0) + 1
    return [CollectiveOp(op=k[0], canonical=k[1], payload_bytes=k[2],
                         group_size=k[3], dtype=k[4], count=v)
            for k, v in sorted(agg.items())]


def _operand_bytes(ins: _Instr) -> int:
    """Total bytes of an instruction's data operands (shapes are
    inlined in the operand list: ``reduce-scatter(f32[64]{0} %x)``)."""
    seg = ins.line.split(ins.opcode + "(", 1)
    if len(seg) < 2:
        return _shape_bytes(ins.result_seg)
    depth, out = 1, []
    for ch in seg[1]:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                break
        out.append(ch)
    return _shape_bytes("".join(out))


def census_wire_total(census: Sequence[CollectiveOp]) -> float:
    return round(sum(c.wire_bytes for c in census), 1)


# ------------------------------------------------------------ jaxpr walk


def _subjaxprs(eqn):
    """(inner jaxpr, outer->inner var mapping) pairs for every
    call-like param of ``eqn`` — pjit, scan, while, cond, shard_map,
    custom_*; the var mapping keeps PRNG identity flowing across the
    boundary when arities line up (unknown layouts map nothing —
    conservative, never a false alias)."""
    if eqn.primitive.name == "while":
        # invars = [cond_consts..., body_consts..., carry...]; the two
        # jaxprs see different slices — align each explicitly.
        nc = eqn.params.get("cond_nconsts", 0)
        nb = eqn.params.get("body_nconsts", 0)
        cond, body = eqn.params["cond_jaxpr"], eqn.params["body_jaxpr"]
        carry = eqn.invars[nc + nb:]
        return [
            (cond.jaxpr, dict(zip(cond.jaxpr.invars,
                                  list(eqn.invars[:nc]) + list(carry)))),
            (body.jaxpr, dict(zip(body.jaxpr.invars,
                                  list(eqn.invars[nc:nc + nb])
                                  + list(carry)))),
        ]
    out = []
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            jaxpr = getattr(v, "jaxpr", None)
            if jaxpr is None and hasattr(v, "eqns"):
                jaxpr = v
            if jaxpr is None:
                continue
            if len(jaxpr.invars) == len(eqn.invars):
                mapping = dict(zip(jaxpr.invars, eqn.invars))
            elif len(eqn.invars) > len(jaxpr.invars):
                # cond branches (pred leads), while bodies: inner
                # invars align with the TAIL of the outer operands.
                mapping = dict(zip(jaxpr.invars,
                                   eqn.invars[-len(jaxpr.invars):]))
            else:
                mapping = {}
            out.append((jaxpr, mapping))
    return out


_PRNG_CONSUMING = {"random_bits", "random_gamma"}
_LOOP_PRIMS = {"scan", "while"}


def _is_key(var) -> bool:
    dtype = getattr(getattr(var, "aval", None), "dtype", None)
    try:
        return dtype is not None and jax.numpy.issubdtype(
            dtype, jax.dtypes.prng_key)
    except TypeError:
        return False


def _audit_jaxpr(closed, spec: TraceSpec) -> list[Finding]:
    findings: list[Finding] = []
    seen_rules: set[tuple] = set()

    def add(rule, severity, message, hint=""):
        key = (rule, message)
        if key in seen_rules:
            return
        seen_rules.add(key)
        findings.append(Finding(
            rule=rule, severity=severity, path=spec.name, line=None,
            message=message, hint=hint,
            suppressed=rule in spec.suppress))

    # PRNG bookkeeping: canonical identity per key var (flow through
    # sub-jaxpr boundaries), sampler-consumption counts, and the set of
    # identities that entered a loop body as loop-invariant captures.
    root_of: dict = {}
    consumed: dict = {}

    def root(v):
        return root_of.setdefault(v, v)

    # f32 ACCUMULATION of a low-precision value is the standard,
    # intentional upcast (sum/mean/argmax promote internally); only
    # upcasts that escape into non-reduction math are "silent".
    reductions = {"reduce_sum", "reduce_prod", "reduce_max",
                  "reduce_min", "argmax", "argmin", "reduce_precision"}

    def walk(jaxpr, in_loop: frozenset):
        uses: dict = {}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    uses.setdefault(v, []).append(eqn.primitive.name)
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for v in eqn.outvars:
                dtype = getattr(getattr(v, "aval", None), "dtype", None)
                if dtype is not None and str(dtype) in ("float64",
                                                        "complex128"):
                    add("dtype-f64", "error",
                        f"f64 value produced by `{prim}`",
                        "the repo's dtype policy is f32/bf16 compute; "
                        "enable-x64 leaks or np.float64 literals "
                        "usually cause this")
            if prim == "convert_element_type":
                src = getattr(eqn.invars[0].aval, "dtype", None)
                dst = eqn.params.get("new_dtype")
                consumers = uses.get(eqn.outvars[0], [])
                accum_only = bool(consumers) and all(
                    c in reductions for c in consumers)
                if (src is not None and str(src) in ("bfloat16", "float16")
                        and str(dst) in ("float32", "float64")
                        and not accum_only):
                    add("dtype-upcast", "warn",
                        f"silent {src} -> {dst} upcast in the traced "
                        "program",
                        "on a low-precision compute path an upcast "
                        "doubles the bytes XLA moves; cast explicitly "
                        "where precision is required and keep the rest "
                        "low-precision")
            if prim.endswith("callback") or prim in (
                    "outside_call", "host_callback_call"):
                add("host-callback", "warn",
                    f"host callback `{prim}` inside the jit region",
                    "each call is a device->host round-trip per "
                    "execution; hoist it out of the step or gate it "
                    "behind a debug flag")
            # PRNG: samplers consume; split/fold_in derive fresh keys.
            if prim in _PRNG_CONSUMING:
                for v in eqn.invars:
                    if not _is_key(v):
                        continue
                    r = root(v)
                    consumed[r] = consumed.get(r, 0) + 1
                    if consumed[r] > 1:
                        add("prng-reuse", "error",
                            "one PRNG key is consumed by two samplers "
                            "with no split/fold_in between",
                            "correlated draws: derive a fresh key per "
                            "sampler (jax.random.split / fold_in)")
                    elif r in in_loop:
                        add("prng-reuse", "error",
                            "a loop-invariant PRNG key is consumed "
                            "inside a scan/while body",
                            "every iteration redraws the same bits; "
                            "fold the loop index into the key first")
            inner_loop = in_loop
            if prim in _LOOP_PRIMS:
                # Only the truly loop-INVARIANT key inputs — the
                # leading consts (scan) / cond+body consts (while).
                # The carry and scanned-over xs vary per iteration, so
                # scanning over pre-split keys is the CORRECT pattern
                # and must not flag.
                if prim == "scan":
                    n_inv = eqn.params.get("num_consts", 0)
                else:
                    n_inv = (eqn.params.get("cond_nconsts", 0)
                             + eqn.params.get("body_nconsts", 0))
                inner_loop = in_loop | frozenset(
                    root(v) for v in eqn.invars[:n_inv] if _is_key(v))
            subs = _subjaxprs(eqn)
            if prim == "cond":
                # Branches are mutually exclusive at runtime: count
                # each from the same baseline and keep the per-key
                # MAX, or a key consumed once in every branch would
                # read as reuse.
                base = dict(consumed)
                merged = dict(base)
                for sub, mapping in subs:
                    for inner_v, outer_v in mapping.items():
                        if _is_key(inner_v) or _is_key(outer_v):
                            root_of[inner_v] = root(outer_v)
                    consumed.clear()
                    consumed.update(base)
                    walk(sub, inner_loop)
                    for key_root, n in consumed.items():
                        merged[key_root] = max(merged.get(key_root, 0),
                                               n)
                consumed.clear()
                consumed.update(merged)
            else:
                for sub, mapping in subs:
                    for inner_v, outer_v in mapping.items():
                        if _is_key(inner_v) or _is_key(outer_v):
                            root_of[inner_v] = root(outer_v)
                    walk(sub, inner_loop)

    walk(closed.jaxpr, frozenset())
    return findings


# ---------------------------------------------------------- donation


def _donated_flat_indices(spec: TraceSpec) -> list[int]:
    """Flat invar indices of the donated argument leaves, from the
    spec's donate_argnums and the example args' pytree shapes."""
    argnums = set(spec.donate_argnums if isinstance(
        spec.donate_argnums, (tuple, list)) else (spec.donate_argnums,))
    idx, out = 0, []
    for i, a in enumerate(spec.args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in argnums:
            out.extend(range(idx, idx + n))
        idx += n
    return out


def _audit_donation(closed, spec: TraceSpec, lower_warnings) -> list[Finding]:
    findings = []

    def add(rule, severity, message, hint=""):
        findings.append(Finding(
            rule=rule, severity=severity, path=spec.name, line=None,
            message=message, hint=hint,
            suppressed=rule in spec.suppress))

    for w in lower_warnings:
        msg = str(w.message)
        if "donated" in msg.lower() or "donation" in msg.lower():
            add("donation-unused", "warn",
                "declared-donated buffer(s) could not be consumed: "
                + msg.split("See an explanation")[0].strip(),
                "a donated leaf needs a same-shape/dtype output to "
                "alias; drop the donation or return the updated value")

    donated = set(_donated_flat_indices(spec))
    if donated:
        invars = closed.jaxpr.invars
        outset = set(id(v) for v in closed.jaxpr.outvars)
        used = set()
        for eqn in closed.jaxpr.eqns:
            used.update(id(v) for v in eqn.invars)
        for i in donated:
            if i >= len(invars):
                continue
            v = invars[i]
            if id(v) in outset and id(v) in used:
                add("donation-read", "warn",
                    f"donated input #{i} is both read and returned "
                    "unchanged",
                    "XLA must copy to honor the aliasing, so the "
                    "donation buys nothing; return the derived value "
                    "or drop the donation for this argument")
    return findings


# ------------------------------------------------------------ entrypoint


@dataclasses.dataclass(frozen=True)
class TraceArtifacts:
    """One target's trace/lower/compile products, produced ONCE so the
    IR lint and the shard lint (analysis/shard_lint.py — placement
    census, resharding attribution) never pay a second backend compile
    for the same program.  ``compiled``/``hlo`` are None when only the
    jaxpr-level audits were requested."""

    closed: Any                 # the ClosedJaxpr (spec.fn traced)
    compiled: Any | None        # jax.stages.Compiled
    hlo: str | None             # post-SPMD HLO text of `compiled`
    lower_warnings: tuple = ()  # warnings captured during trace+lower


def trace_target(spec: TraceSpec, compile: bool = True) -> TraceArtifacts:
    """Trace (jaxpr), lower, and — unless ``compile=False`` — compile
    one target, capturing the lower-time diagnostics the donation audit
    reads.  Nothing executes."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        traced = spec.fn.trace(*spec.args)
        closed = traced.jaxpr
        # Lower the EXISTING trace (no second tracing pass) — cheap,
        # and it emits the donation diagnostics; only the census needs
        # the (expensive) backend compile.
        lowered = traced.lower()
        compiled = lowered.compile() if compile else None
    return TraceArtifacts(closed=closed, compiled=compiled,
                          hlo=compiled.as_text() if compiled else None,
                          lower_warnings=tuple(caught))


def lint_trace(spec: TraceSpec, compile_census: bool = True,
               artifacts: TraceArtifacts | None = None
               ) -> tuple[list[Finding], list[CollectiveOp]]:
    """Run every IR audit on one trace target.

    Returns (findings, collective census).  ``compile_census=False``
    skips the lower+compile (jaxpr-only audits — cheap when the census
    is not needed).  Pass ``artifacts`` (from :func:`trace_target`) to
    reuse an existing trace+compile.
    """
    art = artifacts if artifacts is not None else trace_target(
        spec, compile=compile_census)
    census: list[CollectiveOp] = (
        comm_census(art.hlo) if art.hlo is not None else [])
    findings = _audit_jaxpr(art.closed, spec)
    findings += _audit_donation(art.closed, spec, art.lower_warnings)
    return findings, census


# ------------------------------------------------------------ budgets


def census_to_budget(census: Sequence[CollectiveOp]) -> dict:
    return {"collectives": [c.as_json() for c in census],
            "wire_total": census_wire_total(census)}


def check_budget(name: str, census: Sequence[CollectiveOp],
                 budgets: dict) -> list[Finding]:
    """Compare one target's census against the checked-in budget.
    Any drift — new ops, missing ops, changed bytes — is a finding;
    re-record deliberate changes with ``graph_lint.py
    --update-budgets`` and review the JSON diff."""
    entry = budgets.get(name)
    if entry is None:
        return [Finding(
            rule="comm-budget", severity="error", path=name, line=None,
            message="no communication budget recorded for this target",
            hint="run scripts/graph_lint.py --update-budgets")]
    got = census_to_budget(census)
    want = {"collectives": entry.get("collectives", []),
            "wire_total": entry.get("wire_total")}
    if got == want:
        return []
    return [Finding(
        rule="comm-budget", severity="error", path=name, line=None,
        message=(f"collective census drifted from the budget: expected "
                 f"{want['wire_total']} wire bytes "
                 f"({len(want['collectives'])} op kinds), compiled to "
                 f"{got['wire_total']} wire bytes "
                 f"({len(got['collectives'])} op kinds)"),
        hint="if the change is intentional, re-record with "
             "scripts/graph_lint.py --update-budgets and review the "
             "scripts/comm_budget.json diff")]


def declared_zero_exchange(spec: TraceSpec, stage: int | None = None
                           ) -> dict:
    """Measure the ZeRO exchange the step DECLARES, from its traced
    jaxpr.  Per stage (``spec.zero_stage`` unless overridden):

    * stage 1 — ``rs_bytes``: the sharding-constraint reduce-scatters
      under the ``zero1/reduce_scatter`` scope; ``ag_bytes``: the
      explicit all-gathers (shard_map) under ``zero1/all_gather``;
    * stage 2 — ``rs_bytes``: the in-scan accumulator constraints
      under ``zero2/accum_scatter`` (one program occurrence covers the
      whole window — the scan body is one sub-jaxpr); ``ag_bytes``:
      the update all-gathers under ``zero2/all_gather``;
    * stage 3 — ``ag_bytes``: the gather-on-use constraints under
      ``zero3/param_gather``; ``rs_bytes``: the backward cotangent
      constraints under ``zero3/grad_scatter``.  NOTE the backward
      eqn's name stack reads ``transpose(jvp(zero3/param_gather))/
      zero3/grad_scatter`` — it contains BOTH scopes, so the scatter
      scope takes precedence.

    These are the real program's eqns (the hooks hand out the executed
    step), just read before GSPMD picks a backend-specific
    implementation."""
    stage = spec.zero_stage if stage is None else stage
    closed = spec.fn.trace(*spec.args).jaxpr
    out = {"rs_bytes": 0, "ag_bytes": 0}
    rs_scope = {1: "zero1/reduce_scatter", 2: "zero2/accum_scatter",
                3: "zero3/grad_scatter"}[stage]
    ag_scope = {1: "zero1/all_gather", 2: "zero2/all_gather",
                3: "zero3/param_gather"}[stage]
    ag_prim = "sharding_constraint" if stage == 3 else "shard_map"

    def nbytes(eqn):
        return sum(int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
                   for v in eqn.outvars if hasattr(v.aval, "shape"))

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            stack = str(getattr(eqn.source_info, "name_stack", ""))
            prim = eqn.primitive.name
            if prim == "sharding_constraint" and rs_scope in stack:
                out["rs_bytes"] += nbytes(eqn)
            elif prim == ag_prim and ag_scope in stack:
                out["ag_bytes"] += nbytes(eqn)
            for sub, _ in _subjaxprs(eqn):
                walk(sub)

    walk(closed.jaxpr)
    return out


def declared_zero1_exchange(spec: TraceSpec) -> dict:
    """Stage-1 spelling of :func:`declared_zero_exchange` (kept for
    older call sites)."""
    return declared_zero_exchange(spec, stage=1)


def check_zero1_parity(z1_spec: TraceSpec, dp_census) -> list[Finding]:
    """The ZeRO acceptance check (stages 1/2/3; the stage comes from
    ``spec.zero_stage``): the declared scatter/gather exchange must be
    PAD-FREE — each leg moves exactly the model's parameter bytes.

    With P = the model's parameter bytes, the check asserts (all
    measured, nothing assumed):

    1. the zero step declares scatter payload == P — i.e. the bucket
       layout added ZERO padding — and gather payload == P.  Per
       program occurrence: stage 1's post-scan RS and update AG, stage
       2's in-scan accumulator RS (the scan body is one occurrence
       covering the whole window — so the per-ROUND wire is
       ``window x RS(P) + AG(P)`` vs replicated DP's ``window x
       AR(P)``, stage 2's saving) and update AG, stage 3's
       gather-on-use AG and backward grad RS (no update gather at all);
    2. by the ring identity RS(P) + AG(P) carries exactly AR(P)'s
       wire bytes: ``2 (n-1)/n P`` per device — so stage 1's per-round
       exchange equals the replicated-DP gradient all-reduce volume,
       and stages 2/3 never exceed it;
    3. the DP partner's COMPILED all-reduces move >= P gradient bytes;
       moving more than P is reported as a warn finding (e.g. tied
       weights whose gradient contributions XLA reduces separately).

    (1)+(2) prove the headline claim; (3) pins it to the compiled DP
    program.  Compiled zero bytes are pinned separately by the census
    budget: XLA CPU implements the declared exchange hierarchically
    (subgroup all-reduces + permutes), a backend artifact the budget
    tracks but parity must not depend on.
    """
    findings = []
    P = z1_spec.params_bytes
    stage = z1_spec.zero_stage

    def add(rule, severity, message, hint=""):
        findings.append(Finding(
            rule=rule, severity=severity, path=z1_spec.name, line=None,
            message=message, hint=hint,
            suppressed=rule in z1_spec.suppress))

    if not P:
        add("zero1-parity", "error",
            "zero parity target carries no params_bytes reference",
            "the traced_for_analysis hook must fill params_bytes")
        return findings
    decl = declared_zero_exchange(z1_spec)
    if decl["rs_bytes"] != P or decl["ag_bytes"] != P:
        add("zero1-parity", "error",
            f"declared stage-{stage} exchange scatter="
            f"{decl['rs_bytes']} / gather={decl['ag_bytes']} bytes != "
            f"parameter bytes {P} — the exchange no longer carries "
            "exactly the volume the proof pins",
            "nonzero bucket padding (a leaf size stopped dividing by "
            "the data axis) or a missing zero named scope; inspect "
            "collectives.Zero1Layout for this parameter tree")
    # The DP partner's compiled gradient all-reduce: every AR big
    # enough to be a gradient leaf (scalars like the loss mean are
    # bookkeeping, not exchange).
    min_leaf = max(32, min((c.payload_bytes for c in dp_census
                            if c.op == "all-reduce"), default=0))
    dp_grad = sum(c.payload_bytes * c.count for c in dp_census
                  if c.op == "all-reduce" and c.payload_bytes >= min_leaf)
    if dp_grad < P:
        add("zero1-parity", "error",
            f"DP partner compiles only {dp_grad} gradient all-reduce "
            f"bytes for {P} parameter bytes — the reference volume is "
            "not what zero1 replaces",
            "the gradient-AR classifier (payload >= smallest leaf) "
            "may need tuning for this model, or DP stopped "
            "all-reducing some leaves")
    elif dp_grad > P:
        # Promoted info -> warn (PR 4): the one known instance — the
        # tied embedding's two gradient contributions all-reduced
        # separately in replicated-DP LMTrainer — is fixed (the
        # shard_map-local gradient construction sums them before the
        # exchange, trainers/lm.py), so any reappearance is a
        # regression and gates CI.
        add("comm-redundant-ar", "warn",
            f"replicated-DP compiles {dp_grad} all-reduce bytes for "
            f"{P} parameter bytes ({dp_grad - P} redundant)",
            "usually tied weights whose gradient contributions XLA "
            "reduces separately instead of summing locally first "
            "(sum them before the exchange, as LMTrainer's "
            "_dp_local_value_and_grad does); zero1's declared "
            "exchange does not inherit this")
    return findings


def load_budgets(path: str) -> dict:
    with open(path) as f:
        return json.load(f)["targets"]


def save_budgets(path: str, budgets: dict, device_count: int | None = None
                 ) -> None:
    doc = {
        "comment": "per-step collective census (payload/wire bytes per "
                   "device, ring model) on the 8-device CPU mesh; "
                   "re-record with scripts/graph_lint.py "
                   "--update-budgets and review the diff",
        "device_count": (device_count if device_count is not None
                         else jax.device_count()),
        "targets": budgets,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


__all__ = ["TraceSpec", "CollectiveOp", "TraceArtifacts",
           "trace_target", "comm_census", "lint_trace",
           "census_wire_total", "census_to_budget", "check_budget",
           "declared_zero_exchange", "declared_zero1_exchange",
           "check_zero1_parity", "load_budgets", "save_budgets"]
