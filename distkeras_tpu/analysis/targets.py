"""The repo's standard IR-lint trace targets.

One definition shared by ``scripts/graph_lint.py`` and the tier-1
budget tests: a fixed tiny model per trainer family on the 8-device
CPU mesh, reached through each subsystem's ``traced_for_analysis()``
hook so the lint audits the REAL jitted step programs.  Model shapes
are chosen with every parameter-leaf size divisible by the data-axis
size, so the ZeRO-1 bucket layout is pad-free and the parity check is
exact.

Builders import keras/transformer lazily — importing this module must
stay free of backend initialization.
"""

from __future__ import annotations

import dataclasses

from distkeras_tpu.analysis.ir_lint import TraceSpec

# (zero target, its replicated-DP partner, stage) — the triples the
# declared-exchange parity proof runs on (ir_lint.check_zero1_parity;
# stages 2/3 measure their own scopes — see declared_zero_exchange).
ZERO_PARITY_TARGETS = (
    ("adag_zero1/accum_step", "adag_dp/accum_step", 1),
    ("adag_zero2/accum_step", "adag_dp/accum_step", 2),
    ("adag_zero3/accum_step", "adag_dp/accum_step", 3),
    ("lmtrainer_zero1/train_step", "lmtrainer_dp/train_step", 1),
    ("lmtrainer_zero2/train_step", "lmtrainer_dp/train_step", 2),
    ("lmtrainer_zero3/train_step", "lmtrainer_dp/train_step", 3),
)

# Stage-1 pairs (kept: the historical name some callers import).
ZERO1_PARITY_PAIRS = tuple(
    (z, dp) for z, dp, stage in ZERO_PARITY_TARGETS if stage == 1)


def _lm_cfg():
    from distkeras_tpu.models import transformer as tfm

    # All leaf sizes divide by 8: embedding 64x32, pos 16x32, attn
    # 32x32, mlp 32x64/64x32, norms 32.
    return tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=16)


def _mlp_trainer(zero1: bool = False, **kw):
    import keras

    import distkeras_tpu as dk

    # 8 -> 16 -> 8: kernels 8x16 / 16x8, biases 16 / 8 — every leaf
    # size a multiple of the 8-wide data axis.
    model = keras.Sequential([keras.layers.Input((8,)),
                              keras.layers.Dense(16, activation="relu"),
                              keras.layers.Dense(8)])
    return dk.ADAG(model, loss="sparse_categorical_crossentropy",
                   worker_optimizer="adam", learning_rate=0.05,
                   batch_size=4, communication_window=2, zero1=zero1,
                   **kw)


def _mlp_dataset():
    import numpy as np

    import distkeras_tpu as dk

    rng = np.random.default_rng(0)
    return dk.Dataset({
        "features": rng.normal(size=(64, 8)).astype(np.float32),
        "label": rng.integers(0, 8, 64).astype(np.int32)})


def adag_targets() -> list[TraceSpec]:
    ds = _mlp_dataset()
    specs = (_mlp_trainer(zero1=False).traced_for_analysis(ds)
             + _mlp_trainer(zero1=True).traced_for_analysis(ds)
             # ZeRO stages 2/3: the in-scan scattered accumulator and
             # the gather-on-use parameter census (docs/zero1.md).
             + _mlp_trainer(zero=2).traced_for_analysis(ds)
             + _mlp_trainer(zero=3).traced_for_analysis(ds)
             # Exchange-layer variants (docs/lowcomm.md): the adasum
             # merge and the local-SGD period whose census pins the
             # 1/H per-step collective-count claim.
             + _mlp_trainer(merge_rule="adasum").traced_for_analysis(ds)
             + _mlp_trainer(sync_every=4).traced_for_analysis(ds))
    return _pair(specs)


def lm_targets() -> list[TraceSpec]:
    import distkeras_tpu as dk

    cfg = _lm_cfg()
    specs = []
    # compress="int8": the error-feedback exchange whose census pins
    # the <= 1/4 gradient-wire-bytes claim (s8 payloads) against the
    # dp baseline; zero1 x int8 pins the compressed reduce-scatter
    # leg; zero=2/3 pin the scattered-accumulator and gather-on-use
    # programs; the codec-rules variant pins the per-bucket wire
    # dtypes (embeddings top-k, everything else int8).
    for kw in ({}, {"zero1": True}, {"zero": 2}, {"zero": 3},
               {"fsdp": True},
               {"compress": "int8"},
               {"compress": (("emb", "topk"), (".*", "int8"))},
               {"zero1": True, "compress": "int8"}):
        t = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, **kw)
        specs += t.traced_for_analysis()
    return _pair(specs)


def serving_targets() -> list[TraceSpec]:
    """Both serving engines' real jitted programs, reached through the
    split package (serving/lanes.py, serving/speculative.py): the
    decode/draft-verify steps AND the admission chunk programs (the
    round-10 engine builds — chunked-prefill continuations and
    prefix-pool gathers share the admission program shape, so the
    pooled ContinuousBatcher variant below covers the gather path).
    The paged engine's targets include the round-17 disaggregated
    block-transfer pair (export read + import splice — the
    prefill/decode hop's device programs)."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm

    cfg = _lm_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    cb = dk.ContinuousBatcher(params, cfg, lanes=2,
                              per_request_sampling=True,
                              prompt_buckets=(8,))
    pool = dk.PrefixPool(cfg, slots=2)
    cbp = dk.ContinuousBatcher(params, cfg, lanes=2,
                               prompt_buckets=(8,), prefill_chunk=8,
                               prefix_pool=pool)
    # Paged engine (round 12): the page-table-gather decode step and
    # the block-scatter admission program.
    pgd = dk.PagedBatcher(params, cfg, lanes=2, block=4, n_blocks=9,
                          prompt_buckets=(8,))
    draft = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_len=16)
    dparams = tfm.init_params(jax.random.key(1), draft)
    sb = dk.SpeculativeBatcher(params, dparams, cfg, draft, lanes=2,
                               n_draft=2, temperature=0.7)
    # Pod-sharded engine (round 14): the decode step whose census
    # pins the per-step collectives GSPMD inserts for the TP layout
    # (one psum pair per block + the unembed exchange) — the serve
    # path's wire budget, the way the training steps pin theirs.
    # NOTE the CPU partitioner's AR+slice artifact applies here too:
    # payload/op counts are exact, the reduce-scatter spelling is
    # declared-level until a hardware session (ROADMAP item 5).
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.sharding import serving_plan

    mesh = make_mesh(MeshSpec(data=4, model=2))
    cbs = dk.ContinuousBatcher(params, cfg, lanes=2,
                               prompt_buckets=(8,),
                               plan=serving_plan(), mesh=mesh)
    return (cb.traced_for_analysis() + cbp.traced_for_analysis()
            + pgd.traced_for_analysis() + sb.traced_for_analysis()
            + cbs.traced_for_analysis())


def _pair(specs: list[TraceSpec]) -> list[TraceSpec]:
    """Attach the declared parity partners (and stage) to the zero
    specs."""
    names = {s.name for s in specs}
    out = []
    for s in specs:
        for z, dp, stage in ZERO_PARITY_TARGETS:
            if s.name == z and dp in names:
                s = dataclasses.replace(s, zero1_parity_with=dp,
                                        zero_stage=stage)
        out.append(s)
    return out


def async_targets() -> list[TraceSpec]:
    """The async tier (docs/async.md): the intra-host accumulation
    step AsyncDP trains with, plus the cross-host wire leg — one
    compiled aggregation wave whose all-gather payload dtype the
    census pins (``asyncdp_wire/adasum_int8`` must carry s8, the
    proof that cross-host deltas ride the int8 codec; the ``sum``
    variant documents the uncompressed f32 wire for comparison)."""
    import keras

    import distkeras_tpu as dk

    def trainer(**kw):
        model = keras.Sequential([keras.layers.Input((8,)),
                                  keras.layers.Dense(16,
                                                     activation="relu"),
                                  keras.layers.Dense(8)])
        return dk.AsyncDP(model, hosts=2, tau=2,
                          loss="sparse_categorical_crossentropy",
                          worker_optimizer="adam", learning_rate=0.05,
                          batch_size=4, communication_window=2, **kw)

    ds = _mlp_dataset()
    return (trainer(async_merge="adasum",
                    async_compress="int8").traced_for_analysis(ds)
            + [s for s in trainer(async_merge="sum",
                                  async_compress=None)
               .traced_for_analysis(ds)
               if s.name.startswith("asyncdp_wire/")])


def default_targets() -> list[TraceSpec]:
    """Every standard target: both trainer families (DP / the ZeRO
    stages / fsdp / the exchange variants), the async tier, plus both
    serving engines' decode steps."""
    return (adag_targets() + lm_targets() + serving_targets()
            + async_targets())


__all__ = ["ZERO_PARITY_TARGETS", "ZERO1_PARITY_PAIRS",
           "adag_targets", "lm_targets", "serving_targets",
           "async_targets", "default_targets"]
